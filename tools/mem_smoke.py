"""`make mem-smoke`: the allocation/copy-count regression gate on the
config-2 scan path — ROADMAP item 2's acceptance criteria as a running
gate instead of prose.

Builds a deterministic two-SST storage tree (fixed seed, one segment,
two overlapping writes so the merge fold runs) and scans it with the
config-2 shape — tsid InSet + value predicate (ROOFLINE §4) — under a
memtrace ledger, twice:

- COLD: SSTs read + decoded from the store (materialize / host_prep /
  decode events);
- WARM: the decoded-block cache serves the same scan (the cache-hit
  route's counts).

The BUILD itself runs under a third ledger — the INGEST leg: the fixed
bulk-write shape's append/seal/flush_encode counts plus the
flush-encode alloc density (B/row), pinned under a hard ceiling of
r19's plain-encoding 12.7 B/row.

The ledger's event COUNTS (allocs / copies / views / reuses, per stage)
are compared against `benchmarks/mem_baseline.json`, exactly:

- counts ABOVE baseline fail — a new copy or allocation crept into the
  scan path;
- counts BELOW baseline fail too, with a re-pin hint — an improvement
  must be committed into the baseline (`--pin`) so it cannot silently
  regress back. That is the "beat item 2's baseline" mechanic: the
  Arrow-native refactor lands by re-pinning SMALLER numbers.

Counts (not bytes) are the pinned quantity: byte totals scale with the
synthetic row count, event counts are a property of the code path. The
whole build+scan is run twice over two stores and must produce identical
cold counts — a nondeterministic data plane would make any pin a coin
flip, so drift between the two in-process runs fails loudly.

Also measures memtrace's own cost, the ISSUE's <2% acceptance bar:

- track_bytes() micro-cost, ns/event, default vs off;
- end-to-end scan best-of-reps (cache disabled, so the scan does real decode
  work), default mode vs `HORAEDB_MEMTRACE=off`, arms interleaved. The
  tracked target is <2%; the asserted bound is 10% because ~10 ms scans
  on a busy CI box jitter by more than the target (bench.py's copy_tax
  lane measures the same A/B at 500 k rows: -5.5% on the r19 box, i.e.
  inside noise).

Re-pin after an intentional data-plane change:
    python tools/mem_smoke.py --pin
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # script execution: tools/ is sys.path[0]
    sys.path.insert(0, REPO)
BASELINE_PATH = os.path.join(REPO, "benchmarks", "mem_baseline.json")

N_ROWS = 120_000
N_SERIES = 64
INSET = 8  # config-2 selects a small tsid subset


def counts_of(verdict: dict) -> dict:
    """Project a memtrace verdict onto its pinnable event counts —
    drop *_bytes (row-count-scaled) and keep the per-stage event
    counts (code-path-shaped)."""
    return {
        "allocs": verdict["allocs"],
        "copies": verdict["copies"],
        "views": verdict["views"],
        "reuses": verdict["reuses"],
        "per_stage": {
            stage: {
                k: v for k, v in sorted(row.items())
                if not k.endswith("_bytes")
            }
            for stage, row in sorted(verdict["per_stage"].items())
        },
    }


def measure() -> dict:
    import numpy as np
    import pyarrow as pa

    from horaedb_tpu.common import memtrace
    from horaedb_tpu.common.size_ext import ReadableSize
    from horaedb_tpu.objstore import MemStore
    from horaedb_tpu.ops.filter import And, Compare, InSet
    from horaedb_tpu.storage import (
        ObjectBasedStorage,
        ScanRequest,
        StorageConfig,
        TimeRange,
        WriteRequest,
        scanstats,
    )

    SEG = 24 * 3_600_000
    t_lo = (1_700_000_000_000 // SEG + 1) * SEG
    rng = np.random.default_rng(7)
    schema = pa.schema([
        ("tsid", pa.int64()), ("ts", pa.int64()), ("value", pa.float64()),
    ])

    def make_batch(seed_off: int, n: int) -> tuple:
        r = np.random.default_rng(7 + seed_off)
        tsid = np.sort(r.integers(0, N_SERIES, n, dtype=np.int64))
        ts = t_lo + (np.arange(n, dtype=np.int64) * 15_000) % SEG
        vals = r.normal(size=n)
        batch = pa.RecordBatch.from_pydict(
            {"tsid": tsid, "ts": ts, "value": vals}, schema=schema,
        )
        return batch, TimeRange(int(ts.min()), int(ts.max()) + 1)

    pred = And(
        InSet("tsid", tuple(int(s) for s in rng.choice(
            N_SERIES, INSET, replace=False))),
        Compare("value", "gt", 0.0),
    )

    async def build(cfg: StorageConfig):
        eng = await ObjectBasedStorage.try_new(
            "mem_smoke", MemStore(), schema, num_primary_keys=2,
            segment_duration_ms=SEG, config=cfg,
            enable_compaction_scheduler=False,
            start_background_merger=False,
        )
        # two overlapping writes -> two SSTs -> the scan pays the
        # merge-tree fold, not just a single-file read
        for half in (0, 1):
            batch, rng_t = make_batch(half, N_ROWS // 2)
            await eng.write(WriteRequest(batch, rng_t))
        return eng

    async def scan(eng) -> int:
        rows = 0
        req = ScanRequest(range=TimeRange(0, 2**62), predicate=pred)
        async for b in eng.scan(req):
            rows += b.num_rows
        return rows

    def pinned_legs(cfg: StorageConfig) -> dict:
        # the INGEST leg rides the build: the fixed bulk-write shape's
        # append/seal/flush_encode counts and the encode alloc bytes
        # (the ingest-path half of the zero-copy spine's pin)
        with scanstats.scan_stats() as st:
            eng = asyncio.run(build(cfg))
        ingest = memtrace.verdict(st.mem)
        try:
            with scanstats.scan_stats() as st:
                rows_cold = asyncio.run(scan(eng))
            cold = memtrace.verdict(st.mem)
            with scanstats.scan_stats() as st:
                rows_warm = asyncio.run(scan(eng))
            warm = memtrace.verdict(st.mem)
        finally:
            asyncio.run(eng.close())
        return {
            "rows": rows_cold, "rows_warm": rows_warm,
            "cold": cold, "warm": warm, "ingest": ingest,
        }

    prior = memtrace.mode()
    memtrace.configure("")
    try:
        run_a = pinned_legs(StorageConfig())
        run_b = pinned_legs(StorageConfig())

        # -- memtrace cost, micro: ns per tracked event -------------------
        def track_ns(n: int) -> float:
            with memtrace.mem_trace():
                t0 = time.perf_counter()
                for _ in range(n):
                    memtrace.track_bytes(1024, "parse", "alloc")
                return (time.perf_counter() - t0) / n * 1e9

        micro_on = track_ns(200_000)
        memtrace.configure("off")
        micro_off = track_ns(200_000)
        memtrace.configure("")

        # -- memtrace cost, end to end: scan best-of-reps, default vs off -
        # cache OFF so every rep pays decode + host_prep (real work, ~ms
        # scale); arms interleaved so box drift hits both equally
        eng = asyncio.run(build(StorageConfig(scan_cache=ReadableSize(0))))
        try:
            def one_scan() -> float:
                t0 = time.perf_counter()
                with scanstats.scan_stats():
                    asyncio.run(scan(eng))
                return time.perf_counter() - t0

            one_scan()  # warm default-mode path
            memtrace.configure("off")
            one_scan()  # warm off-mode path
            on_times, off_times = [], []
            for _ in range(9):
                memtrace.configure("")
                on_times.append(one_scan())
                memtrace.configure("off")
                off_times.append(one_scan())
            # min-of-interleaved: the best rep of each arm is the code's
            # actual cost — medians absorb whatever else the CI box was
            # doing during the window, min does not
            on_best = min(on_times)
            off_best = min(off_times)
        finally:
            memtrace.configure("")
            asyncio.run(eng.close())
    finally:
        memtrace.configure(prior)

    return {
        "run_a": run_a, "run_b": run_b,
        "micro_ns_on": round(micro_on, 1),
        "micro_ns_off": round(micro_off, 1),
        "scan_on_s": round(on_best, 5),
        "scan_off_s": round(off_best, 5),
        "overhead_pct": round(
            (on_best - off_best) / max(off_best, 1e-9) * 100, 2),
    }


def main() -> int:
    pin = "--pin" in sys.argv[1:]
    t0 = time.perf_counter()
    m = measure()
    failures: list[str] = []

    def check(cond: bool, msg: str) -> None:
        if not cond:
            failures.append(msg)

    from horaedb_tpu.common import memtrace

    a, b = m["run_a"], m["run_b"]
    check(a["rows"] > 0, "config-2 scan returned zero rows")
    check(a["rows"] == a["rows_warm"],
          f"warm scan row drift: {a['rows']} vs {a['rows_warm']}")
    for leg in ("cold", "warm", "ingest"):
        check(set(a[leg]) == set(memtrace.VERDICT_KEYS),
              f"{leg} verdict schema drift: {sorted(a[leg])}")
        check(counts_of(a[leg]) == counts_of(b[leg]),
              f"{leg} scan counts are nondeterministic across two "
              f"identical builds — pinning is impossible:\n"
              f"  a={counts_of(a[leg])}\n  b={counts_of(b[leg])}")
    enc_bytes = int(
        a["ingest"]["per_stage"].get("flush_encode", {})
        .get("alloc_bytes", 0))
    measured = {
        "shape": {
            "n_rows": N_ROWS, "n_series": N_SERIES, "inset": INSET,
            "ssts": 2, "predicate": "tsid InSet + value>0 (config-2)",
        },
        "cold": counts_of(a["cold"]),
        "warm": counts_of(a["warm"]),
        "ingest": {
            "counts": counts_of(a["ingest"]),
            "flush_encode_alloc_b_per_row": round(enc_bytes / N_ROWS, 2),
        },
    }

    if pin and not failures:
        with open(BASELINE_PATH, "w", encoding="utf-8") as f:
            json.dump(measured, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"mem-smoke: pinned baseline -> {BASELINE_PATH}")
        print(json.dumps(measured["cold"]))
        return 0

    if not os.path.exists(BASELINE_PATH):
        failures.append(
            f"no committed baseline at {BASELINE_PATH} — run "
            f"`python tools/mem_smoke.py --pin` and commit the file")
        baseline = {}
    else:
        baseline = json.load(open(BASELINE_PATH, encoding="utf-8"))
    for leg in ("cold", "warm"):
        want, got = baseline.get(leg), measured[leg]
        if want is None:
            check(False, f"baseline missing the {leg} leg")
            continue
        if got == want:
            continue
        worse = (got["allocs"] > want["allocs"]
                 or got["copies"] > want["copies"])
        verdict_word = ("REGRESSION" if worse else
                        "improvement — re-pin with "
                        "`python tools/mem_smoke.py --pin`")
        check(False,
              f"{leg} scan counts drifted off the pinned baseline "
              f"({verdict_word}):\n"
              f"  pinned:   {json.dumps(want, sort_keys=True)}\n"
              f"  measured: {json.dumps(got, sort_keys=True)}")

    # ingest leg: event counts pin exactly (both directions, like the
    # scan legs); the flush-encode alloc density pins with a small
    # tolerance (encoder version skew) under a HARD ceiling — r19's
    # plain-encoding 12.7 B/row is the number the zero-copy spine +
    # type-driven column encodings must stay strictly below
    want_ing = baseline.get("ingest")
    got_ing = measured["ingest"]
    if want_ing is None and baseline:
        check(False, "baseline missing the ingest leg — re-pin with "
                     "`python tools/mem_smoke.py --pin`")
    elif want_ing is not None:
        if got_ing["counts"] != want_ing["counts"]:
            worse = (got_ing["counts"]["allocs"]
                     > want_ing["counts"]["allocs"]
                     or got_ing["counts"]["copies"]
                     > want_ing["counts"]["copies"])
            verdict_word = ("REGRESSION" if worse else
                            "improvement — re-pin with "
                            "`python tools/mem_smoke.py --pin`")
            check(False,
                  f"ingest counts drifted off the pinned baseline "
                  f"({verdict_word}):\n"
                  f"  pinned:   "
                  f"{json.dumps(want_ing['counts'], sort_keys=True)}\n"
                  f"  measured: "
                  f"{json.dumps(got_ing['counts'], sort_keys=True)}")
        drift = abs(got_ing["flush_encode_alloc_b_per_row"]
                    - want_ing["flush_encode_alloc_b_per_row"])
        check(drift <= 0.3,
              f"flush_encode alloc density drifted "
              f"{got_ing['flush_encode_alloc_b_per_row']} B/row vs pinned "
              f"{want_ing['flush_encode_alloc_b_per_row']} (tol 0.3)")
    check(got_ing["flush_encode_alloc_b_per_row"] < 12.7,
          f"flush_encode allocs {got_ing['flush_encode_alloc_b_per_row']} "
          f"B/row — at or above the r19 plain-encoding 12.7 B/row bar")

    # memtrace's own cost: the micro bound is tight (a dict upsert),
    # the e2e bound is the CI-safe envelope around the <2% target
    check(m["micro_ns_on"] < 5_000,
          f"track_bytes costs {m['micro_ns_on']} ns/event (budget 5 µs)")
    check(m["micro_ns_off"] < 500,
          f"memtrace-off track_bytes not near-free: "
          f"{m['micro_ns_off']} ns/event (budget 500 ns)")
    check(m["overhead_pct"] < 10.0,
          f"memtrace default-mode scan overhead {m['overhead_pct']}% "
          f"(target <2%, CI bound 10%): on={m['scan_on_s']}s "
          f"off={m['scan_off_s']}s")

    elapsed = time.perf_counter() - t0
    check(elapsed < 120, f"mem-smoke took {elapsed:.0f}s (budget 120s)")
    if failures:
        for f in failures:
            print(f"mem-smoke: FAIL {f}")
        return 1
    print(
        f"mem-smoke: OK in {elapsed:.1f}s — cold "
        f"allocs={measured['cold']['allocs']} "
        f"copies={measured['cold']['copies']} "
        f"views={measured['cold']['views']}, warm "
        f"copies={measured['warm']['copies']}; ingest "
        f"flush_encode "
        f"{measured['ingest']['flush_encode_alloc_b_per_row']} B/row; "
        f"track "
        f"{m['micro_ns_on']:.0f} ns/event on / "
        f"{m['micro_ns_off']:.0f} ns off; scan overhead "
        f"{m['overhead_pct']}% (target <2%)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
