"""JAX-aware static analysis gate (`make jaxlint`, folded into `make lint`).

tools/lint.py covers generic Python defects; this pass enforces the
TPU-native invariants the reference enforces with `clippy -D warnings`:
the engine's scan/merge/aggregate kernels are only "as fast as the
hardware allows" (ROADMAP north star) while they stay on-device, and a
single silent host sync or retrace in a hot path blows the decode-
throughput budget without failing any test. Stdlib `ast` only.

Rules (see docs/static-analysis.md for rationale and examples):

  J000  malformed suppression (missing reason — every suppression must
        say WHY the invariant is waived)
  J001  host-sync call on a hot path: `.item()`, `.tolist()`,
        `float()/int()/bool()` on array expressions, `np.asarray`/
        `np.array`, `jax.device_get`, `.block_until_ready()` inside a
        jit-traced function (decorated or wrapped with `jax.jit`/`pjit`/
        `shard_map`), plus the unambiguous device syncs (`.item()`,
        `.block_until_ready()`, `jax.device_get`) anywhere in the
        allowlisted hot modules (HOT_MODULES below)
  J002  retrace / trace-staleness hazard inside jit-traced code:
        trace-time-frozen calls (`time.time()`, `datetime.now()`,
        `np.random.*`, `random.*`), `print()` and f-strings (run at
        trace time only / concretize tracers), and call sites passing
        untraceable literals (str/bytes/set) to a function jit-wrapped
        WITHOUT static_argnums/static_argnames
  J003  dtype drift: a bare float literal flowing into `jnp.array`/
        `jnp.full` without an explicit dtype (weak-type promotion makes
        the result dtype depend on the surrounding expression — on TPU
        that silently doubles lane width or truncates to f32)
  J004  lock discipline: a class that owns a `*lock` attribute
        (threading/asyncio Lock/RLock) but mutates `self._*` state in a
        PUBLIC method outside any `with self._lock:` block — the
        storage/fence/compaction concurrency surface
  J005  host timer/span context manager inside a jit-traced function:
        `scanstats.stage(...)`, `scanstats.scan_stats(...)`, and
        tracing's `span`/`trace`/`start_trace` opened in a jit body time
        TRACE time, not device execution (kernels dispatch
        asynchronously and the body runs once at trace time) — a
        J001-adjacent lie; time at the kernel call boundary outside jit
  J006  ad-hoc aggregation lane outside the registry: host ufunc
        scatter/segment calls (`np.add.at`, `np.<ufunc>.reduceat`)
        inside a jit-traced body (they concretize tracers AND bypass
        the calibrated dispatcher), and one-hot materializations
        (`jax.nn.one_hot` above 64 classes, or an `==` against a
        rank-3+ `broadcasted_iota`) in engine code outside
        ops/blockagg.py / ops/agg_registry.py — every segment-reduction
        strategy must register in ops/agg_registry.py so the
        measured-winner dispatch stays complete
  J007  naked `jax.jit`/`jax.pjit` (or `from jax import jit`) in the
        hot modules (ops/, parallel/, promql/): an uninstrumented jit
        wrapper silently bypasses the compile telemetry, kernel catalog,
        and EXPLAIN compile/steady split that common/xprof.py feeds —
        route through `xprof.xjit` instead (same signature, jit kwargs
        pass through)
  J008  blocking flush work reachable from the append hot path
        (ingest/, engine/ outside engine/flush_executor.py): direct
        parquet-encode calls (`pq.ParquetWriter`/`pq.write_table`) and
        direct object-store puts (`.put`/`.put_stream`/
        `.put_if_absent`) — the overlapped ingest->flush pipeline only
        holds its measured 3x with-flush throughput while flush work
        runs on the flush executor through the storage layer; control-
        plane writes (descriptors, sidecars) suppress with the reason
  J010  ad-hoc tombstone/retention row filtering on the scan path:
        touching `Visibility.tombstones` / `.retention_floor_ms` outside
        storage/visibility.py (the shared mask helper) or
        storage/manifest/ (the record store) — every scan route, the
        downsample pushdown, AND compaction must subtract the same rows
        through apply_visibility, or deletes "mostly work" (one reader
        filters, another resurrects). Harness/test fixtures that
        introspect the records suppress with the reason
  J011  query entry point bypassing the admission scheduler: a call of
        `<...>.engine.query(...)` / `.query_exemplars(...)` in server
        code outside server/admission.py skips the bounded scheduler —
        no concurrency cap, no queue/stall backpressure, no end-to-end
        deadline, no per-tenant fairness, no shed metrics; route through
        admission.run_query / run_query_exemplars (or hold an admission
        slot and suppress with the reason)
  J012  ad-hoc decode of an encoded SST lane outside the sanctioned
        funnel (storage/encoding.py host codecs, ops/decode.py device
        kernels, storage/read.py's encoded reader): calling the funnel's
        decode primitives (`decode_lane`/`decode_blob`/
        `decode_page_device`/`unpack_bits`/`unzigzag`) elsewhere, or
        running a decode-shaped op (`cumsum`, `unpackbits`,
        `associative_scan`, `.accumulate`) over an encoded buffer (an
        argument named like one: `*_enc`, `enc_*`, `*encoded*`,
        `payload`) — a second decode path diverges from the funnel's
        bit-exactness contract and dodges the calibrated host/device
        dispatcher; harnesses that measure the funnel itself suppress
        with the reason
  J013  serving-tier funnel breach: the result cache and rollup
        artifacts are read at ONE planner choke point (engine/data.py's
        query methods, plus the serving/rollup modules themselves) and
        mutated through ONE invalidation funnel (the storage write
        commit, the compaction commit, the tombstone path, and the
        reader's eviction hooks). Calling the read primitives
        (`serving_get`/`serving_single_flight`/`plan_rollups`/
        `read_rollup`/`resident_block`) elsewhere creates a second
        lookup path that can serve stale results after the funnel
        invalidated; calling the mutation primitives (`serving_put`/
        `serving_invalidate`/`note_fetch`/`evict_sst`/`evict_rollup`)
        elsewhere lets cache state change without the commit that
        justifies it. Harness/test introspection suppresses with the
        reason
  J014  invalidation-funnel subscription outside the audited consumer
        set: `serving_subscribe`/`serving_unsubscribe`
        (serving/cache.py) register synchronous callbacks inside every
        mutation commit; the only sanctioned consumers are the cache
        itself (serving/) and the rule evaluator (horaedb_tpu/rules,
        whose dirty-set exactness is chaos-tested). A third subscriber
        is a second standing-query engine growing outside the audited
        one — consume the rule engine's dirty sets instead, or suppress
        with the reason
  J016  ad-hoc stacking/padding of query result lanes outside the query
        batcher (server/batching.py) and the sanctioned stacked kernels
        (ops/aggregate.py): a stack/pad-shaped call (`stack`/`vstack`/
        `hstack`/`dstack`/`column_stack`/`pad`) whose arguments name a
        batched query lane (`stacked_*`, `padded_*`, `batch_*`, `*_grids`,
        `*_lanes`, ...) builds a second stacked-execution path — one that
        dodges the batcher's power-of-two shape classes (retraces escape
        the compiled-shape sharing), its pad-waste accounting
        (horaedb_batch_pad_waste_ratio lies), and its bit-exact demux
        contract. Route through the batcher, or suppress with the reason
        for harnesses measuring the stacked lane itself
  J015  ad-hoc per-tenant accounting outside the metering funnel
        (horaedb_tpu/telemetry/): registering a `horaedb_tenant_*`
        metric family, a family with a `tenant` labelname, or a legacy
        string-API call embedding a `tenant="..."` label anywhere else
        forks the usage ledger — /metrics, /api/v1/usage, and any future
        billing export would disagree about what a tenant consumed.
        Account through telemetry.metering.GLOBAL_METER.account(...), or
        suppress with the reason
  J017  cluster-funnel breach (horaedb_tpu/cluster), two prongs:
        (1) manifest snapshot VIEWS (`read_snapshot`/`read_folded_view`)
        consumed outside the manifest package and the replica funnel
        (cluster/replica.py drives them via read-only opens) — a second
        view consumer is a second replication path whose staleness
        token, swap invalidation, and watch backoff are untested;
        (2) assignment-record mutation (a store put/delete whose
        arguments name `cluster/assignment` / `assignment_path`)
        outside cluster/assignment.py's fenced CAS API — an unversioned
        write forks the meta plane and can silently reroute writes to a
        deposed owner. Suppress with the reason for harnesses seeding
        records on purpose
  J009  naked object-store construction outside objstore/: a concrete
        store (`MemStore`/`LocalStore`/`S3LikeStore`) built in engine
        code without being handed straight to a `ResilientStore(...)`
        gives every component that receives it single-naked-attempt
        semantics — no retry/backoff, no per-op deadline, no circuit
        breaker, no horaedb_objstore_* attribution. The store boundary
        is where resilience is decided, so the lint enforces it at the
        construction site; harness/test fixtures that WANT raw-store
        semantics suppress with the reason

Suppressions: `# jaxlint: disable=J001 <reason>` on the finding's line
or the line immediately above. The reason is mandatory (J000 otherwise);
multiple codes separate with commas. tools/lint.py's `# noqa` does NOT
suppress jaxlint findings — the two gates are independent.

Precision choices (documented, deliberate):
- `np.asarray`/`float()` OUTSIDE jit in hot modules are not flagged: on
  the host side of a kernel boundary they are routinely numpy->numpy
  and flagging them would bury the signal in suppressions. Inside a
  traced function they are always wrong and always flagged.
- dict/list literals at jit call sites are legal pytrees with a fixed
  structure per call site and are not flagged; str/bytes/set cannot be
  traced at all and are.
- J004 only inspects direct `self._x` assignments/augments/deletes and
  known mutator-method calls (`.append`, `.pop`, ...); aliasing through
  a local name is out of scope for a stdlib pass.

Zero unsuppressed findings is the bar. Exit code = number of findings
(capped 125), matching tools/lint.py.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

# one file-discovery policy for BOTH gates (same roots semantics, same
# __pycache__/pb codegen exclusions) — a scope change in lint.py must
# never silently diverge this gate's file set
try:
    from lint import iter_py_files  # script execution: sibling on sys.path
except ImportError:  # package-style import (tools.jaxlint)
    from tools.lint import iter_py_files

# Modules whose host-side code is ALSO held to the no-silent-sync bar
# (the columnar scan/merge/aggregate surface PAPERS.md budgets):
HOT_MODULES = (
    "horaedb_tpu/ops/",
    "horaedb_tpu/parallel/",
    "horaedb_tpu/storage/read.py",
)
# Engine-code scope for the dtype rule (J003):
DTYPE_MODULES = (
    "horaedb_tpu/ops/",
    "horaedb_tpu/parallel/",
    "horaedb_tpu/engine/",
    "horaedb_tpu/storage/",
)

JIT_WRAPPERS = {
    "jit", "jax.jit", "pjit", "jax.pjit",
    "jax.experimental.pjit.pjit",
    "shard_map", "jax.experimental.shard_map.shard_map",
    # the instrumented wrapper (common/xprof.py) IS a jit wrapper: bodies
    # it traces stay under the J001/J002/J005/J006 in-jit rules
    "xjit", "xprof.xjit", "common.xprof.xjit",
}
PARTIAL_NAMES = {"partial", "functools.partial"}

# J007: jit spellings that bypass xprof's compile telemetry. Scope below
# (J007_MODULES); `shard_map` alone is fine — the telemetry hook is the
# OUTER jit wrapper, which must be xjit.
NAKED_JIT = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
J007_MODULES = (
    "horaedb_tpu/ops/",
    "horaedb_tpu/parallel/",
    "horaedb_tpu/promql/",
)

# J008: the append hot path (ingest decode + the engine write layers)
# must not reach blocking flush work directly — parquet encodes and
# object-store puts belong behind the flush executor
# (engine/flush_executor.py) and the storage layer it drives.
J008_MODULES = (
    "horaedb_tpu/ingest/",
    "horaedb_tpu/engine/",
)
J008_EXEMPT = ("horaedb_tpu/engine/flush_executor.py",)

# J009: the resilience boundary (objstore/resilient.py). Concrete store
# constructors outside objstore/ must be immediate arguments of a
# ResilientStore(...) call. tests/ and benchmarks/tools harnesses are out
# of scope — they deliberately build raw stores to inject faults.
J009_MODULES = ("horaedb_tpu/",)
J009_EXEMPT = ("horaedb_tpu/objstore/",)

# J011: the query-admission boundary (server/admission.py). Server-layer
# code must reach the engine's query surface only through the admission
# helpers; the owner-name heuristic (`engine`/`_engine` receiver) matches
# this codebase's handler idiom (`state.engine.query(...)`) without
# flagging unrelated `.query()` methods on other objects.
J011_MODULES = ("horaedb_tpu/server/",)
J011_EXEMPT = ("horaedb_tpu/server/admission.py",)
QUERY_ENTRY_ATTRS = {"query", "query_exemplars"}
ENGINE_RECEIVERS = {"engine", "_engine"}

# J010: tombstone/retention filtering is ONE shared helper
# (storage/visibility.py, funneled through ParquetReader.read_sst); any
# other engine code touching the visibility state's row-filtering fields
# is an ad-hoc reader filter waiting to diverge. The manifest package is
# the record STORE (load/persist/GC) and is exempt.
J010_MODULES = ("horaedb_tpu/",)
J010_EXEMPT = (
    "horaedb_tpu/storage/visibility.py",
    "horaedb_tpu/storage/manifest/",
)
VISIBILITY_FIELDS = {"tombstones", "retention_floor_ms"}

# J012: the encoded-lane decode funnel (storage/encoding.py host codecs,
# ops/decode.py device kernels) and the one reader that drives it
# (storage/read.py's encoded path). Everything else in engine code must
# not decode encoded buffers by hand.
J012_MODULES = ("horaedb_tpu/",)
J012_EXEMPT = (
    "horaedb_tpu/storage/encoding.py",
    "horaedb_tpu/ops/decode.py",
    "horaedb_tpu/storage/read.py",
)
# the funnel's own decode entry points (dotted-name tail match)
DECODE_FUNNEL_FUNCS = {
    "decode_lane", "decode_blob", "decode_page_device", "unpack_bits",
    "unzigzag",
}
# decode-shaped primitives that, applied to an encoded buffer, are an
# ad-hoc decode path (tail match; `.accumulate` covers ufunc scans like
# np.bitwise_xor.accumulate)
DECODE_SHAPED_TAILS = {"cumsum", "unpackbits", "associative_scan", "accumulate"}
_ENC_NAME_RE = re.compile(r"(^|_)enc(oded)?(_|$)|encoded|^payload$")

# J013: the serving-tier funnel (horaedb_tpu/serving + storage/rollup.py).
# READ side: cache lookups / rollup planning / residency probes belong at
# the planner choke point (engine/data.py) and in the tier's own modules
# (storage/read.py hosts the residency hooks). WRITE side: cache/residency
# mutation belongs to the invalidation funnel — the storage write commit,
# the compaction commit, the tombstone path (all in storage/storage.py /
# compaction/executor.py), the manifest's record store, and the reader's
# eviction hooks.
J013_MODULES = ("horaedb_tpu/",)
J013_READ_EXEMPT = (
    "horaedb_tpu/serving/",
    "horaedb_tpu/engine/data.py",
    "horaedb_tpu/storage/rollup.py",
    "horaedb_tpu/storage/read.py",
)
J013_WRITE_EXEMPT = (
    "horaedb_tpu/serving/",
    "horaedb_tpu/storage/storage.py",
    "horaedb_tpu/storage/compaction/executor.py",
    "horaedb_tpu/storage/manifest/",
    "horaedb_tpu/storage/rollup.py",
    "horaedb_tpu/storage/read.py",
    # the replica's snapshot swap IS its flush/delete commit — the swap
    # routes through serving_invalidate with the mutation's time range
    "horaedb_tpu/cluster/replica.py",
)
SERVING_READ_FUNCS = {
    "serving_get", "serving_single_flight", "plan_rollups", "read_rollup",
    "resident_block",
}
SERVING_WRITE_FUNCS = {
    "serving_put", "serving_invalidate", "note_fetch", "evict_sst",
    "evict_rollup",
}

# J014: the invalidation funnel's CONSUMER set. serving_subscribe /
# serving_unsubscribe (serving/cache.py) hand out a synchronous callback
# inside every mutation commit; the audited consumers are the cache
# itself (serving/) and the rule evaluator (rules/ — the streaming rule
# engine's dirty sets). Anything else subscribing is a second standing-
# query engine growing outside the one whose exactness is tested.
J014_MODULES = ("horaedb_tpu/",)
J014_EXEMPT = (
    "horaedb_tpu/serving/",
    "horaedb_tpu/rules/",
)
FUNNEL_SUBSCRIBE_FUNCS = {"serving_subscribe", "serving_unsubscribe"}

# J015: the per-tenant usage funnel (telemetry/metering.py). Tenant
# accounting registered anywhere else forks the ledger.
J015_MODULES = ("horaedb_tpu/",)
J015_EXEMPT = ("horaedb_tpu/telemetry/",)

# J016: the stacked-execution funnel (server/batching.py pads/stacks the
# coalesced query lanes; ops/aggregate.py hosts the sanctioned stacked
# kernels). Stack/pad-shaped calls over batched-query-lane names anywhere
# else are a second stacking path (same heuristic class as J012's
# encoded-buffer prong: primitive tail + argument naming idiom).
J016_MODULES = ("horaedb_tpu/",)
J016_EXEMPT = (
    "horaedb_tpu/server/batching.py",
    "horaedb_tpu/ops/aggregate.py",
)
STACK_SHAPED_TAILS = {
    "stack", "vstack", "hstack", "dstack", "column_stack", "pad",
}
_BATCH_LANE_RE = re.compile(
    r"(^|_)(stacked?|padded|batch(ed)?|grids?|lanes?)(_|$)"
)

# J017: the cluster funnel (horaedb_tpu/cluster). Prong 1: manifest
# snapshot views belong to the manifest package + the replica funnel.
# Prong 2: assignment records mutate only through assignment.py's
# fenced CAS (put_if_absent-arbitrated versions).
J017_MODULES = ("horaedb_tpu/",)
J017_VIEW_EXEMPT = (
    "horaedb_tpu/storage/manifest/",
    "horaedb_tpu/cluster/replica.py",
)
J017_ASSIGN_EXEMPT = ("horaedb_tpu/cluster/assignment.py",)
MANIFEST_VIEW_FUNCS = {"read_snapshot", "read_folded_view"}
STORE_MUTATION_TAILS = {"put", "put_if_absent", "put_stream", "delete"}
_ASSIGNMENT_NAME_RE = re.compile(
    r"cluster/assignment|assignment_path|assignment_dir|ASSIGNMENT_DIR"
)
METRIC_REGISTER_VERBS = {"counter", "gauge", "histogram"}
TENANT_FAMILY_PREFIX = "horaedb_tenant_"
RAW_STORE_CTORS = {"MemStore", "LocalStore", "S3LikeStore"}
STORE_BOUNDARY_WRAPPERS = {"ResilientStore", "ChaosStore"}
PARQUET_ENCODE_CALLS = {
    "pq.ParquetWriter", "pq.write_table", "pq.write_to_dataset",
    "pyarrow.parquet.ParquetWriter", "pyarrow.parquet.write_table",
    "parquet.ParquetWriter", "parquet.write_table",
}
OBJSTORE_PUT_VERBS = {"put", "put_stream", "put_if_absent"}

# device -> host syncs, unambiguous even outside jit
SYNC_METHODS = {"item", "block_until_ready"}
SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
# additionally wrong inside a traced function
TRACE_SYNC_METHODS = SYNC_METHODS | {"tolist"}
TRACE_SYNC_CALLS = SYNC_CALLS | {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.block_until_ready",
}
CONCRETIZING_BUILTINS = {"float", "int", "bool"}

# trace-time-frozen calls: evaluated ONCE at trace time, silently stale
# on every cached-trace call after that
FROZEN_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.perf_counter",
    "time.process_time", "datetime.now", "datetime.datetime.now",
    "datetime.utcnow", "datetime.datetime.utcnow",
}
FROZEN_PREFIXES = ("np.random.", "numpy.random.", "random.")

JNP_DTYPE_CTORS = {
    "jnp.array": 1, "jnp.full": 2,          # positional index of dtype
    "jax.numpy.array": 1, "jax.numpy.full": 2,
}

# Host-wall-clock timer / span context managers (J005): legitimate on the
# host side of a kernel boundary, a lie inside a traced body. Bare names
# cover `from ... import stage` style; dotted forms match only when the
# module component is literally `scanstats`/`tracing` — an alias like
# `import ... as ss; ss.stage(...)` evades the rule (the cost of not
# flagging every unrelated `.trace()`/`.stage()` method, e.g. the linalg
# `jnp.trace`). The tree imports these modules by their real names.
TIMER_FUNCS = {"stage", "scan_stats", "span", "start_trace"}
TIMER_MODULES = {"scanstats", "tracing"}


def _is_timer_cm(fd: str | None) -> bool:
    if fd is None:
        return False
    parts = fd.split(".")
    tail = parts[-1]
    if tail not in TIMER_FUNCS and not (tail == "trace" and len(parts) > 1):
        return False
    if len(parts) == 1:
        return True
    return parts[-2] in TIMER_MODULES or parts[0] in TIMER_MODULES


# J006 scope: modules allowed to hold aggregation lanes (the registry and
# its execution module); everything else in engine code must go through
# them. Host-ufunc prong matches (np|numpy).<ufunc>.(at|reduceat).
AGG_LANE_MODULES = (
    "horaedb_tpu/ops/agg_registry.py",
    "horaedb_tpu/ops/blockagg.py",
)
ONE_HOT_CALLS = {"jax.nn.one_hot", "nn.one_hot"}
ONE_HOT_CLASS_THRESHOLD = 64
IOTA_CALLS = {"jax.lax.broadcasted_iota", "lax.broadcasted_iota"}


def _is_host_ufunc_lane(fd: str | None) -> bool:
    if fd is None:
        return False
    parts = fd.split(".")
    return (
        len(parts) == 3
        and parts[0] in ("np", "numpy")
        and parts[-1] in ("at", "reduceat")
    )


LOCK_FACTORIES = ("Lock", "RLock", "Semaphore", "Condition")
MUTATORS = {
    "append", "appendleft", "add", "update", "pop", "popitem", "clear",
    "extend", "remove", "discard", "insert", "setdefault",
}

SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=((?:J\d{3})(?:\s*,\s*J\d{3})*)(?:\s+(.+))?"
)


def dotted(node: ast.AST) -> str | None:
    """`jax.numpy.full` -> "jax.numpy.full"; None for non-name chains."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.expr) -> bool:
    """True for `jax.jit`, `partial(jax.jit, ...)`, `shard_map`, and
    calls of those (e.g. the decorator `@partial(jax.jit, ...)`)."""
    d = dotted(node)
    if d in JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        fd = dotted(node.func)
        if fd in JIT_WRAPPERS:
            return True
        if fd in PARTIAL_NAMES and node.args and _is_jit_expr(node.args[0]):
            return True
    return False


def _jit_call_static(call: ast.Call) -> bool:
    """Does this jit/partial(jit) call carry static_argnums/argnames?"""
    kws = {kw.arg for kw in call.keywords}
    if {"static_argnums", "static_argnames"} & kws:
        return True
    # partial(jax.jit, static_argnames=...) nests one level
    if dotted(call.func) in PARTIAL_NAMES and call.args:
        inner = call.args[0]
        if isinstance(inner, ast.Call):
            return _jit_call_static(inner)
    return False


class Suppressions:
    """Per-file `# jaxlint: disable=...` map (same line or line above)."""

    def __init__(self, lines: list[str]):
        self.by_line: dict[int, tuple[set[str], str]] = {}
        self.malformed: list[int] = []
        for i, line in enumerate(lines, 1):
            m = SUPPRESS_RE.search(line)
            if not m:
                continue
            codes = {c.strip() for c in m.group(1).split(",")}
            reason = (m.group(2) or "").strip()
            if not reason:
                self.malformed.append(i)
            self.by_line[i] = (codes, reason)

    def covers(self, lineno: int, code: str) -> bool:
        for ln in (lineno, lineno - 1):
            ent = self.by_line.get(ln)
            if ent and code in ent[0] and ent[1]:
                return True
        return False


class JitIndex(ast.NodeVisitor):
    """First pass: which defs/lambdas run under a jit trace, and which
    NAMES are bound to bare (no-static) jit wrappers — for the J002
    call-site check."""

    def __init__(self) -> None:
        self.jit_defs: set[ast.AST] = set()       # FunctionDef/Lambda nodes
        self.wrapped_names: set[str] = set()       # names passed to jit/shard_map
        self.bare_jit_names: set[str] = set()      # jit-wrapped, no statics
        self._defs_by_name: dict[str, list[ast.AST]] = {}

    def visit_FunctionDef(self, node):  # noqa  (shared handler)
        self._defs_by_name.setdefault(node.name, []).append(node)
        for dec in node.decorator_list:
            if _is_jit_expr(dec):
                self.jit_defs.add(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        fd = dotted(node.func)
        is_wrap = fd in JIT_WRAPPERS or (
            fd in PARTIAL_NAMES and node.args and _is_jit_expr(node.args[0])
        )
        if is_wrap and node.args:
            pos = 1 if fd in PARTIAL_NAMES else 0
            target = node.args[pos] if len(node.args) > pos else None
            if isinstance(target, ast.Lambda):
                self.jit_defs.add(target)
            elif isinstance(target, ast.Name):
                self.wrapped_names.add(target.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # `kernel = jax.jit(fn)` without statics: calls to `kernel` with
        # untraceable literal args are J002 call-site findings
        if (
            isinstance(node.value, ast.Call)
            and dotted(node.value.func) in JIT_WRAPPERS
            and not _jit_call_static(node.value)
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.bare_jit_names.add(t.id)
        self.generic_visit(node)

    def finish(self) -> None:
        # names handed to jit()/shard_map() mark their local defs traced
        for name in self.wrapped_names:
            for d in self._defs_by_name.get(name, []):
                self.jit_defs.add(d)
        # a def decorated @jax.jit with NO statics is also a bare-jit name
        for defs in self._defs_by_name.values():
            for d in defs:
                if d in self.jit_defs and isinstance(
                    d, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    for dec in d.decorator_list:
                        if _is_jit_expr(dec) and not (
                            isinstance(dec, ast.Call) and _jit_call_static(dec)
                        ):
                            self.bare_jit_names.add(d.name)


def _walk_no_nested_defs(body: list[ast.stmt]):
    """Yield nodes of a function body WITHOUT descending into nested
    function/class definitions (those are visited separately, with their
    own jit-context flag)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


class Finding:
    __slots__ = ("lineno", "code", "msg")

    def __init__(self, lineno: int, code: str, msg: str):
        self.lineno, self.code, self.msg = lineno, code, msg


def _check_traced_body(fn, findings: list[Finding]) -> None:
    """J001 + J002 inside one jit-traced function body."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for node in _walk_no_nested_defs(body):
        if isinstance(node, ast.JoinedStr):
            findings.append(Finding(
                node.lineno, "J002",
                "f-string under jit runs at trace time only (and "
                "concretizes tracers); move formatting outside the kernel "
                "or use jax.debug.print",
            ))
            continue
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        if _is_host_ufunc_lane(fd):
            findings.append(Finding(
                node.lineno, "J006",
                f"host ufunc lane `{fd}(...)` inside a jit-traced function "
                "— concretizes tracers AND bypasses the calibrated "
                "aggregation dispatcher; register the strategy in "
                "ops/agg_registry.py and call it outside jit",
            ))
        elif _is_timer_cm(fd):
            findings.append(Finding(
                node.lineno, "J005",
                f"host timer/span `{fd}(...)` inside a jit-traced function "
                "— the block measures trace time, not device execution "
                "(kernels dispatch asynchronously); time at the kernel call "
                "boundary outside jit",
            ))
        elif fd in TRACE_SYNC_CALLS:
            findings.append(Finding(
                node.lineno, "J001",
                f"host sync `{fd}(...)` inside a jit-traced function — "
                "forces a device->host transfer (or trace-time "
                "concretization) on the hot path",
            ))
        elif fd in CONCRETIZING_BUILTINS and node.args and not isinstance(
            node.args[0], ast.Constant
        ):
            findings.append(Finding(
                node.lineno, "J001",
                f"`{fd}()` on a traced value inside jit concretizes the "
                "tracer (ConcretizationTypeError at best, a silent host "
                "sync at worst)",
            ))
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in TRACE_SYNC_METHODS
            and not node.args
        ):
            findings.append(Finding(
                node.lineno, "J001",
                f"host sync `.{node.func.attr}()` inside a jit-traced "
                "function — forces a device->host transfer on the hot path",
            ))
        elif fd == "print":
            findings.append(Finding(
                node.lineno, "J002",
                "print() under jit runs at trace time only (silent on "
                "cached traces); use jax.debug.print",
            ))
        elif fd in FROZEN_CALLS or (
            fd is not None and fd.startswith(FROZEN_PREFIXES)
        ):
            findings.append(Finding(
                node.lineno, "J002",
                f"`{fd}()` under jit is evaluated once at trace time and "
                "frozen into the compiled graph — every later call reuses "
                "the stale value",
            ))


def _check_host_hot(tree: ast.Module, jit_defs: set, findings: list) -> None:
    """J001 outside jit, hot modules only: unambiguous device syncs."""
    # collect nodes inside traced defs so we don't double-report them
    traced: set[ast.AST] = set()
    for d in jit_defs:
        body = d.body if isinstance(d.body, list) else [d.body]
        for stmt in body:
            traced.update(ast.walk(stmt))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or node in traced:
            continue
        fd = dotted(node.func)
        if fd in SYNC_CALLS:
            findings.append(Finding(
                node.lineno, "J001",
                f"`{fd}(...)` in a hot module — an explicit device->host "
                "sync on the scan/merge path; move it behind the kernel "
                "boundary or suppress with the measured justification",
            ))
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in SYNC_METHODS
            and not node.args
        ):
            findings.append(Finding(
                node.lineno, "J001",
                f"`.{node.func.attr}()` in a hot module — an explicit "
                "device->host sync on the scan/merge path",
            ))


def _check_jit_call_sites(tree, bare_jit_names: set[str], findings) -> None:
    """J002: untraceable literal args to bare-jit callables."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Name)
                and node.func.id in bare_jit_names):
            continue
        exprs = list(node.args) + [kw.value for kw in node.keywords]
        for a in exprs:
            bad = None
            if isinstance(a, ast.Constant) and isinstance(a.value, (str, bytes)):
                bad = f"{type(a.value).__name__} literal"
            elif isinstance(a, ast.Set):
                bad = "set literal"
            if bad:
                findings.append(Finding(
                    node.lineno, "J002",
                    f"{bad} passed to jit-wrapped `{node.func.id}` with no "
                    "static_argnums/static_argnames — untraceable types "
                    "must be static (and each distinct value retraces)",
                ))


def _check_dtype(tree: ast.Module, findings: list[Finding]) -> None:
    """J003: bare float literals into jnp.array/jnp.full without dtype."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        if fd not in JNP_DTYPE_CTORS:
            continue
        dtype_pos = JNP_DTYPE_CTORS[fd]
        if len(node.args) > dtype_pos:
            continue  # positional dtype given
        if any(kw.arg == "dtype" for kw in node.keywords):
            continue
        value_args = node.args[:dtype_pos]
        has_float = any(
            isinstance(sub, ast.Constant) and isinstance(sub.value, float)
            for a in value_args
            for sub in ast.walk(a)
        )
        if has_float:
            findings.append(Finding(
                node.lineno, "J003",
                f"bare float literal into `{fd}` without dtype= — weak-type "
                "promotion decides the lane width (f32 vs f64) from context; "
                "pin it explicitly in engine code",
            ))


def _check_onehot(tree: ast.Module, findings: list[Finding]) -> None:
    """J006 prong 2: one-hot materializations in engine code outside the
    registry modules. Two idioms: `jax.nn.one_hot(x, N)` with N above the
    size threshold (a literal N <= 64 is a small embedding, not an
    aggregation one-hot; a non-literal N is flagged — it can be anything),
    and the `rank == broadcasted_iota(..., rank-3+ shape, ...)` compare
    this codebase's block compaction uses."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fd = dotted(node.func)
            if fd in ONE_HOT_CALLS:
                n_arg = None
                if len(node.args) > 1:
                    n_arg = node.args[1]
                else:
                    for kw in node.keywords:
                        if kw.arg == "num_classes":
                            n_arg = kw.value
                if (
                    isinstance(n_arg, ast.Constant)
                    and isinstance(n_arg.value, int)
                    and n_arg.value <= ONE_HOT_CLASS_THRESHOLD
                ):
                    continue
                findings.append(Finding(
                    node.lineno, "J006",
                    f"`{fd}` materialization above {ONE_HOT_CLASS_THRESHOLD} "
                    "classes outside ops/blockagg.py / ops/agg_registry.py — "
                    "one-hot traffic is the aggregate path's roofline "
                    "(ROOFLINE §1); register the kernel so the calibrated "
                    "dispatcher can measure it",
                ))
        elif isinstance(node, ast.Compare):
            sides = [node.left, *node.comparators]
            for side in sides:
                if not (isinstance(side, ast.Call)
                        and dotted(side.func) in IOTA_CALLS):
                    continue
                shape = side.args[1] if len(side.args) > 1 else None
                if isinstance(shape, (ast.Tuple, ast.List)) \
                        and len(shape.elts) < 3:
                    continue  # rank-2 iota compares are index masks, not
                    # materialized one-hots
                findings.append(Finding(
                    node.lineno, "J006",
                    "one-hot materialization via `== broadcasted_iota` "
                    "(rank-3+ shape) outside ops/blockagg.py / "
                    "ops/agg_registry.py — register the kernel in the "
                    "aggregation registry instead of an ad-hoc lane",
                ))
                break


def _check_naked_jit(tree: ast.Module, findings: list[Finding]) -> None:
    """J007, hot modules only: any use of `jax.jit`/`jax.pjit` — call,
    decorator, or `partial(jax.jit, ...)` (all contain the `jax.jit`
    attribute node this walks for) — plus the import-alias escape hatch
    `from jax import jit`. The instrumented wrapper (common/xprof.xjit)
    is the only sanctioned jit spelling here: a naked jit silently drops
    the kernel out of compile telemetry, /debug/kernels, and EXPLAIN's
    compile/steady split."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            fd = dotted(node)
            if fd in NAKED_JIT:
                findings.append(Finding(
                    node.lineno, "J007",
                    f"naked `{fd}` in a hot module bypasses compile "
                    "telemetry (horaedb_jit_* families, /debug/kernels, "
                    "EXPLAIN compile split); route through "
                    "common/xprof.xjit",
                ))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax" and any(
                a.name in ("jit", "pjit") for a in node.names
            ):
                findings.append(Finding(
                    node.lineno, "J007",
                    "`from jax import jit` in a hot module — importing the "
                    "uninstrumented wrapper invites naked jit call sites; "
                    "use common/xprof.xjit",
                ))


def _check_append_hot_path(tree: ast.Module, findings: list[Finding]) -> None:
    """J008, append-hot modules only: direct parquet-encode calls and
    direct object-store put verbs. The storage layer (`storage.write`)
    is the sanctioned durability path — it runs on the flush executor's
    workers with encode offloaded to the SST pool; a call site here
    would drag that work back onto the append path. Control-plane writes
    (region descriptors, index sidecars) carry reasoned suppressions."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        if fd in PARQUET_ENCODE_CALLS:
            findings.append(Finding(
                node.lineno, "J008",
                f"parquet encode `{fd}(...)` reachable from the append hot "
                "path — flush encode belongs behind the flush executor "
                "(engine/flush_executor.py) via the storage layer",
            ))
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in OBJSTORE_PUT_VERBS
        ):
            findings.append(Finding(
                node.lineno, "J008",
                f"direct object-store `.{node.func.attr}()` reachable from "
                "the append hot path — route durability through the "
                "storage layer / flush executor, or suppress with the "
                "control-plane justification",
            ))


def _check_store_boundary(tree: ast.Module, findings: list[Finding]) -> None:
    """J009: concrete ObjectStore constructors outside objstore/ that are
    not immediate arguments of a ResilientStore(...) (or ChaosStore(...)
    — the chaos harness wraps before resilience does). One pass collects
    the wrapped argument nodes; a second flags naked constructions."""
    wrapped: set[ast.AST] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        if fd and fd.rsplit(".", 1)[-1] in STORE_BOUNDARY_WRAPPERS:
            wrapped.update(node.args)
            wrapped.update(kw.value for kw in node.keywords)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or node in wrapped:
            continue
        fd = dotted(node.func)
        if fd and fd.rsplit(".", 1)[-1] in RAW_STORE_CTORS:
            findings.append(Finding(
                node.lineno, "J009",
                f"concrete object store `{fd}(...)` constructed outside "
                "objstore/ without the ResilientStore boundary — the "
                "receiver gets single-naked-attempt semantics (no retry/"
                "backoff, deadlines, breaker, or horaedb_objstore_* "
                "attribution); wrap it in objstore/resilient.ResilientStore "
                "at the construction site or suppress with the reason",
            ))


def _check_admission_boundary(tree: ast.Module, findings: list[Finding]) -> None:
    """J011: `<...>.engine.query(...)` / `.query_exemplars(...)` in server
    code outside server/admission.py. The receiver must be named
    `engine`/`_engine` (directly or as the last attribute before the
    verb) — the handler idiom this tree uses — so `registry.query(...)`
    on unrelated objects never trips the rule."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in QUERY_ENTRY_ATTRS):
            continue
        owner = f.value
        owner_name = None
        if isinstance(owner, ast.Attribute):
            owner_name = owner.attr
        elif isinstance(owner, ast.Name):
            owner_name = owner.id
        if owner_name in ENGINE_RECEIVERS:
            findings.append(Finding(
                node.lineno, "J011",
                f"direct engine `.{f.attr}(...)` in server code bypasses "
                "the admission scheduler (no concurrency cap, queue/stall "
                "backpressure, end-to-end deadline, tenant fairness, or "
                "shed metrics); route through server/admission.run_query"
                "/run_query_exemplars, or suppress with the reason",
            ))


def _arg_identifiers(node: ast.Call):
    """Every Name/Attribute identifier reachable from a call's arguments."""
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name):
                yield sub.id
            elif isinstance(sub, ast.Attribute):
                yield sub.attr


def _check_decode_funnel(tree: ast.Module, findings: list[Finding]) -> None:
    """J012, two prongs: (1) calls of the funnel's decode primitives
    outside the funnel; (2) decode-shaped ops (cumsum/unpackbits/
    associative_scan/ufunc .accumulate) whose arguments name an encoded
    buffer (`*_enc`, `enc_*`, `*encoded*`, `payload`) — the naming idiom
    of every encoded-buffer variable in this tree, same heuristic class
    as J011's `engine` receiver match."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if tail in DECODE_FUNNEL_FUNCS:
            findings.append(Finding(
                node.lineno, "J012",
                f"`{tail}(...)` called outside the sanctioned decode "
                "funnel (storage/encoding.py / ops/decode.py / the "
                "encoded reader in storage/read.py) — ad-hoc decode paths "
                "diverge from the funnel's bit-exactness contract and "
                "skip the calibrated host/device dispatcher; route "
                "through the reader, or suppress with the reason",
            ))
        elif tail in DECODE_SHAPED_TAILS and any(
            _ENC_NAME_RE.search(name) for name in _arg_identifiers(node)
        ):
            findings.append(Finding(
                node.lineno, "J012",
                f"decode-shaped `{tail}(...)` over an encoded buffer "
                "outside the sanctioned funnel — hand-rolled prefix-sum/"
                "unpack of encoded lanes belongs in storage/encoding.py "
                "(host) or ops/decode.py (device kernels); suppress with "
                "the reason for harnesses measuring the funnel itself",
            ))


def _check_serving_funnel(
    tree: ast.Module, findings: list[Finding],
    check_reads: bool, check_writes: bool,
) -> None:
    """J013: serving-tier read primitives outside the planner choke point,
    or mutation primitives outside the invalidation funnel (dotted-name
    tail match, the J011/J012 heuristic class)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if check_reads and tail in SERVING_READ_FUNCS:
            findings.append(Finding(
                node.lineno, "J013",
                f"serving-tier read `{tail}(...)` outside the planner "
                "choke point (engine/data.py's query methods) — a second "
                "lookup path can serve results the invalidation funnel "
                "already declared stale; route through the choke point, "
                "or suppress with the reason",
            ))
        elif check_writes and tail in SERVING_WRITE_FUNCS:
            findings.append(Finding(
                node.lineno, "J013",
                f"serving-tier mutation `{tail}(...)` outside the "
                "invalidation funnel (storage write commit / compaction "
                "commit / tombstone path / reader eviction hooks) — cache "
                "state must only change with the commit that justifies "
                "it; route through the funnel, or suppress with the "
                "reason",
            ))


def _check_stacking_funnel(tree: ast.Module,
                           findings: list[Finding]) -> None:
    """J016: stack/pad-shaped primitives over query result lanes outside
    the batcher and the sanctioned stacked kernels. A call fires when its
    dotted tail is a stacking/padding primitive AND any argument
    identifier names a batched query lane (`stacked_*`, `padded_*`,
    `batch_*`, `*_grids`, `*_lanes` — the naming idiom of every stacked
    buffer in this tree, the J011/J012 heuristic class)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if tail in STACK_SHAPED_TAILS and any(
            _BATCH_LANE_RE.search(name) for name in _arg_identifiers(node)
        ):
            findings.append(Finding(
                node.lineno, "J016",
                f"stacking/padding `{tail}(...)` over a query result lane "
                "outside the query batcher (server/batching.py) / the "
                "sanctioned stacked kernels (ops/aggregate.py) — a second "
                "stacked-execution path dodges the batcher's power-of-two "
                "shape classes (retraces escape the shared compiled "
                "shapes), its pad-waste accounting, and the bit-exact "
                "demux contract; route through the batcher, or suppress "
                "with the reason for harnesses measuring the stacked "
                "lane itself",
            ))


def _check_cluster_funnel(
    tree: ast.Module, findings: list[Finding],
    check_views: bool, check_assign: bool,
) -> None:
    """J017: manifest-view consumption outside the replica funnel, and
    assignment-record mutation outside the fenced CAS API (dotted-tail +
    argument-naming heuristics, the J012/J016 class)."""
    def _arg_names_and_strings(node: ast.Call):
        for name in _arg_identifiers(node):
            yield name
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    yield sub.value
                elif isinstance(sub, ast.JoinedStr):
                    for v in sub.values:
                        if isinstance(v, ast.Constant) and isinstance(v.value, str):
                            yield v.value

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if check_views and tail in MANIFEST_VIEW_FUNCS:
            findings.append(Finding(
                node.lineno, "J017",
                f"manifest view `{tail}(...)` consumed outside the "
                "manifest package / the cluster replica funnel "
                "(cluster/replica.py) — a second snapshot consumer is a "
                "second replication path with no staleness token, swap "
                "invalidation, or watch backoff; open the storage "
                "read-only (read_only=True) or go through ReplicaEngine, "
                "or suppress with the reason",
            ))
        elif check_assign and tail in STORE_MUTATION_TAILS and any(
            _ASSIGNMENT_NAME_RE.search(s)
            for s in _arg_names_and_strings(node)
        ):
            findings.append(Finding(
                node.lineno, "J017",
                f"assignment-record mutation `{tail}(...)` outside the "
                "fenced CAS API (cluster/assignment.py) — an unversioned "
                "write forks the meta plane and can reroute writes to a "
                "deposed owner; use propose_assignment/claim_regions/"
                "takeover_region, or suppress with the reason",
            ))


def _check_funnel_subscribers(tree: ast.Module,
                              findings: list[Finding]) -> None:
    """J014: the invalidation funnel's consumer set is pinned — only the
    cache (serving/) and the rule evaluator (rules/) may subscribe. A
    third subscriber is a standing-query engine growing outside the one
    whose dirty-set exactness is chaos-tested."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if tail in FUNNEL_SUBSCRIBE_FUNCS:
            findings.append(Finding(
                node.lineno, "J014",
                f"invalidation-funnel subscription `{tail}(...)` outside "
                "the audited consumer set (serving/cache.py internals and "
                "the rule evaluator, horaedb_tpu/rules) — mutation-commit "
                "callbacks are a standing-query surface; consume the rule "
                "engine's dirty sets instead, or suppress with the reason",
            ))


def _check_metering_funnel(tree: ast.Module, findings: list[Finding]) -> None:
    """J015: per-tenant accounting goes through telemetry/metering.py —
    three prongs: (1) a metric family registered under the reserved
    `horaedb_tenant_*` namespace; (2) a family registered with a
    `tenant` labelname; (3) a legacy string-API name literal embedding a
    `tenant="..."` label."""
    def _str_const(node):
        return node.value if (isinstance(node, ast.Constant)
                              and isinstance(node.value, str)) else None

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        name_arg = None
        if node.args:
            name_arg = _str_const(node.args[0])
        for kw in node.keywords:
            if kw.arg == "name" and name_arg is None:
                name_arg = _str_const(kw.value)
        if f.attr in METRIC_REGISTER_VERBS:
            if name_arg and name_arg.startswith(TENANT_FAMILY_PREFIX):
                findings.append(Finding(
                    node.lineno, "J015",
                    f"metric family {name_arg!r} registered outside the "
                    "metering funnel (horaedb_tpu/telemetry/) — the "
                    "horaedb_tenant_* namespace is the usage ledger's; "
                    "account through telemetry.metering.GLOBAL_METER, or "
                    "suppress with the reason",
                ))
                continue
            for kw in node.keywords:
                if kw.arg != "labelnames":
                    continue
                if isinstance(kw.value, (ast.Tuple, ast.List)) and any(
                    _str_const(e) == "tenant" for e in kw.value.elts
                ):
                    findings.append(Finding(
                        node.lineno, "J015",
                        "metric family registered with a `tenant` "
                        "labelname outside the metering funnel — ad-hoc "
                        "per-tenant series fork the usage ledger; route "
                        "the accounting through telemetry.metering."
                        "GLOBAL_METER, or suppress with the reason",
                    ))
        elif f.attr in ("inc", "set") and node.args:
            legacy = _str_const(node.args[0])
            if legacy and "tenant=\"" in legacy:
                findings.append(Finding(
                    node.lineno, "J015",
                    f"legacy metric name {legacy!r} embeds a tenant "
                    "label outside the metering funnel; route through "
                    "telemetry.metering.GLOBAL_METER, or suppress with "
                    "the reason",
                ))


def _check_visibility_boundary(tree: ast.Module, findings: list[Finding]) -> None:
    """J010: attribute access on the visibility state's row-filtering
    fields (`.tombstones`, `.retention_floor_ms`) outside the shared
    helper. Keyword construction (`Visibility(tombstones=...)`) and the
    manifest's accessor methods (`all_tombstones()`) are deliberately NOT
    flagged — building/storing the state is fine; CONSUMING it for row
    filtering belongs in storage/visibility.apply_visibility alone."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in VISIBILITY_FIELDS:
            findings.append(Finding(
                node.lineno, "J010",
                f"`.{node.attr}` consumed outside storage/visibility.py — "
                "tombstone/retention row filtering must go through the "
                "shared apply_visibility helper (one funnel for every "
                "scan route, the downsample pushdown, and compaction), "
                "or deletes diverge between readers; suppress with the "
                "reason for harness introspection",
            ))


def _lock_attrs_of(cls: ast.ClassDef) -> set[str]:
    """Attribute names of locks this class OWNS (self._lock = Lock())."""
    out: set[str] = set()
    for node in ast.walk(cls):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        else:
            continue
        name = None
        if isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ) and target.value.id in ("self", "cls"):
            name = target.attr
        elif isinstance(target, ast.Name) and node in cls.body:
            name = target.id
        if name is None or not name.endswith("lock"):
            continue
        if isinstance(value, ast.Call):
            vd = dotted(value.func) or ""
            if vd.rsplit(".", 1)[-1] in LOCK_FACTORIES:
                out.add(name)
    return out


def _self_underscore_target(expr: ast.expr, bound: str) -> str | None:
    """Resolve (possibly subscripted) `<bound>._x...` store targets to
    the owning attribute name `_x` (`bound` is the method's receiver
    parameter: self or cls)."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == bound
        and expr.attr.startswith("_")
    ):
        return expr.attr
    return None


def _check_lock_discipline(tree: ast.Module, findings: list[Finding]) -> None:
    """J004 per class, two passes: (1) which `self._*` attrs does ANY
    method mutate under a `with self.<lock>:` block — that set IS the
    lock-guarded state, declared by the code itself; (2) a PUBLIC method
    mutating one of those attrs outside the lock is the finding. Attrs
    the lock never guards anywhere (event-loop-confined counters next
    to a lock that serializes something else) are not flagged — the
    class never claimed the lock covers them."""
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs_of(cls)
        if not locks:
            continue
        guarded: set[str] = set()
        for meth in cls.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_method_locking(meth, locks, guarded, None)
        if not guarded:
            continue
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if meth.name.startswith("_"):
                continue  # private/dunder: callers hold the lock
            _scan_method_locking(meth, locks, guarded, findings)


def _scan_method_locking(meth, locks, guarded, findings) -> None:
    """findings=None: COLLECT attrs mutated under a lock into `guarded`.
    Otherwise: FLAG unlocked mutations of guarded attrs."""
    # only the method's FIRST parameter names the shared instance; `self`
    # as a plain local (the `self = object.__new__(cls)` constructor
    # idiom inside classmethods) is a not-yet-published object and its
    # attribute writes race with nobody
    params = meth.args.posonlyargs + meth.args.args
    bound = params[0].arg if params else None
    if bound not in ("self", "cls"):
        return

    def held_by(with_node) -> bool:
        for item in with_node.items:
            ctx = item.context_expr
            if (
                isinstance(ctx, ast.Attribute)
                and isinstance(ctx.value, ast.Name)
                and ctx.value.id == bound
                and ctx.attr in locks
            ):
                return True
        return False

    def visit(nodes, locked: bool) -> None:
        for node in nodes:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                visit(node.body, locked or held_by(node))
                continue
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                       ast.ClassDef)
            ):
                continue  # nested scopes have their own call discipline
            mut = _mutation_of(node, bound)
            if mut is not None:
                attr, verb = mut
                if findings is None:
                    if locked:
                        guarded.add(attr)
                elif not locked and attr in guarded:
                    findings.append(Finding(
                        node.lineno, "J004",
                        f"public method {verb} `self.{attr}` outside "
                        f"`with self.{'/'.join(sorted(locks))}:` — other "
                        "methods mutate this attribute under the lock, so "
                        "unlocked writes race them; take the lock or make "
                        "the method private",
                    ))
            visit(ast.iter_child_nodes(node), locked)

    visit(meth.body, False)


def _mutation_of(node, bound: str) -> tuple[str, str] | None:
    """(attr, verb) when `node` mutates `<bound>._x` state, else None.
    Bare annotations (`self._x: int` with no value) declare, not write."""
    attr = None
    verb = None
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        if isinstance(node, ast.AnnAssign) and node.value is None:
            return None
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            a = _self_underscore_target(t, bound)
            if a:
                attr, verb = a, "assigns"
                break
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            a = _self_underscore_target(t, bound)
            if a:
                attr, verb = a, "deletes"
                break
    elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr in MUTATORS:
        a = _self_underscore_target(node.func.value, bound)
        if a:
            attr, verb = a, f"mutates (.{node.func.attr})"
    if attr is None or attr.endswith("lock"):
        return None  # lazy lock creation is the lock's own lifecycle
    return attr, verb


def lint_file(path: Path) -> list[str]:
    text = path.read_bytes().decode("utf-8", errors="replace")
    lines = text.split("\n")
    sup = Suppressions(lines)
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: J999 syntax error: {e.msg}"]

    posix = path.as_posix()
    is_hot = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in HOT_MODULES
    )
    in_dtype_scope = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in DTYPE_MODULES
    )
    in_j007_scope = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J007_MODULES
    )
    in_j008_scope = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J008_MODULES
    ) and not any(posix.endswith(m) for m in J008_EXEMPT)
    in_j009_scope = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J009_MODULES
    ) and not any(
        (m.endswith("/") and f"/{m}" in f"/{posix}") or posix.endswith(m)
        for m in J009_EXEMPT
    )
    in_j010_scope = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J010_MODULES
    ) and not any(
        (m.endswith("/") and f"/{m}" in f"/{posix}") or posix.endswith(m)
        for m in J010_EXEMPT
    )
    in_j011_scope = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J011_MODULES
    ) and not any(posix.endswith(m) for m in J011_EXEMPT)
    in_j012_scope = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J012_MODULES
    ) and not any(posix.endswith(m) for m in J012_EXEMPT)
    in_j013_base = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J013_MODULES
    )
    j013_reads = in_j013_base and not any(
        (m.endswith("/") and f"/{m}" in f"/{posix}") or posix.endswith(m)
        for m in J013_READ_EXEMPT
    )
    j013_writes = in_j013_base and not any(
        (m.endswith("/") and f"/{m}" in f"/{posix}") or posix.endswith(m)
        for m in J013_WRITE_EXEMPT
    )
    in_j014_scope = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J014_MODULES
    ) and not any(
        (m.endswith("/") and f"/{m}" in f"/{posix}") or posix.endswith(m)
        for m in J014_EXEMPT
    )
    in_j015_scope = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J015_MODULES
    ) and not any(
        (m.endswith("/") and f"/{m}" in f"/{posix}") or posix.endswith(m)
        for m in J015_EXEMPT
    )
    in_j016_scope = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J016_MODULES
    ) and not any(posix.endswith(m) for m in J016_EXEMPT)
    in_j017_base = any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in J017_MODULES
    )
    j017_views = in_j017_base and not any(
        (m.endswith("/") and f"/{m}" in f"/{posix}") or posix.endswith(m)
        for m in J017_VIEW_EXEMPT
    )
    j017_assign = in_j017_base and not any(
        posix.endswith(m) for m in J017_ASSIGN_EXEMPT
    )

    idx = JitIndex()
    idx.visit(tree)
    idx.finish()

    findings: list[Finding] = []
    for fn in idx.jit_defs:
        _check_traced_body(fn, findings)
    if is_hot:
        _check_host_hot(tree, idx.jit_defs, findings)
    _check_jit_call_sites(tree, idx.bare_jit_names, findings)
    if in_dtype_scope:
        _check_dtype(tree, findings)
        if not any(posix.endswith(m) for m in AGG_LANE_MODULES):
            _check_onehot(tree, findings)
    if in_j007_scope:
        _check_naked_jit(tree, findings)
    if in_j008_scope:
        _check_append_hot_path(tree, findings)
    if in_j009_scope:
        _check_store_boundary(tree, findings)
    if in_j010_scope:
        _check_visibility_boundary(tree, findings)
    if in_j011_scope:
        _check_admission_boundary(tree, findings)
    if in_j012_scope:
        _check_decode_funnel(tree, findings)
    if j013_reads or j013_writes:
        _check_serving_funnel(tree, findings, j013_reads, j013_writes)
    if in_j014_scope:
        _check_funnel_subscribers(tree, findings)
    if in_j015_scope:
        _check_metering_funnel(tree, findings)
    if in_j016_scope:
        _check_stacking_funnel(tree, findings)
    if j017_views or j017_assign:
        _check_cluster_funnel(tree, findings, j017_views, j017_assign)
    _check_lock_discipline(tree, findings)

    out = [
        f"{path}:{ln}: J000 suppression missing reason (say why the "
        "invariant is waived)"
        for ln in sup.malformed
    ]
    for f in sorted(findings, key=lambda f: (f.lineno, f.code)):
        if not sup.covers(f.lineno, f.code):
            out.append(f"{path}:{f.lineno}: {f.code} {f.msg}")
    return out


def main() -> None:
    # tests/ are deliberately out of the default roots: test corpora seed
    # the very defects this gate rejects (tests/test_jaxlint.py)
    roots = sys.argv[1:] or [
        "horaedb_tpu", "benchmarks", "tools",
        "bench.py", "__graft_entry__.py",
    ]
    files = iter_py_files(roots)
    all_findings: list[str] = []
    for f in files:
        all_findings.extend(lint_file(f))
    for line in all_findings:
        print(line)
    n = len(all_findings)
    print(f"jaxlint: {n} finding(s) in {len(files)} files")
    raise SystemExit(min(n, 125))


if __name__ == "__main__":
    main()
