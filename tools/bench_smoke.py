"""`make bench-smoke`: a <60 s quick-shape bench.py run that gates the
aggregation registry's dispatch plumbing (wired into `make lint` next to
smoke-metrics).

Asserts, against the single JSON line bench.py --smoke emits:
- the JSON parses and carries the headline metric;
- the calibrated dispatcher picked a VALID registered impl for both the
  sorted and unsorted lane (no env pinning — the automatic path);
- `sorted_ab` and `unsorted_ab` are non-empty (the r05 regression:
  unsorted_ab rendered `{}` while the harness claimed A/B coverage);
- the calibration cache was written and round-trips as JSON.

Runs on the CPU backend with HORAEDB_LINK_PROFILE=skip and a throwaway
calibration cache, so the gate also exercises the COLD calibration path
every time and never touches an accelerator tunnel.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # script execution: tools/ is sys.path[0]
    sys.path.insert(0, REPO)
BUDGET_S = 240  # hard kill; the soft target is <150 s


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="bench-smoke-") as tmp:
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            HORAEDB_LINK_PROFILE="skip",
            HORAEDB_AGG_CACHE=os.path.join(tmp, "agg_calib.json"),
            HORAEDB_AGG_CALIB_N="65536",
            HORAEDB_DECODE_CACHE=os.path.join(tmp, "decode_calib.json"),
            HORAEDB_DECODE_CALIB_N="16384",
        )
        env.pop("HORAEDB_AGG_IMPL", None)  # the gate tests the AUTO path
        env.pop("HORAEDB_SORTED_IMPL", None)
        env.pop("HORAEDB_UNSORTED_IMPL", None)
        env.pop("HORAEDB_DECODE_IMPL", None)
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
            capture_output=True, text=True, timeout=BUDGET_S, env=env,
            cwd=REPO,
        )
        elapsed = time.perf_counter() - t0
        if proc.returncode != 0:
            print(proc.stdout[-2000:])
            print(proc.stderr[-2000:], file=sys.stderr)
            print(f"bench-smoke: FAIL (bench.py rc={proc.returncode})")
            return 1
        result = None
        for line in reversed(proc.stdout.splitlines()):
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and cand.get("metric"):
                result = cand
                break
        failures: list[str] = []
        if result is None:
            failures.append("no JSON result line in bench output")
            result = {}

        def check(cond: bool, msg: str) -> None:
            if not cond:
                failures.append(msg)

        from horaedb_tpu.ops import agg_registry

        check(result.get("metric") == "downsample_rows_per_sec",
              f"wrong metric: {result.get('metric')!r}")
        check(result.get("value", 0) > 0, "non-positive headline value")
        check(result.get("sorted_impl") in agg_registry.SORTED_IMPLS,
              f"dispatcher picked unknown sorted impl "
              f"{result.get('sorted_impl')!r}")
        check(result.get("unsorted_impl") in agg_registry.UNSORTED_IMPLS,
              f"dispatcher picked unknown unsorted impl "
              f"{result.get('unsorted_impl')!r}")
        check(bool(result.get("sorted_ab")), "sorted_ab is empty")
        check(bool(result.get("unsorted_ab")),
              "unsorted_ab is empty (the r05 regression)")
        disp = result.get("agg_dispatcher") or {}
        check(disp.get("source") in ("cache", "calibrated"),
              f"missing calibration provenance: {disp.get('source')!r}")
        # compile/steady split (common/xprof.py): the cold-calibration run
        # must have traced at least one instrumented kernel, and the split
        # fields must ride the payload so bench trajectory can separate a
        # compile-time regression from a kernel regression
        check(result.get("recompiles", 0) > 0,
              f"no recompiles recorded: {result.get('recompiles')!r}")
        check(result.get("compile_s", 0) > 0,
              f"compile_s missing/zero: {result.get('compile_s')!r}")
        check(result.get("steady_s", 0) > 0,
              f"steady_s missing/zero: {result.get('steady_s')!r}")
        # ingest lane (overlapped ingest->flush pipeline): both numbers
        # must ride the payload so bench trajectory can track the overlap
        check(result.get("ingest_pure_samples_per_sec", 0) > 0,
              "ingest lane: pure samples/s missing/zero")
        check(result.get("ingest_with_flush_samples_per_sec", 0) > 0,
              "ingest lane: with-flush samples/s missing/zero")
        # dirty-traffic lanes: the out-of-order-ratio knob must report all
        # three ratios, and the cardinality sketch's per-series cost must
        # stay a rounding error against the ~110 ns/sample ingest budget
        # (10 samples/series in the bench shape -> 1100 ns/series of
        # budget; 1000 ns is already alarm-worthy on any box)
        ooo = result.get("ingest_ooo_samples_per_sec") or {}
        check(set(ooo) == {"0", "5", "25"}
              and all(v > 0 for v in ooo.values()),
              f"ingest ooo lanes missing/zero: {ooo}")
        check("ingest_ooo_overhead_pct" in result,
              "ingest ooo overhead missing")
        sketch_ns = result.get("cardinality_sketch_ns_per_series", 0)
        check(0 < sketch_ns < 1000,
              f"cardinality sketch overhead out of budget: "
              f"{sketch_ns} ns/series (budget <1000)")
        # query QPS lane (admission scheduler): all three concurrency
        # levels present and sane — positive QPS, p50 <= p99, shed rate
        # a valid percentage (the 64-client level runs over a cap of 4,
        # so shedding is expected, not an error)
        full = result.get("query_qps") or {}
        # "batching" nests the coalescing A/B beside the level rows —
        # split it out before the per-level shape checks below
        qps = {k: v for k, v in full.items() if k != "batching"}
        check(set(qps) == {"1", "8", "64"},
              f"query qps lane levels missing: {sorted(qps)}")
        for lvl, row in qps.items():
            check(row.get("qps", 0) > 0,
                  f"query qps lane {lvl}: non-positive qps: {row}")
            p50, p99 = row.get("p50_ms"), row.get("p99_ms")
            check(p50 is not None and p99 is not None and 0 < p50 <= p99,
                  f"query qps lane {lvl}: bad latency percentiles: {row}")
            check(0.0 <= row.get("shed_pct", -1) <= 100.0,
                  f"query qps lane {lvl}: bad shed_pct: {row}")
        # query batching A/B (server/batching.py): all three levels with
        # both arms present; at 8/64 clients the coalescing arm must
        # actually coalesce (batched_with > 1 in the mix) AND hold the
        # acceptance bar — batched p50 <= unbatched p50 (a 1.1 slack
        # absorbs box noise on the loaded 2-core bench host; measured
        # headroom is ~1.9x at 8 clients, so a real regression still
        # trips it) — while the 1-client level stays unregressed (1.25
        # slack: sub-3ms absolute numbers wobble harder)
        ab = full.get("batching") or {}
        check(set(ab) == {"1", "8", "64"},
              f"batching A/B levels missing: {sorted(ab)}")
        for lvl, row in ab.items():
            for arm in ("on", "off"):
                r = row.get(arm) or {}
                check(r.get("qps", 0) > 0 and r.get("p50_ms"),
                      f"batching A/B {lvl}/{arm}: missing numbers: {r}")
        for lvl in ("8", "64"):
            row = ab.get(lvl) or {}
            mix = (row.get("on") or {}).get("batched_with_mix") or {}
            check(any(int(k) > 1 for k in mix),
                  f"batching {lvl}-client arm never coalesced: {mix}")
            p_on = (row.get("on") or {}).get("p50_ms") or 1e9
            p_off = (row.get("off") or {}).get("p50_ms") or 0
            check(p_on <= p_off * 1.1,
                  f"batched p50 not <= unbatched at {lvl} clients "
                  f"(on={p_on} off={p_off})")
        lone = ab.get("1") or {}
        p_on = (lone.get("on") or {}).get("p50_ms") or 1e9
        p_off = (lone.get("off") or {}).get("p50_ms") or 0
        check(p_on <= p_off * 1.25,
              f"1-client p50 regressed under batching "
              f"(on={p_on} off={p_off})")
        # compressed-domain scan lane (storage/encoding.py +
        # ops/decode.py): present, the calibrated dispatcher picked a
        # VALID decode impl per codec, and the tsid/ts lanes actually
        # compressed (the whole point of shipping them encoded)
        from horaedb_tpu.ops import decode as decode_ops

        se = result.get("scan_encoded") or {}
        check(se.get("rows", 0) > 0, "scan_encoded lane missing")
        check(se.get("encode_ns_per_row", 0) > 0,
              "scan_encoded: encode cost missing")
        bpr = se.get("bytes_per_row") or {}
        check(bpr.get("ratio", 0) > 1.0,
              f"scan_encoded: no wire-byte reduction: {bpr}")
        for codec, impl in (se.get("decode_auto_impl") or {}).items():
            check(impl in decode_ops.DECODE_IMPLS,
                  f"scan_encoded: auto picked unknown impl {impl!r} "
                  f"for {codec}")
        check(bool(se.get("decode_auto_impl")),
              "scan_encoded: auto-dispatch resolved no codec")
        e2e = se.get("e2e") or {}
        check({"filtered", "full"} <= set(e2e),
              f"scan_encoded: e2e shapes missing: {sorted(e2e)}")
        for shape, row in e2e.items():
            check(row.get("raw_rows_per_sec", 0) > 0
                  and row.get("encoded_rows_per_sec", 0) > 0,
                  f"scan_encoded e2e {shape}: non-positive rate: {row}")
        # serving-tier lane (horaedb_tpu/serving): the zipf dashboard
        # workload must be present, every concurrency level warm, the
        # result cache actually hitting, rollup substitution happening,
        # and warm p50 strictly faster than cold p50 (the whole point
        # of the tier; cold pays a real scan, warm is a cache probe)
        qs = result.get("query_serving") or {}
        check(qs.get("panels") == 64,
              f"query_serving lane missing/wrong panels: {qs.get('panels')}")
        check(qs.get("cold_p50_ms", 0) > 0,
              "query_serving: cold p50 missing/zero")
        check(qs.get("rollup_substitution_rate", 0) > 0,
              f"query_serving: no rollup substitution: "
              f"{qs.get('rollup_substitution_rate')!r}")
        qs_levels = qs.get("levels") or {}
        check(set(qs_levels) == {"1", "8", "64"},
              f"query_serving levels missing: {sorted(qs_levels)}")
        for lvl, row in qs_levels.items():
            check(row.get("qps", 0) > 0,
                  f"query_serving {lvl}: non-positive qps: {row}")
            check(row.get("hit_rate") is not None
                  and row["hit_rate"] > 0.5,
                  f"query_serving {lvl}: cache not hitting: {row}")
        warm_p50 = (qs_levels.get("1") or {}).get("p50_ms")
        check(warm_p50 is not None
              and warm_p50 < qs.get("cold_p50_ms", 0),
              f"query_serving: warm p50 not faster than cold "
              f"(warm={warm_p50}, cold={qs.get('cold_p50_ms')})")
        # rule-storm lane (horaedb_tpu/rules): the dirty-set proof — a
        # no-mutation tick evaluates ZERO rules and beats the full
        # materialization tick by an order of magnitude; alert rules
        # sharing a selector ride the result cache
        rs = result.get("rule_storm") or {}
        check(rs.get("rules", 0) > 0, "rule_storm lane missing")
        check(rs.get("materialize_rules_per_sec", 0) > 0,
              f"rule_storm: non-positive materialize rate: {rs}")
        check(rs.get("quiet_evaluated", -1) == 0,
              f"rule_storm: quiet tick evaluated "
              f"{rs.get('quiet_evaluated')} rules (want 0)")
        check(rs.get("quiet_skipped", 0)
              == rs.get("rules", 0) + rs.get("alert_rules", 0),
              f"rule_storm: quiet tick skipped {rs.get('quiet_skipped')} "
              f"of {rs.get('rules', 0) + rs.get('alert_rules', 0)}")
        check(rs.get("quiet_speedup_vs_materialize", 0) > 10,
              f"rule_storm: quiet tick not >10x cheaper than "
              f"materialize: {rs.get('quiet_speedup_vs_materialize')}")
        check(rs.get("incremental_tick_p99_ms", 0) > 0,
              "rule_storm: incremental tick p99 missing")
        check(rs.get("eval_lag_after_tick_s", 1) == 0,
              f"rule_storm: evaluator lagging after tick: "
              f"{rs.get('eval_lag_after_tick_s')}")
        hr = rs.get("alert_cache_hit_rate")
        check(hr is not None and hr > 0.5,
              f"rule_storm: alert rules not riding the result cache "
              f"(hit rate {hr})")
        # self-telemetry lane (horaedb_tpu/telemetry): the monitor's own
        # cost — a real tick measured, and the steady-state duty cycle
        # (tick wall / default 15 s interval) inside the <2% ingest
        # overhead budget the acceptance bar pins. The interleaved-A/B
        # overhead is reported but not asserted (box-noise territory).
        st = result.get("self_telemetry") or {}
        check(st.get("families", 0) > 20,
              f"self_telemetry lane missing/implausible: {st}")
        check(st.get("samples_per_tick", 0) > 100,
              f"self_telemetry: snapshot too small: {st}")
        check(st.get("snapshot_ns_per_family", 0) > 0,
              "self_telemetry: snapshot cost missing")
        check(st.get("tick_ms", 0) > 0, "self_telemetry: tick cost missing")
        duty = st.get("duty_pct_at_default_interval")
        check(duty is not None and 0 < duty < 2.0,
              f"self_telemetry: steady-state duty cycle out of the <2% "
              f"budget: {duty}")
        check(st.get("ingest_base_samples_per_sec", 0) > 0
              and st.get("ingest_with_scrape_samples_per_sec", 0) > 0,
              f"self_telemetry: ingest A/B missing: {st}")
        # cluster lane (horaedb_tpu/cluster): both arms present at every
        # level, replicas answered BIT-IDENTICALLY to the writer after
        # catch-up, and the scale-out factor + lag p99 are reported
        # (their magnitudes are box-dependent; presence + correctness
        # are the gate)
        cs = result.get("cluster_scaleout") or {}
        check(cs.get("replica_exact") is True,
              f"cluster lane: replica-served results not exact: {cs}")
        for lvl in ("1", "8", "64"):
            row = cs.get(lvl) or {}
            for arm in ("writer_only", "writer_plus_2_replicas"):
                a = row.get(arm) or {}
                check(a.get("qps", 0) > 0,
                      f"cluster lane {lvl}/{arm}: missing/zero qps: {a}")
        check(cs.get("scale_out_factor", 0) > 0,
              f"cluster lane: scale_out_factor missing: {cs}")
        check(cs.get("replica_lag_p99_ms", 0) > 0,
              f"cluster lane: replica lag p99 missing: {cs}")
        # scatter-gather A/B: both arms present at every level, the
        # merged split answer BIT-EXACT vs the single-node scan, and
        # the calibrated capacity speedup reported (its magnitude is
        # box-dependent; presence + exactness are the gate)
        sg = cs.get("scatter_gather") or {}
        check(sg.get("split_exact") is True,
              f"scatter-gather: merged split result not bit-exact: {sg}")
        for lvl in ("1", "8", "64"):
            row = sg.get(lvl) or {}
            for arm in ("whole_forward", "split_compute"):
                a = row.get(arm) or {}
                check(a.get("qps", 0) > 0,
                      f"scatter-gather {lvl}/{arm}: missing/zero qps: {a}")
        check(sg.get("capacity_speedup", 0) > 0,
              f"scatter-gather: capacity_speedup missing: {sg}")
        wire = sg.get("wire_bytes_per_query") or {}
        check(wire.get("whole_forward_json", 0) > 0
              and wire.get("split_partials", 0) > 0,
              f"scatter-gather: wire-bytes A/B missing: {wire}")
        # trace-shipping A/B on the forwarded write path: both arms
        # present, and the overhead is not runaway. The tracked target
        # is <5% at full iters; the smoke bound is loose because 50
        # interleaved requests on a busy CI box jitter by several
        # percent either way — this gate catches a broken budget
        # (unbounded header shipping reads as 50%+), not box noise.
        fw = cs.get("forwarded_write") or {}
        check(fw.get("p50_ms_untraced", 0) > 0
              and fw.get("p50_ms_traced", 0) > 0,
              f"cluster lane: forwarded-write trace A/B missing: {fw}")
        check(fw.get("trace_ship_overhead_pct", 1e9) < 25.0,
              f"cluster lane: trace shipping overhead runaway "
              f"(target <5% at full iters): {fw}")
        # copy-tax lane (common/memtrace.py): the ledger must see the
        # scan move every row exactly once — bytes_copied_per_row on the
        # 24 B/row (tsid+ts+value) schema pins at 24 with zero slack
        # (a second materialize pass reads as 48, a missed funnel as 0).
        # The overhead arm is sanity-only here: smoke scans run ~5 ms,
        # where asyncio.run jitter swamps the real <2% target (the
        # mem-smoke gate measures that bound properly); this check only
        # catches a runaway (accidentally-deep default mode reads 100%+).
        ct = result.get("copy_tax") or {}
        check(ct.get("rows", 0) > 0, "copy_tax lane missing")
        ct_scan = ct.get("scan") or {}
        check(ct_scan.get("rows_scanned") == ct.get("rows"),
              f"copy_tax: scan saw {ct_scan.get('rows_scanned')} of "
              f"{ct.get('rows')} rows (merge dedup regression?)")
        check(ct_scan.get("bytes_copied_per_row") == 24.0,
              f"copy_tax: scan copy tax not pinned at 24 B/row: "
              f"{ct_scan.get('bytes_copied_per_row')}")
        check(ct_scan.get("views", 0) > 0,
              f"copy_tax: no view-classified hand-offs recorded: {ct_scan}")
        # zero-copy spine (common/colblock.py): the chunk-aware merge
        # must make NO host_prep copies — the one remaining scan copy is
        # the materialize take (the 24 B/row above, the output itself)
        hp = (ct_scan.get("per_stage") or {}).get("host_prep") or {}
        check(hp.get("copied_bytes_per_row") == 0.0,
              f"copy_tax: host_prep copies crept back into the merge "
              f"path (zero-copy spine regression): {hp}")
        # and the host-side prep+materialize wall stays ms-scale — a
        # refactor trading copies for slow chunk-walking shows up here
        check(ct_scan.get("host_prep_materialize_ms", 1e9) <= 2.0,
              f"copy_tax: host_prep+materialize wall "
              f"{ct_scan.get('host_prep_materialize_ms')} ms (bar 2 ms)")
        ct_ingest = ct.get("ingest") or {}
        check(ct_ingest.get("bytes_allocated_per_row", 0) > 0,
              f"copy_tax: ingest alloc accounting missing: {ct_ingest}")
        # flush-encode alloc density: type-driven column encodings
        # (DELTA_BINARY_PACKED ints / BYTE_STREAM_SPLIT floats) must
        # stay strictly below r19's plain-encoding 12.7 B/row
        enc = (ct_ingest.get("per_stage") or {}).get("flush_encode") or {}
        check(enc.get("alloc_bytes_per_row", 1e9) < 12.7,
              f"copy_tax: flush_encode allocs "
              f"{enc.get('alloc_bytes_per_row')} B/row — at or above the "
              f"r19 12.7 B/row bar")
        ov = ct.get("overhead") or {}
        check(ov.get("scan_default_s", 0) > 0 and ov.get("scan_off_s", 0) > 0,
              f"copy_tax: overhead A/B arms missing: {ov}")
        check(abs(ov.get("overhead_pct", 1e9)) < 75.0,
              f"copy_tax: memtrace overhead runaway (target <2% at real "
              f"scan sizes; this bound is smoke-noise-only): {ov}")
        cache_file = env["HORAEDB_AGG_CACHE"]
        if not os.path.exists(cache_file):
            failures.append("calibration cache was not persisted")
        else:
            try:
                json.load(open(cache_file, encoding="utf-8"))
            except ValueError:
                failures.append("calibration cache is not valid JSON")
        # budget grew 60 -> 120 s when the query_serving lane joined,
        # 120 -> 150 s when self_telemetry did (118 s measured),
        # 150 -> 180 s when the batching A/B joined (six timed arms +
        # stacked-kernel warmup compiles), 180 -> 200 s for the cluster
        # lane (six more timed arms at 0.3 s + replica opens), and
        # 200 -> 230 s for the scatter-gather A/B (regioned boot +
        # calibration + six 1 s closed-loop arms); the copy_tax lane
        # rides inside the same budget (~5 s: 30 k-row ingest + ms-scale
        # scans); the gate exists to catch runaway regressions, not 20%
        # box noise
        check(elapsed < 230,
              f"smoke bench took {elapsed:.0f}s (budget 230s)")
        if failures:
            for f in failures:
                print(f"bench-smoke: FAIL {f}")
            print(json.dumps(result)[:1500])
            return 1
        print(
            f"bench-smoke: OK in {elapsed:.1f}s — sorted="
            f"{result['sorted_impl']} ({len(result['sorted_ab'])} impls), "
            f"unsorted={result['unsorted_impl']} "
            f"({len(result['unsorted_ab'])} impls), "
            f"{result['value'] / 1e6:.1f}M rows/s"
        )
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
