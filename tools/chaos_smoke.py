"""Chaos smoke gate (`make chaos-smoke`, folded into `make lint`).

Two phases, both at smoke scale (seconds, CPU-only):

1. **Server on a ChaosStore.** Boots the REAL server (build_app) over a
   seeded ChaosStore (injected errors, torn writes, listing lag) wrapped
   in the ResilientStore the production boot path uses. Pushes
   remote-write batches with sender-style retries, queries them back,
   and asserts the engine's answers match the host model EXACTLY under
   live faults. Then trips the circuit breaker and asserts the shedding
   contract: writes answer **503 + Retry-After** (never a hang, never a
   silent drop), and recover to 200 after reset. Finally checks the
   `horaedb_objstore_*` families render on /metrics with retries
   actually counted.

2. **Crash recovery.** An epoch-fenced engine crashes (InjectedCrash)
   between an SST upload and its manifest commit. Reopen must acquire
   the next epoch with no unfencing step, recover to the committed
   snapshot (zero acknowledged-row loss), and GC the orphan SST.

This is the end-to-end half the unit chaos lane (tests/test_chaos.py)
can't give: the HTTP status mapping, the boot-path store wrapping, and
the metric rendering only exist in one live process.

Run: JAX_PLATFORMS=cpu python tools/chaos_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

SMOKE_SEED = 7


def make_payload(metric: str, rows: list[tuple[str, int, float]]) -> bytes:
    from horaedb_tpu.pb import remote_write_pb2

    by_host: dict[str, list[tuple[int, float]]] = {}
    for host, ts, v in rows:
        by_host.setdefault(host, []).append((ts, v))
    req = remote_write_pb2.WriteRequest()
    for host in sorted(by_host):
        series = req.timeseries.add()
        for k, v in ((b"__name__", metric.encode()), (b"host", host.encode())):
            lab = series.labels.add()
            lab.name = k
            lab.value = v
        for t, val in by_host[host]:
            s = series.samples.add()
            s.timestamp = t
            s.value = val
    return req.SerializeToString()


async def server_phase(check) -> None:
    import aiohttp
    from aiohttp import web

    from horaedb_tpu.common.time_ext import ReadableDuration
    from horaedb_tpu.objstore import MemStore
    from horaedb_tpu.objstore.chaos import ChaosStore, FaultPlan, OpFaults
    from horaedb_tpu.objstore.resilient import (
        BreakerPolicy,
        ResilientStore,
        RetryPolicy,
    )
    from horaedb_tpu.server.config import Config
    from horaedb_tpu.server.main import build_app

    import tempfile

    ms = ReadableDuration.millis
    scratch = tempfile.mkdtemp(prefix="horaedb-chaos-smoke-")
    chaos = ChaosStore(MemStore(), FaultPlan(
        seed=SMOKE_SEED,
        ops={
            "put": OpFaults(error_rate=0.10, torn_write_rate=0.05,
                            lost_ack_rate=0.03),
            "get": OpFaults(error_rate=0.06),
            "list": OpFaults(error_rate=0.06),
            "delete": OpFaults(error_rate=0.08),
        },
        visibility_lag_ops=5,
    ))
    store = ResilientStore(
        chaos,
        retry=RetryPolicy(max_attempts=10, backoff_base=ms(1),
                          backoff_cap=ms(5)),
        breaker=BreakerPolicy(failure_threshold=5,
                              open_for=ReadableDuration.secs(30)),
        name="chaos-smoke",
    )
    cfg = Config.from_dict({
        "metric_engine": {
            "storage": {"object_store": {"data_dir": scratch}},
            "ingest_buffer_rows": 16,
            # dirty-traffic lane: the series-cardinality limit the breach
            # check below crosses (ingest/cardinality.py)
            "limits": {"max_series": 30},
        },
    })
    app = await build_app(cfg, store=store)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    model: dict[int, float] = {}
    try:
        async with aiohttp.ClientSession() as s:

            async def send_acked(payload: bytes) -> bool:
                """Sender semantics: retry any 5xx; honor tiny Retry-After."""
                for _ in range(40):
                    async with s.post(f"{base}/api/v1/write",
                                      data=payload) as r:
                        if r.status == 200:
                            return True
                        await asyncio.sleep(0.01)
                return False

            # 8 rounds of faulted writes; the model folds in only acked rows
            for rnd in range(8):
                rows = [
                    (f"h{i % 3}", 1000 + rnd * 10_000 + i * 100,
                     float(rnd * 100 + i))
                    for i in range(6)
                ]
                acked = await send_acked(make_payload("chaos_smoke", rows))
                check(acked, f"round {rnd}: write acked under faults")
                if acked:
                    for _h, ts, v in rows:
                        model[ts] = v
            async with s.post(f"{base}/api/v1/query", json={
                "metric": "chaos_smoke", "start_ms": 0, "end_ms": 10**9,
            }) as r:
                body = await r.json()
            got = dict(zip(body.get("ts", []), body.get("value", [])))
            check(r.status == 200 and got == model,
                  f"query matches host model exactly under faults "
                  f"({len(model)} acked rows, "
                  f"{chaos.injected_errors} injected faults)")

            # ---- overload shedding: breaker open -> bounded 503s
            store.breaker.force_open()
            t0 = asyncio.get_running_loop().time()
            async with s.post(
                f"{base}/api/v1/write",
                data=make_payload("chaos_shed", [("x", 1000, 1.0)]),
            ) as r:
                elapsed = asyncio.get_running_loop().time() - t0
                check(r.status == 503,
                      f"breaker-open write answers 503 (got {r.status})")
                check(r.headers.get("Retry-After", "").isdigit(),
                      f"503 carries Retry-After "
                      f"({r.headers.get('Retry-After')!r})")
                check(elapsed < 5.0,
                      f"shed response is bounded-latency ({elapsed:.2f}s)")
            store.breaker.reset()
            ok = await send_acked(
                make_payload("chaos_shed", [("x", 1000, 1.0)])
            )
            check(ok, "writes recover to 200 after breaker reset")

            # ---- objstore resilience families render, retries counted
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
            for fam in ("horaedb_objstore_attempts_total",
                        "horaedb_objstore_retries_total",
                        "horaedb_objstore_gave_up_total",
                        "horaedb_objstore_breaker_state"):
                check(fam in text, f"/metrics exposes {fam}")
            retry_lines = [
                ln for ln in text.splitlines()
                if ln.startswith("horaedb_objstore_retries_total{")
            ]
            total_retries = sum(float(ln.rsplit(" ", 1)[1])
                                for ln in retry_lines)
            check(total_retries > 0,
                  f"injected faults produced counted retries "
                  f"({int(total_retries)})")

            # ---- dirty-traffic lane: duplicates, late data, a tombstone
            # delete, and a cardinality breach — all over the SAME faulted
            # store, asserted exact against the host model
            host_of: dict[int, str] = {}
            for rnd in range(8):
                for i in range(6):
                    host_of[1000 + rnd * 10_000 + i * 100] = f"h{i % 3}"

            async def query_map() -> dict:
                async with s.post(f"{base}/api/v1/query", json={
                    "metric": "chaos_smoke", "start_ms": 0,
                    "end_ms": 10**12,
                }) as r:
                    body = await r.json()
                return dict(zip(body.get("ts", []), body.get("value", [])))

            # DUPLICATES: overwrite three existing points (LWW by seq)
            dup_rows = [(host_of[ts], ts, 9_000.0 + ts) for ts in
                        sorted(model)[:3]]
            ok = await send_acked(make_payload("chaos_smoke", dup_rows))
            check(ok, "duplicate overwrites acked under faults")
            if ok:
                for _h, ts, v in dup_rows:
                    model[ts] = v
            # LATE: a lagging agent 13+ hours behind (a SEGMENT older than
            # the watermark at the default 12h segment duration)
            late_ts = 50 * 3_600_000
            head_rows = [("h9", late_ts + 14 * 3_600_000, 1.0)]
            late_rows = [("h9", late_ts + i, float(i)) for i in range(3)]
            for rows in (head_rows, late_rows):
                ok = await send_acked(make_payload("chaos_smoke", rows))
                check(ok, "late-lane write acked under faults")
                if ok:
                    for h, ts, v in rows:
                        model[ts] = v
                        host_of[ts] = h
            got = await query_map()
            check(got == model,
                  "query matches model exactly with duplicates + late data")
            # DELETE: tombstone one host's window through the admin API
            del_end_ms = 100_000
            for _ in range(40):
                async with s.post(
                    f"{base}/api/v1/admin/tsdb/delete_series",
                    params={"match[]": 'chaos_smoke{host="h1"}',
                            "start": "0", "end": str(del_end_ms // 1000)},
                ) as r:
                    if r.status == 200:
                        body = await r.json()
                        break
                    await asyncio.sleep(0.01)
            check(r.status == 200 and body.get("status") == "success",
                  f"delete_series acked under faults ({body})")
            deleted = [ts for ts, h in host_of.items()
                       if h == "h1" and ts <= del_end_ms and ts in model]
            check(len(deleted) > 0, "delete matched existing rows")
            for ts in deleted:
                del model[ts]
            got = await query_map()
            check(got == model,
                  f"deletes mask immediately and exactly "
                  f"({len(deleted)} rows gone)")
            # post-delete re-ingest into the deleted window survives
            re_ts = deleted[0]
            ok = await send_acked(make_payload(
                "chaos_smoke", [("h1", re_ts, 4_242.0)]
            ))
            if ok:
                model[re_ts] = 4_242.0
            got = await query_map()
            check(ok and got == model,
                  "post-delete re-ingest into the deleted range survives")
            # CARDINALITY breach: flood past the limit, then expect the
            # counted 503/Retry-After partial-accept (bounded latency, the
            # existing-series sample still accepted)
            flood = [(f"x{i:02d}", 900_000 + i, 1.0) for i in range(40)]
            ok = await send_acked(make_payload("chaos_card", flood))
            check(ok, "flood payload crossing the limit acked")
            over = make_payload("chaos_smoke", [
                (host_of[re_ts], re_ts, 4_243.0),   # existing series
                ("brandnew1", 901_001, 1.0),
                ("brandnew2", 901_002, 1.0),
            ])
            body = {}
            for _ in range(40):
                t0 = asyncio.get_running_loop().time()
                async with s.post(f"{base}/api/v1/write", data=over) as r:
                    elapsed = asyncio.get_running_loop().time() - t0
                    body = await r.json()
                    if r.status == 503 and body.get("partial_accept"):
                        break
                    await asyncio.sleep(0.01)
            check(r.status == 503 and body.get("partial_accept") is True,
                  f"cardinality breach answers 503 partial-accept ({body})")
            check(body.get("rejected_series") == 2
                  and body.get("accepted_samples") == 1,
                  f"partial-accept accounting exact ({body})")
            check(r.headers.get("Retry-After", "").isdigit(),
                  "cardinality 503 carries Retry-After")
            check(elapsed < 5.0,
                  f"cardinality shed is bounded-latency ({elapsed:.2f}s)")
            model[re_ts] = 4_243.0  # the accepted existing-series sample
            got = await query_map()
            check(got == model, "in-budget samples survive the breach")
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
            for fam in ("horaedb_series_cardinality",
                        "horaedb_late_samples_total",
                        "horaedb_tombstones_applied_total",
                        "horaedb_cardinality_rejected_samples_total"):
                check(fam in text, f"/metrics exposes {fam}")
            card_lines = [
                ln for ln in text.splitlines()
                if ln.startswith("horaedb_cardinality_limited_requests_total{")
            ]
            check(sum(float(ln.rsplit(" ", 1)[1]) for ln in card_lines) > 0,
                  "cardinality rejections are counted")
    finally:
        await runner.cleanup()
        import shutil

        shutil.rmtree(scratch, ignore_errors=True)


async def crash_phase(check) -> None:
    from horaedb_tpu.common.time_ext import ReadableDuration
    from horaedb_tpu.engine import MetricEngine, QueryRequest
    from horaedb_tpu.ingest import PooledParser
    from horaedb_tpu.objstore import MemStore
    from horaedb_tpu.objstore.chaos import ChaosStore, InjectedCrash
    from horaedb_tpu.objstore.resilient import ResilientStore, RetryPolicy

    HOUR = 3_600_000
    inner = MemStore()
    chaos = ChaosStore(inner)
    store = ResilientStore(
        chaos,
        retry=RetryPolicy(max_attempts=4,
                          backoff_base=ReadableDuration.millis(1)),
        name="chaos-crash",
    )

    async def open_engine(node: str) -> MetricEngine:
        return await MetricEngine.open(
            "db", store, segment_duration_ms=HOUR, enable_compaction=False,
            fence_node_id=node, fence_validate_interval_s=0.0,
        )

    eng = await open_engine("chaos-a")
    await eng.write_parsed(PooledParser.decode(
        make_payload("crash_smoke", [("a", 1000, 1.0), ("a", 2000, 2.0)])
    ))
    # the crash: SST upload lands, its manifest commit never does
    chaos.crash_next("put", "db/data/manifest/delta/")
    crashed = False
    try:
        await eng.write_parsed(PooledParser.decode(
            make_payload("crash_smoke", [("a", 3000, 3.0)])
        ))
    except InjectedCrash:
        crashed = True
    check(crashed, "crash point fired between upload and commit")
    # the dead process runs nothing: cancel its background tasks
    for t in (eng.metrics_table, eng.series_table, eng.index_table,
              eng.tags_table, eng.data_table, eng.exemplars_table):
        await t.manifest.close()
    old_epoch = eng._fence.epoch
    del eng

    eng2 = await open_engine("chaos-b")
    check(eng2._fence.epoch == old_epoch + 1,
          f"replacement writer acquired next epoch "
          f"({old_epoch} -> {eng2._fence.epoch}) with no unfencing step")
    t = await eng2.query(QueryRequest(metric=b"crash_smoke", start_ms=0,
                                      end_ms=HOUR))
    vals = sorted(t.column("value").to_pylist()) if t is not None else []
    check(vals == [1.0, 2.0],
          f"recovered to the committed snapshot exactly (rows={vals})")
    live = {s.id for s in eng2.data_table.manifest.all_ssts()}
    orphans = [
        p for p in inner._objects
        if p.startswith("db/data/data/") and p.endswith(".sst")
        and int(p.rsplit("/", 1)[-1][:-4]) not in live
    ]
    check(orphans == [], f"orphan SSTs GC'd at reopen ({orphans})")
    await eng2.close()


async def run() -> int:
    failures: list[str] = []

    def check(ok: bool, msg: str) -> None:
        print(("ok   " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    await server_phase(check)
    await crash_phase(check)
    print(f"chaos-smoke: {len(failures)} failure(s)")
    return 1 if failures else 0


def main() -> None:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    raise SystemExit(asyncio.run(run()))


if __name__ == "__main__":
    main()
