"""Stdlib lint gate (`make lint`).

The reference CI enforces `clippy -D warnings` + rustfmt + cargo-sort
(/root/reference/Makefile:37-53). This environment ships no ruff/mypy and
installs are off-limits, so the gate is a from-scratch AST linter covering
the highest-signal subset:

  F401  unused import
  F403  `from x import *`
  F811  redefinition of an imported name by another import
  F601  duplicate key in a dict literal
  E101  tab indentation / CRLF line endings
  E501  line longer than MAX_LINE columns
  W291  trailing whitespace
  B006  mutable default argument (list/dict/set literals)
  C901  bare `except:` (use `except Exception` at minimum)

Zero findings is the bar: the tree is kept clean and CI (make lint) fails
on any regression. Exit code = number of findings (capped 125).
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

MAX_LINE = 100
_IDENT = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")

# names a module re-exports on purpose (import kept for its side effect or
# for package API) — the linter honors `__all__` and `# noqa` instead of a
# config file
NOQA = "# noqa"


def iter_py_files(roots: list[str]) -> list[Path]:
    out: list[Path] = []
    for r in roots:
        p = Path(r)
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        else:
            # a vanished root must FAIL the gate, not quietly narrow it
            raise SystemExit(f"lint: root does not exist: {r}")
    # pb/ holds protoc codegen — machine-formatted, not held to hand-written
    # style (the reference likewise lints source, not generated stubs)
    return [p for p in out
            if "__pycache__" not in p.parts and "pb" not in p.parts]


class ImportVisitor(ast.NodeVisitor):
    """Collect imported names + every identifier/attribute usage."""

    def __init__(self) -> None:
        self.imports: dict[str, tuple[int, str]] = {}  # name -> (line, code)
        self.used: set[str] = set()
        self.stars: list[int] = []          # lineno of each `import *`
        self.redefs: list[tuple[str, int]] = []  # (name, lineno) reimports
        self._depth = 0                     # function/class nesting
        self._module_imports: set[str] = set()

    def _record(self, name: str, lineno: int) -> None:
        # F811 only for MODULE-level redefinition — re-importing inside a
        # function body is deliberate scoping (lazy imports), not shadowing
        if self._depth == 0:
            if name in self._module_imports:
                self.redefs.append((name, lineno))
            self._module_imports.add(name)
        self.imports[name] = (lineno, "F401")

    def _scoped(self, node: ast.AST) -> None:
        self._depth += 1
        self.generic_visit(node)
        self._depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._mark_annotation(node.returns)
        self._scoped(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._mark_annotation(node.returns)
        self._scoped(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scoped(node)

    def visit_Try(self, node: ast.Try) -> None:
        # the try/except ImportError fallback-import idiom re-imports the
        # same name by design — not an F811 redefinition
        self._scoped(node)

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self._record(a.asname or a.name.split(".")[0], node.lineno)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            self.generic_visit(node)
            return
        for a in node.names:
            if a.name == "*":
                self.stars.append(node.lineno)
                continue
            self._record(a.asname or a.name, node.lineno)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # `np.foo` marks `np` used via the Name child; nothing extra needed
        self.generic_visit(node)

    def _mark_annotation(self, ann: ast.expr | None) -> None:
        """Quoted annotations (`x: "PathLike"`, the TYPE_CHECKING idiom)
        are plain strings in the AST; count their identifier tokens as
        usages so F401 doesn't fire on them. Docstrings deliberately do
        NOT count — only annotation positions."""
        if ann is None:
            return
        for sub in ast.walk(ann):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                self.used.update(_IDENT.findall(sub.value))

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._mark_annotation(node.annotation)
        self.generic_visit(node)

    def visit_arg(self, node: ast.arg) -> None:
        self._mark_annotation(node.annotation)
        self.generic_visit(node)


def lint_file(path: Path) -> list[str]:
    findings: list[str] = []
    raw = path.read_bytes()
    text = raw.decode("utf-8", errors="replace")
    # split on \n only: ast.parse counts only \n/\r\n as line breaks, and
    # splitlines() would also split on \f/\v/ , desyncing linenos
    lines = text.split("\n")

    def flagged(lineno: int) -> bool:
        return 0 < lineno <= len(lines) and NOQA in lines[lineno - 1]

    if b"\r\n" in raw:
        findings.append(f"{path}:1: E101 CRLF line endings")
    for i, line in enumerate(lines, 1):
        if NOQA in line:
            continue
        if line.rstrip("\n") != line.rstrip():
            findings.append(f"{path}:{i}: W291 trailing whitespace")
        if "\t" in line.split("#")[0]:
            findings.append(f"{path}:{i}: E101 tab in source")
        if len(line) > MAX_LINE:
            findings.append(
                f"{path}:{i}: E501 line too long ({len(line)} > {MAX_LINE})"
            )

    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: E999 syntax error: {e.msg}"]

    # names listed in the module __all__ count as used (re-exports)
    exported: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        for elt in node.value.elts:
                            if isinstance(elt, ast.Constant):
                                exported.add(str(elt.value))

    v = ImportVisitor()
    v.visit(tree)
    is_init = path.name == "__init__.py"
    for name, (lineno, _code) in v.imports.items():
        if name in v.used or name in exported or name.startswith("_"):
            continue
        if is_init:  # packages re-export via imports by design
            continue
        if flagged(lineno):
            continue
        findings.append(f"{path}:{lineno}: F401 unused import: {name}")
    for lineno in v.stars:
        if not flagged(lineno):
            findings.append(f"{path}:{lineno}: F403 star import")
    for name, lineno in v.redefs:
        if not flagged(lineno):
            findings.append(
                f"{path}:{lineno}: F811 import redefines earlier "
                f"import: {name}"
            )

    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            seen: set = set()
            for k in node.keys:
                if isinstance(k, ast.Constant):
                    if k.value in seen and not flagged(k.lineno):
                        findings.append(
                            f"{path}:{k.lineno}: F601 duplicate dict key: "
                            f"{k.value!r}"
                        )
                    seen.add(k.value)
        elif isinstance(node, ast.ExceptHandler):
            if node.type is None and not flagged(node.lineno):
                findings.append(f"{path}:{node.lineno}: C901 bare except")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.args.defaults + node.args.kw_defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)) \
                        and not flagged(d.lineno):
                    findings.append(
                        f"{path}:{d.lineno}: B006 mutable default argument "
                        f"in {node.name}()"
                    )
    return findings


def main() -> None:
    roots = sys.argv[1:] or [
        "horaedb_tpu", "tests", "benchmarks", "tools",
        "bench.py", "__graft_entry__.py",
    ]
    files = iter_py_files(roots)
    all_findings: list[str] = []
    for f in files:
        all_findings.extend(lint_file(f))
    for line in all_findings:
        print(line)
    n = len(all_findings)
    print(f"lint: {n} finding(s) in {len(files)} files")
    raise SystemExit(min(n, 125))


if __name__ == "__main__":
    main()
