"""Cluster smoke gate (`make cluster-smoke`, folded into `make lint`).

Boots ONE writer and ONE stateless read replica — two real servers
(build_app), two independent S3 clients — over one fake-S3 bucket
(objstore/fake_s3.py, real HTTP + ETag/304 conditional GETs), and
asserts the scale-out contract end to end:

- writes acked by the writer are served EXACTLY by the replica once its
  manifest epoch catches up (`/api/v1/cluster/refresh` forces the probe
  instead of waiting out the watch interval);
- replica query responses carry the `X-Horaedb-Staleness-Ms` header and
  the EXPLAIN `cluster` verdict names the serving role + staleness token;
- a write POSTed to the replica forwards to the owning writer (200 with
  the writer's accounting; `horaedb_cluster_forwards_total` moves);
- `/api/v1/cluster/status` answers on both nodes with matching manifest
  epochs after catch-up, and the `horaedb_cluster_*` families render on
  /metrics from boot;
- fleet observability: the replica-forwarded write yields ONE stitched
  two-node trace (the writer's span subtree grafted under the replica's
  forward span, node-labeled, at `/debug/traces/{id}`); an offloaded
  read on the writer answers with a federated `fleet` EXPLAIN verdict
  naming both nodes; a forced telemetry tick on the writer peer-scrapes
  the replica and lands `instance="r1"`-labeled series in `_system`,
  answerable by a label-matched range query; `/debug/cluster` renders
  the per-node fleet view.

This is the end-to-end half tests/test_cluster.py can't give: two live
server processes' worth of boot paths, the HTTP router, the header
plumbing, and the real S3 wire protocol for the conditional-GET watch.

Run: JAX_PLATFORMS=cpu python tools/cluster_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def make_payload(metric: str, rows: list) -> bytes:
    from horaedb_tpu.pb import remote_write_pb2

    by_host: dict = {}
    for host, ts, v in rows:
        by_host.setdefault(host, []).append((ts, v))
    req = remote_write_pb2.WriteRequest()
    for host in sorted(by_host):
        series = req.timeseries.add()
        for k, v in ((b"__name__", metric.encode()), (b"host", host.encode())):
            lab = series.labels.add()
            lab.name = k
            lab.value = v
        for t, val in by_host[host]:
            s = series.samples.add()
            s.timestamp = t
            s.value = val
    return req.SerializeToString()


async def run(check) -> None:
    import aiohttp
    from aiohttp import web

    from horaedb_tpu.objstore.fake_s3 import FakeS3
    from horaedb_tpu.objstore.resilient import ResilientStore
    from horaedb_tpu.objstore.s3 import S3LikeConfig, S3LikeStore
    from horaedb_tpu.server.config import Config
    from horaedb_tpu.server.main import build_app

    creds = dict(region="us-east-1", key_id="smoke", key_secret="smoke")
    fake = FakeS3(bucket="cluster-smoke")
    s3_url = await fake.start()

    def bucket_store(name: str):
        # each "process" builds its own client over the ONE bucket, and
        # wraps it in the same ResilientStore the production boot uses
        return ResilientStore(
            S3LikeStore(S3LikeConfig(endpoint=s3_url, bucket="cluster-smoke",
                                     **creds)),
            name=name,
        )

    def cfg(port: int, node: str, role: str, peers: list,
            telemetry: "dict | None" = None) -> Config:
        return Config.from_dict({
            "port": port,
            "metric_engine": {
                "node_id": node,
                # smoke boxes: small + quiet
                "rules": {"enabled": False},
                "telemetry": telemetry or {"enabled": False},
                "storage": {"object_store": {
                    "data_dir": tempfile.mkdtemp(prefix=f"horaedb-cs-{node}-"),
                }},
                "cluster": {
                    "enabled": True,
                    "role": role,
                    "watch_interval": "500ms",
                    "self_url": f"http://127.0.0.1:{port}",
                    "peers": peers,
                },
            },
        })

    async def boot(config: Config, store):
        app = await build_app(config, store=store)
        # bounded shutdown: a peer router's keep-alive connection must
        # not stall cleanup for the 60s graceful-shutdown default
        runner = web.AppRunner(app, handler_cancellation=True,
                               shutdown_timeout=1.0)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", config.port)
        await site.start()
        return runner

    wport, rport = 28871, 28872
    wrunner = await boot(
        cfg(wport, "w1", "writer",
            [{"node": "r1", "url": f"http://127.0.0.1:{rport}",
              "role": "replica"}],
            # the writer is the fleet's telemetry origin: long intervals
            # so nothing ticks behind the smoke's back — the forced
            # scrape below drives both self-scrape and peer federation
            telemetry={"enabled": True, "scrape_interval": "1h",
                       "federation": {"enabled": True,
                                      "scrape_interval": "1h"}}),
        bucket_store("w1"),
    )
    rrunner = await boot(
        cfg(rport, "r1", "replica",
            [{"node": "w1", "url": f"http://127.0.0.1:{wport}",
              "role": "writer"}]),
        bucket_store("r1"),
    )
    wbase = f"http://127.0.0.1:{wport}"
    rbase = f"http://127.0.0.1:{rport}"
    try:
        async with aiohttp.ClientSession() as s:
            # ---- write on the writer, catch the replica up, read exact
            rows = [(f"h{i % 3}", 1000 + i * 500, float(i)) for i in range(12)]
            async with s.post(f"{wbase}/api/v1/write",
                              data=make_payload("cs_metric", rows)) as r:
                check(r.status == 200, f"writer accepts the write ({r.status})")
            async with s.post(f"{rbase}/api/v1/cluster/refresh") as r:
                body = await r.json()
                check(r.status == 200, "replica refresh answers 200")
                check(body["data"]["outcome"] in ("refreshed", "unchanged"),
                      f"refresh outcome sane ({body['data']})")
            # the writer booted before the replica, so its first probe
            # round marked r1 down; a forced refresh re-probes and
            # restores it to the routable set (offload + federation)
            async with s.post(f"{wbase}/api/v1/cluster/refresh") as r:
                check(r.status == 200, "writer refresh (re-probe) answers")

            async def query(base: str):
                async with s.post(f"{base}/api/v1/query", json={
                    "metric": "cs_metric", "start_ms": 0, "end_ms": 10**9,
                    "explain": 1,
                }) as r:
                    return r.status, await r.json(), dict(r.headers)

            ws, wbody, _ = await query(wbase)
            rs, rbody, rheaders = await query(rbase)
            check(ws == 200 and rs == 200, "both nodes answer the query")
            check(wbody["rows"] == len(rows), f"writer rows ({wbody['rows']})")
            check(
                {k: rbody[k] for k in ("rows", "tsid", "ts", "value")}
                == {k: wbody[k] for k in ("rows", "tsid", "ts", "value")},
                "replica serves BIT-IDENTICAL results after catch-up",
            )
            check("X-Horaedb-Staleness-Ms" in rheaders,
                  "replica response carries X-Horaedb-Staleness-Ms")
            verdict = rbody.get("explain", {}).get("cluster", {})
            check(verdict.get("role") == "replica"
                  and "staleness_ms" in verdict,
                  f"EXPLAIN cluster verdict on the replica ({verdict})")

            # ---- federated EXPLAIN: the writer's query offloaded to
            # the healthy replica merges BOTH nodes' fragments into one
            # `fleet` verdict (origin routed, replica executed)
            fleet = wbody.get("explain", {}).get("fleet", {})
            check(fleet.get("origin") == "w1",
                  f"fleet verdict names the routing origin ({fleet})")
            fleet_nodes = {f.get("node") for f in fleet.get("nodes", [])}
            check(fleet_nodes == {"w1", "r1"},
                  f"fleet verdict carries both nodes ({fleet_nodes})")
            check(fleet.get("partial") == 0,
                  f"no partial fragments on a healthy fleet ({fleet})")
            frag_stale = [f.get("staleness_ms", 0.0)
                          for f in fleet.get("nodes", [])]
            check(fleet.get("staleness_ms") == max(frag_stale, default=0.0),
                  f"fleet staleness is max-wins over fragments ({fleet})")

            # ---- status on both nodes: epochs match after catch-up
            async with s.get(f"{wbase}/api/v1/cluster/status") as r:
                wst = (await r.json())["data"]
            async with s.get(f"{rbase}/api/v1/cluster/status") as r:
                rst = (await r.json())["data"]
            check(wst["role"] == "writer" and rst["role"] == "replica",
                  f"status roles ({wst['role']}, {rst['role']})")
            check(wst["manifest_epoch"] == rst["manifest_epoch"],
                  f"manifest epochs match after catch-up "
                  f"({wst['manifest_epoch']} vs {rst['manifest_epoch']})")
            check(rst.get("stale") is False, "replica within max_staleness")

            # ---- a write POSTed to the REPLICA forwards to the writer
            fwd_rows = [("fwd", 50_000, 7.0)]
            async with s.post(f"{rbase}/api/v1/write",
                              data=make_payload("cs_metric", fwd_rows)) as r:
                body = await r.json()
                check(r.status == 200 and body.get("samples") == 1,
                      f"replica forwards the write ({r.status}, {body})")
                fwd_trace_id = r.headers.get("X-Horaedb-Trace-Id")
            check(bool(fwd_trace_id),
                  "forwarded write echoes X-Horaedb-Trace-Id")

            # ---- ONE stitched two-node trace: the writer's span
            # subtree shipped back in the bounded response header and
            # grafted (node-labeled) under the replica's forward span
            async with s.get(f"{rbase}/debug/traces/{fwd_trace_id}") as r:
                tr = await r.json()
                check(r.status == 200,
                      f"/debug/traces/{{id}} resolves the forwarded "
                      f"write's trace ({r.status})")

            def walk(span, out):
                # only non-`cluster_*` names prove a GRAFTED remote
                # span — the funnel's own client span also carries a
                # `node` attr (it names the target, not a shipped tree)
                if not isinstance(span, dict):
                    return
                node = (span.get("attrs") or {}).get("node")
                if node and not str(span.get("name", "")).startswith(
                        "cluster_"):
                    out.add(node)
                for child in span.get("children") or []:
                    walk(child, out)

            trace_nodes: set = set()
            walk(tr.get("root"), trace_nodes)
            check("w1" in trace_nodes,
                  f"stitched trace carries the writer's node-labeled "
                  f"remote spans ({trace_nodes or '{}'})")
            async with s.post(f"{rbase}/api/v1/cluster/refresh") as r:
                check(r.status == 200, "post-forward refresh")
            _, rbody2, _ = await query(rbase)
            check(rbody2["rows"] == len(rows) + 1,
                  f"forwarded row visible on the replica ({rbody2['rows']})")

            # ---- telemetry federation: a forced tick on the writer
            # self-scrapes AND peer-scrapes r1's registry snapshot,
            # landing `instance="r1"`-relabeled series in `_system`
            async with s.post(f"{wbase}/api/v1/telemetry/scrape") as r:
                data = (await r.json()).get("data") or {}
                check(r.status == 200 and data.get("written", 0) > 0,
                      f"forced tick lands the self-scrape "
                      f"({r.status}, {data.get('written')})")
                fed = data.get("federation") or {}
                check(fed.get("peers", {}).get("r1") == "ok",
                      f"federation sweep scraped the replica ({fed})")
                check(fed.get("written", 0) > 0,
                      f"federated series written ({fed.get('written')})")
                check(fed.get("dropped", 1) == 0,
                      f"no federated series dropped by the budget ({fed})")
                fed_ts_s = data["ts_ms"] / 1000.0
            fam = 'horaedb_cluster_manifest_epoch{instance="r1"}'
            async with s.get(
                f"{wbase}/api/v1/query_range",
                params={"query": fam, "start": fed_ts_s,
                        "end": fed_ts_s, "step": 15},
                # loop-guard header pins the query to the writer's OWN
                # engine — the federated rows live in ITS memstore
                headers={"X-Horaedb-Forwarded": "smoke"},
            ) as r:
                body = await r.json()
                res = ((body.get("data") or {}).get("result") or [])
                check(r.status == 200 and len(res) >= 1,
                      f"instance-matched range query answers over the "
                      f"federated series ({r.status}, {len(res)} series)")
                inst = (res[0].get("metric") or {}).get("instance") \
                    if res else None
                check(inst == "r1",
                      f"federated series carries instance=\"r1\" ({inst})")

            # ---- /debug/cluster: the operator's one-page fleet view
            async with s.get(f"{wbase}/debug/cluster") as r:
                fleet_view = (await r.json()).get("data") or {}
                check(r.status == 200
                      and fleet_view.get("self", {}).get("node") == "w1",
                      f"/debug/cluster answers with the self view "
                      f"({r.status})")
                check("r1" in (fleet_view.get("peers") or {}),
                      f"/debug/cluster lists the replica peer "
                      f"({list((fleet_view.get('peers') or {}))})")
                check(fleet_view.get("federation", {}).get("enabled")
                      is True,
                      f"/debug/cluster reports federation enabled "
                      f"({fleet_view.get('federation')})")
                check("load" in fleet_view.get("self", {}),
                      "/debug/cluster self view carries the load block")

            # ---- cluster metric families render on /metrics
            async with s.get(f"{rbase}/metrics") as r:
                text = await r.text()
            for fam in ("horaedb_cluster_replica_lag_seconds",
                        "horaedb_cluster_manifest_epoch",
                        "horaedb_cluster_refreshes_total",
                        "horaedb_cluster_forwards_total",
                        "horaedb_cluster_watch_errors_total"):
                check(fam in text, f"/metrics exposes {fam}")
            fwd_lines = [
                ln for ln in text.splitlines()
                if ln.startswith("horaedb_cluster_forwards_total")
                and 'kind="write"' in ln
            ]
            check(bool(fwd_lines)
                  and float(fwd_lines[0].rsplit(" ", 1)[1]) >= 1,
                  "write forward counted")
    finally:
        await rrunner.cleanup()
        await wrunner.cleanup()
        await fake.stop()


async def run_split(check) -> None:
    """Scatter-gather lanes: a regioned writer + one computing replica
    split one range-aggregate query (fleet EXPLAIN proves >= 2 computing
    nodes, partial-grid provenance, wire bytes at bucket scale), then
    the chaos rung — the replica dies and the same query still answers
    EXACTLY via the coordinator's local re-run."""
    import aiohttp
    from aiohttp import web

    from horaedb_tpu.objstore.fake_s3 import FakeS3
    from horaedb_tpu.objstore.resilient import ResilientStore
    from horaedb_tpu.objstore.s3 import S3LikeConfig, S3LikeStore
    from horaedb_tpu.server.config import Config
    from horaedb_tpu.server.main import build_app

    creds = dict(region="us-east-1", key_id="smoke", key_secret="smoke")
    fake = FakeS3(bucket="cluster-smoke-split")
    s3_url = await fake.start()

    def bucket_store(name: str):
        return ResilientStore(
            S3LikeStore(S3LikeConfig(endpoint=s3_url,
                                     bucket="cluster-smoke-split", **creds)),
            name=name,
        )

    def cfg(port: int, node: str, role: str, peers: list) -> Config:
        return Config.from_dict({
            "port": port,
            "metric_engine": {
                "node_id": node,
                "num_regions": 3,
                "rules": {"enabled": False},
                "telemetry": {"enabled": False},
                "storage": {"object_store": {
                    "data_dir": tempfile.mkdtemp(prefix=f"horaedb-cs-{node}-"),
                }},
                "cluster": {
                    "enabled": True,
                    "role": role,
                    "watch_interval": "500ms",
                    # health changes only through the explicit refreshes
                    # below — no background probe races the chaos rung
                    "probe_interval": "1h",
                    "self_url": f"http://127.0.0.1:{port}",
                    "peers": peers,
                },
            },
        })

    async def boot(config: Config, store):
        app = await build_app(config, store=store)
        runner = web.AppRunner(app, handler_cancellation=True,
                               shutdown_timeout=1.0)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", config.port)
        await site.start()
        return runner

    wport, rport = 28873, 28874
    wrunner = await boot(
        cfg(wport, "w1", "writer",
            [{"node": "r1", "url": f"http://127.0.0.1:{rport}",
              "role": "replica"}]),
        bucket_store("w1"),
    )
    rrunner = await boot(
        cfg(rport, "r1", "replica",
            [{"node": "w1", "url": f"http://127.0.0.1:{wport}",
              "role": "writer"}]),
        bucket_store("r1"),
    )
    wbase = f"http://127.0.0.1:{wport}"
    rbase = f"http://127.0.0.1:{rport}"
    replica_dead = False
    try:
        async with aiohttp.ClientSession() as s:
            # many series x many samples: the query aggregates row-scale
            # input into bucket-scale output, which is the whole point
            # of shipping partial grids instead of rows
            n_series, n_samples = 12, 400
            rows = [
                (f"h{i}", 1000 + j * 500, float(i * 1000 + j))
                for i in range(n_series) for j in range(n_samples)
            ]
            async with s.post(f"{wbase}/api/v1/write",
                              data=make_payload("sg_metric", rows)) as r:
                check(r.status == 200,
                      f"regioned writer accepts the write ({r.status})")
            async with s.post(f"{rbase}/api/v1/cluster/refresh") as r:
                check(r.status == 200, "split-lane replica catches up")
            async with s.post(f"{wbase}/api/v1/cluster/refresh") as r:
                check(r.status == 200, "split-lane writer re-probes r1")

            grid_q = {"metric": "sg_metric", "start_ms": 0,
                      "end_ms": 1000 + n_samples * 500,
                      "bucket_ms": 20_000, "explain": 1}

            async def grid_query(headers=None):
                async with s.post(f"{wbase}/api/v1/query", json=grid_q,
                                  headers=headers or {}) as r:
                    return r.status, await r.json()

            # the oracle: the loop-guard header pins single-node local
            # execution (a forwarded request never re-splits)
            bs, baseline = await grid_query({"X-Horaedb-Forwarded": "smoke"})
            check(bs == 200 and len(baseline["tsids"]) == n_series,
                  f"single-node baseline answers ({bs}, "
                  f"{len(baseline.get('tsids', []))} series)")

            ds, dist = await grid_query()
            check(ds == 200, f"split query answers ({ds})")
            same = all(
                dist.get(k) == baseline.get(k)
                for k in ("tsids", "buckets", "truncated", "mean", "count")
            )
            check(same, "split-computed grid is EXACTLY the single-node "
                        "answer (same JSON doubles, bit for bit)")
            fleet = dist.get("explain", {}).get("fleet", {})
            plan = fleet.get("distributed", {}).get("plan", {})
            check(len(plan) >= 2,
                  f"scatter plan spans >= 2 computing nodes ({plan})")
            computing = [f for f in fleet.get("nodes", [])
                         if f.get("regions")]
            check(len(computing) >= 2,
                  f"fleet EXPLAIN shows >= 2 nodes computing region "
                  f"shards ({fleet.get('nodes')})")
            check(fleet.get("partial") == 0,
                  f"healthy split: no partial fragments ({fleet})")
            remote = [f for f in fleet.get("nodes", [])
                      if f.get("node") == "r1"]
            check(bool(remote) and remote[0].get("wire_bytes", 0) > 0,
                  f"partial-grid provenance carries per-fragment wire "
                  f"bytes ({remote})")
            wire = fleet.get("wire_bytes", 0)
            row_bytes = len(rows) * 16  # (ts u64, value f64) per sample
            check(0 < wire < row_bytes / 4,
                  f"wire bytes are bucket-scale, far under row scale "
                  f"({wire} vs {row_bytes} row bytes)")

            # satellite family: the wire counter moved on both ends
            async with s.get(f"{wbase}/metrics") as r:
                wtext = await r.text()
            check("horaedb_cluster_wire_bytes_total" in wtext,
                  "/metrics exposes horaedb_cluster_wire_bytes_total")
            rx = [ln for ln in wtext.splitlines()
                  if ln.startswith("horaedb_cluster_wire_bytes_total")
                  and 'kind="partial_grid"' in ln and 'direction="rx"' in ln]
            check(bool(rx) and float(rx[0].rsplit(" ", 1)[1]) > 0,
                  "coordinator counted partial_grid rx wire bytes")
            async with s.get(f"{rbase}/metrics") as r:
                rtext = await r.text()
            tx = [ln for ln in rtext.splitlines()
                  if ln.startswith("horaedb_cluster_wire_bytes_total")
                  and 'kind="partial_grid"' in ln and 'direction="tx"' in ln]
            check(bool(tx) and float(tx[0].rsplit(" ", 1)[1]) > 0,
                  "replica counted partial_grid tx wire bytes")

            # ---- chaos rung: kill the replica, re-ask the SAME query.
            # The planned fragment dies on the wire; its region shards
            # re-run locally — exact answer, degraded parallelism.
            await rrunner.cleanup()
            replica_dead = True
            cs, chaos = await grid_query()
            check(cs == 200, f"query survives replica death ({cs})")
            same = all(
                chaos.get(k) == baseline.get(k)
                for k in ("tsids", "buckets", "truncated", "mean", "count")
            )
            check(same, "post-death answer is EXACT via local re-run")
            cfleet = chaos.get("explain", {}).get("fleet", {})
            check(cfleet.get("partial", 0) >= 1,
                  f"dead fragment counted in fleet partial ({cfleet})")
    finally:
        if not replica_dead:
            await rrunner.cleanup()
        await wrunner.cleanup()
        await fake.stop()


def main() -> int:
    failures: list[str] = []

    def check(ok: bool, msg: str) -> None:
        tag = "ok" if ok else "FAIL"
        print(f"[cluster-smoke] {tag}: {msg}")
        if not ok:
            failures.append(msg)

    asyncio.run(run(check))
    asyncio.run(run_split(check))
    if failures:
        print(f"[cluster-smoke] {len(failures)} failure(s)")
        return 1
    print("[cluster-smoke] all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
