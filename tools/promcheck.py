"""Prometheus text exposition format validator (stdlib only).

`make smoke-metrics` pipes the live server's /metrics body through this
and fails on any violation — the render path in server/metrics.py is the
contract every scraper depends on, and a malformed line (bare metric with
no `# TYPE`, an unescaped quote in a label value, a non-cumulative
histogram) breaks collectors silently or, worse, mis-counts.

Checks, per https://prometheus.io/docs/instrumenting/exposition_formats/:
- line grammar: comments (`# HELP` / `# TYPE` / plain `#`), sample lines
  `name{labels} value [timestamp]`, blank lines;
- metric and label names match the allowed charsets;
- label values escape backslash, double-quote, and newline;
- `# TYPE` appears at most once per family, BEFORE its samples, with a
  valid type; every sample belongs to a family with an explicit TYPE
  (untyped families must say `untyped`);
- sample values parse as floats (`+Inf`/`-Inf`/`NaN` accepted);
- histograms: `le` bounds sorted, bucket counts cumulative
  (nondecreasing), a `+Inf` bucket present per child, and `_count` ==
  the `+Inf` bucket;
- no duplicate sample (same name + label set);
- no reserved scrape-time target label (`instance`) exposed by the
  process itself — that axis belongs to the self-scrape collector and
  the fleet telemetry federation, which stamp it at write time (both
  exposition modes enforce this).

OpenMetrics mode (`validate_openmetrics`, auto-detected by a `# EOF`
line or forced with --openmetrics): the exposition served under
`Accept: application/openmetrics-text` —
- the body MUST end with exactly one `# EOF` line (a truncated scrape is
  indistinguishable from a complete one without it);
- counter samples spell `<family>_total` with `# TYPE <family> counter`
  (the family name drops the suffix);
- exemplars (` # {labels} value [timestamp]`) are allowed ONLY on
  histogram `_bucket` samples and counter `_total` samples — an exemplar
  on a gauge/unknown/`_sum`/`_count` line is a violation;
- exemplar label sets parse with the escaped-label grammar and stay
  within the spec's 128-rune budget; exemplar values parse as floats.

Usage:
    python tools/promcheck.py [file]      # file or stdin
    python tools/promcheck.py --openmetrics [file]
    from tools.promcheck import validate, validate_openmetrics
"""

from __future__ import annotations

import re
import sys

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# a sample line: name, optional {labels}, value, optional timestamp
SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)"
    r"(?:\s+(?P<ts>-?\d+))?\s*$"
)
LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\["\\n])*)"')
VALID_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")
SUMMARY_SUFFIXES = ("_sum", "_count")
# labels a scraper assigns at WRITE time, reserved off the exposition
# surface: the self-scrape collector stamps `instance="<self>"` on its
# own stored series and the telemetry federation stamps the PEER's node
# id on pulled series — a family exposing its own `instance` label
# would collide with (and lie about) that axis. The Prometheus target-
# label convention, enforced here for both exposition modes.
RESERVED_EXPOSITION_LABELS = {"instance"}


def _parse_value(s: str) -> float | None:
    if s in ("+Inf", "Inf"):
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    if s == "NaN":
        return float("nan")
    try:
        return float(s)
    except ValueError:
        return None


def _parse_labels(raw: str, err) -> tuple[tuple[str, str], ...] | None:
    """Parse `a="b",c="d"` strictly: every byte must be consumed by
    well-formed, properly escaped pairs."""
    out = []
    pos = 0
    while pos < len(raw):
        m = LABEL_RE.match(raw, pos)
        if m is None:
            err(f"malformed label pair at {raw[pos:pos + 30]!r}")
            return None
        out.append((m.group(1), m.group(2)))
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                err(f"expected ',' between label pairs at {raw[pos:]!r}")
                return None
            pos += 1
    return tuple(out)


def _base_family(name: str, typed: dict) -> tuple[str, str | None]:
    """Resolve a sample name to its declared family: histogram/summary
    samples use the family name + a suffix."""
    if name in typed:
        return name, typed[name]
    for suf in HISTOGRAM_SUFFIXES:
        base = name[: -len(suf)] if name.endswith(suf) else None
        if base and typed.get(base) in ("histogram", "summary"):
            return base, typed[base]
    return name, None


def validate(text: str) -> list[str]:
    errors: list[str] = []
    typed: dict[str, str] = {}
    first_sample_line: dict[str, int] = {}
    seen_samples: set[tuple] = set()
    # family -> child label key (minus le) -> list of (le, count)
    buckets: dict[str, dict[tuple, list[tuple[float, float]]]] = {}
    counts: dict[str, dict[tuple, float]] = {}

    for i, line in enumerate(text.split("\n"), 1):
        def err(msg: str, i=i) -> None:
            errors.append(f"line {i}: {msg}")

        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] in ("HELP", "TYPE"):
                if len(parts) < 3:
                    err(f"# {parts[1]} without a metric name")
                    continue
                name = parts[2]
                if not METRIC_NAME_RE.match(name):
                    err(f"invalid metric name in {parts[1]}: {name!r}")
                    continue
                if parts[1] == "TYPE":
                    t = parts[3].strip() if len(parts) > 3 else ""
                    if t not in VALID_TYPES:
                        err(f"invalid TYPE {t!r} for {name}")
                    if name in typed:
                        err(f"duplicate # TYPE for {name}")
                    if name in first_sample_line:
                        err(f"# TYPE for {name} after its samples "
                            f"(first at line {first_sample_line[name]})")
                    typed[name] = t
            continue
        m = SAMPLE_RE.match(line)
        if m is None:
            err(f"unparseable sample line: {line[:60]!r}")
            continue
        name = m.group("name")
        value = _parse_value(m.group("value"))
        if value is None:
            err(f"unparseable value {m.group('value')!r} for {name}")
        raw_labels = m.group("labels")
        labels = _parse_labels(raw_labels, err) if raw_labels else ()
        if labels is None:
            continue
        for k, _v in labels:
            if not LABEL_NAME_RE.match(k):
                err(f"invalid label name {k!r} on {name}")
            elif k in RESERVED_EXPOSITION_LABELS:
                err(f"reserved label {k!r} on {name}: scrape-time "
                    "target labels (the federation's instance axis) "
                    "must not be exposed by the process itself")
        key = (name, labels)
        if key in seen_samples:
            err(f"duplicate sample {name}{dict(labels)}")
        seen_samples.add(key)

        family, ftype = _base_family(name, typed)
        first_sample_line.setdefault(family, i)
        if ftype is None:
            err(f"sample {name!r} has no preceding # TYPE "
                f"(bare metric line)")
            continue
        if ftype == "histogram" and value is not None:
            child = tuple(p for p in labels if p[0] != "le")
            if name.endswith("_bucket"):
                le = dict(labels).get("le")
                if le is None:
                    err(f"{name} bucket without an le label")
                    continue
                b = _parse_value(le)
                if b is None:
                    err(f"{name}: unparseable le {le!r}")
                    continue
                buckets.setdefault(family, {}).setdefault(child, []).append(
                    (b, value)
                )
            elif name.endswith("_count"):
                counts.setdefault(family, {})[child] = value

    for family, children in buckets.items():
        for child, rows in children.items():
            lbl = dict(child)
            les = [b for b, _ in rows]
            if les != sorted(les):
                errors.append(f"{family}{lbl}: le bounds not sorted")
            cum = [c for _, c in rows]
            if any(later < earlier for earlier, later in zip(cum, cum[1:])):
                errors.append(f"{family}{lbl}: bucket counts not cumulative")
            if not les or les[-1] != float("inf"):
                errors.append(f"{family}{lbl}: missing +Inf bucket")
            else:
                total = counts.get(family, {}).get(child)
                if total is not None and total != cum[-1]:
                    errors.append(
                        f"{family}{lbl}: _count {total} != +Inf bucket "
                        f"{cum[-1]}"
                    )
    return errors


# ---------------------------------------------------------------------------
# OpenMetrics mode
# ---------------------------------------------------------------------------

# sample line with an optional exemplar tail: the base grammar plus
# ` # {labels} value [timestamp]`
OM_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*?)\})?"
    r"\s+(?P<value>\S+)"
    r"(?:\s+(?P<ts>-?\d+(?:\.\d+)?))?"
    r"(?:\s+#\s+\{(?P<exlabels>.*)\}\s+(?P<exvalue>\S+)"
    r"(?:\s+(?P<exts>-?\d+(?:\.\d+)?))?)?\s*$"
)
OM_EXEMPLAR_TYPES = ("histogram", "counter")
OM_EXEMPLAR_RUNE_BUDGET = 128


def _om_family(name: str, typed: dict) -> tuple[str, str | None]:
    """Resolve an OpenMetrics sample name to its declared family:
    counters drop `_total`, histograms drop `_bucket`/`_sum`/`_count`."""
    if name in typed:
        return name, typed[name]
    for suf in ("_total",) + HISTOGRAM_SUFFIXES:
        base = name[: -len(suf)] if name.endswith(suf) else None
        if base and base in typed:
            return base, typed[base]
    return name, None


def validate_openmetrics(text: str) -> list[str]:
    """OpenMetrics-specific checks (module docstring) PLUS the
    structural checks the classic validator enforces where the grammars
    agree: no duplicate samples, histogram le bounds sorted, bucket
    counts cumulative, a +Inf bucket per child, `_count` == +Inf."""
    errors: list[str] = []
    typed: dict[str, str] = {}
    seen_samples: set[tuple] = set()
    # family -> child label key (minus le) -> [(le, count)], and _count
    buckets: dict[str, dict[tuple, list[tuple[float, float]]]] = {}
    counts: dict[str, dict[tuple, float]] = {}
    lines = text.split("\n")
    # -- the EOF contract ----------------------------------------------------
    stripped = [ln for ln in lines if ln.strip()]
    if not stripped or stripped[-1].strip() != "# EOF":
        errors.append("missing `# EOF` terminator as the final line")
    eof_count = sum(1 for ln in stripped if ln.strip() == "# EOF")
    if eof_count > 1:
        errors.append(f"{eof_count} `# EOF` lines (must be exactly one, "
                      "at the end)")
    for i, line in enumerate(lines, 1):
        def err(msg: str, i=i) -> None:
            errors.append(f"line {i}: {msg}")

        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                name = parts[2]
                t = parts[3].strip() if len(parts) > 3 else ""
                if name in typed:
                    err(f"duplicate # TYPE for {name}")
                typed[name] = t
                if t == "counter" and name.endswith("_total"):
                    err(f"counter family {name!r} must drop the _total "
                        "suffix (the sample keeps it)")
            continue
        m = OM_SAMPLE_RE.match(line)
        if m is None:
            err(f"unparseable sample line: {line[:60]!r}")
            continue
        name = m.group("name")
        value = _parse_value(m.group("value"))
        if value is None:
            err(f"unparseable value {m.group('value')!r} for {name}")
        labels = (_parse_labels(m.group("labels"), err)
                  if m.group("labels") else ())
        if labels is not None:
            for k, _v in labels:
                if k in RESERVED_EXPOSITION_LABELS:
                    err(f"reserved label {k!r} on {name}: scrape-time "
                        "target labels (the federation's instance axis) "
                        "must not be exposed by the process itself")
            skey = (name, labels)
            if skey in seen_samples:
                err(f"duplicate sample {name}{dict(labels)}")
            seen_samples.add(skey)
        family, ftype = _om_family(name, typed)
        if ftype is None:
            err(f"sample {name!r} has no preceding # TYPE")
            continue
        if ftype == "counter" and name != f"{family}_total":
            err(f"counter sample {name!r} must be spelled "
                f"{family}_total")
        if ftype == "histogram" and labels is not None and value is not None:
            child = tuple(p for p in labels if p[0] != "le")
            if name.endswith("_bucket"):
                le = dict(labels).get("le")
                b = _parse_value(le) if le is not None else None
                if b is None:
                    err(f"{name}: missing/unparseable le {le!r}")
                else:
                    buckets.setdefault(family, {}).setdefault(
                        child, []).append((b, value))
            elif name.endswith("_count"):
                counts.setdefault(family, {})[child] = value
        if m.group("exlabels") is None:
            continue
        # -- exemplar checks -------------------------------------------------
        ok_target = (
            (ftype == "histogram" and name.endswith("_bucket"))
            or (ftype == "counter" and name.endswith("_total"))
        )
        if not ok_target:
            err(f"exemplar on {name!r} ({ftype}): exemplars are only "
                "allowed on histogram _bucket and counter _total samples")
        pairs = _parse_labels(m.group("exlabels"), err)
        if pairs is not None:
            runes = sum(len(k) + len(v) for k, v in pairs)
            if runes > OM_EXEMPLAR_RUNE_BUDGET:
                err(f"exemplar labelset on {name!r} is {runes} runes "
                    f"(budget {OM_EXEMPLAR_RUNE_BUDGET})")
        if _parse_value(m.group("exvalue")) is None:
            err(f"unparseable exemplar value {m.group('exvalue')!r} "
                f"on {name}")
    # structural histogram checks (identical contract to the classic
    # validator: sorted le, cumulative counts, +Inf present, _count ==
    # the +Inf bucket)
    for family, children in buckets.items():
        for child, rows in children.items():
            lbl = dict(child)
            les = [b for b, _ in rows]
            if les != sorted(les):
                errors.append(f"{family}{lbl}: le bounds not sorted")
            cum = [c for _, c in rows]
            if any(later < earlier
                   for earlier, later in zip(cum, cum[1:])):
                errors.append(f"{family}{lbl}: bucket counts not "
                              f"cumulative")
            if not les or les[-1] != float("inf"):
                errors.append(f"{family}{lbl}: missing +Inf bucket")
            else:
                total = counts.get(family, {}).get(child)
                if total is not None and total != cum[-1]:
                    errors.append(
                        f"{family}{lbl}: _count {total} != +Inf bucket "
                        f"{cum[-1]}"
                    )
    return errors


def main() -> None:
    args = [a for a in sys.argv[1:] if a != "--openmetrics"]
    force_om = len(args) != len(sys.argv) - 1
    if args:
        with open(args[0], encoding="utf-8") as f:
            text = f.read()
    else:
        text = sys.stdin.read()
    openmetrics = force_om or any(
        ln.strip() == "# EOF" for ln in text.split("\n")
    )
    errors = validate_openmetrics(text) if openmetrics else validate(text)
    for e in errors:
        print(e)
    mode = "openmetrics" if openmetrics else "text"
    print(f"promcheck[{mode}]: {len(errors)} violation(s)")
    raise SystemExit(min(len(errors), 125))


if __name__ == "__main__":
    main()
