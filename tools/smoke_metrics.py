"""Observability smoke gate (`make smoke-metrics`).

Boots the real server (build_app) against the in-process fake S3 object
store, pushes one remote-write batch, runs one raw and one downsample
query, then fails loudly unless:

- every /metrics line passes the Prometheus text-format validator
  (tools/promcheck.py);
- the expected metric families are present (per-stage scan histograms,
  ingest/flush/storage/compaction families, HTTP latency, and the
  horaedb_jit_* compile-telemetry families with at least one labeled
  kernel);
- the query response echoed an X-Horaedb-Trace-Id whose span tree
  round-trips through GET /debug/traces/{id};
- a `?explain=1` downsample query returns a plan with the dispatcher
  impl, per-lane stage seconds, and a compile/steady split;
- GET /debug/kernels serves the instrumented-kernel catalog and
  GET /debug/slowlog returns the recorded query.

This is the end-to-end check the unit tests can't give: the families are
registered at import time across six modules, and only a live request
drives them all through one process.

Run: python tools/smoke_metrics.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from promcheck import validate, validate_openmetrics  # noqa: E402

REQUIRED_FAMILIES = (
    "horaedb_scan_stage_seconds_bucket",
    'horaedb_scan_stage_seconds_bucket{stage="io_decode"',
    'horaedb_scan_stage_seconds_bucket{stage="transfer"',
    'horaedb_scan_stage_seconds_bucket{stage="kernel"',
    'horaedb_scan_stage_seconds_bucket{stage="host_prep"',
    "horaedb_scan_path_total",
    "horaedb_agg_impl_total",
    "horaedb_remote_write_samples_total",
    "horaedb_remote_write_batch_samples_bucket",
    "horaedb_ingest_parse_seconds_bucket",
    "horaedb_storage_write_seconds_bucket",
    "horaedb_storage_scan_seconds_bucket",
    "horaedb_sst_bytes_bucket",
    "horaedb_compaction_queue_depth",
    "horaedb_compaction_seconds_bucket",
    "horaedb_http_request_seconds_bucket",
    "horaedb_ingest_flush_seconds_bucket",
    # overlapped ingest->flush pipeline (engine/flush_executor.py): the
    # bulk write below crosses the buffer threshold, so a background
    # flush must have run and fed the stage histograms
    "horaedb_flush_queue_depth",
    "horaedb_ingest_stall_seconds_bucket",
    # (table renders before stage in this family's label set)
    "horaedb_flush_stage_seconds_bucket",
    'stage="drain"',
    'stage="encode"',
    'stage="upload"',
    "horaedb_flush_failures_total",
    "horaedb_flush_overlap_ratio_bucket",
    "horaedb_uptime_seconds",
    # device-side compile telemetry (common/xprof.py): the counter must
    # carry at least one real labeled kernel after the queries ran
    "horaedb_jit_compile_total",
    'horaedb_jit_compile_total{kernel="',
    "horaedb_jit_compile_seconds_bucket",
    "horaedb_jit_cache_entries",
    'horaedb_scan_stage_seconds_bucket{stage="compile"',
    "horaedb_slowlog_records_total",
    # object-store resilience layer (objstore/resilient.py): the server
    # wraps its store in a ResilientStore at boot, so the families must
    # render with per-verb children from the manifest/boot traffic alone
    "horaedb_objstore_attempts_total",
    'horaedb_objstore_attempts_total{op="put",result="ok"',
    'horaedb_objstore_attempts_total{op="get",result="ok"',
    "horaedb_objstore_retries_total",
    "horaedb_objstore_gave_up_total",
    "horaedb_objstore_breaker_state",
    "horaedb_orphan_ssts_gc_total",
    # dirty-traffic hardening families: all must render from boot (the
    # engine/storage pre-register their children), counters move only
    # when late/deleted/over-limit traffic arrives
    "horaedb_series_cardinality",
    "horaedb_late_samples_total",
    "horaedb_tombstones_applied_total",
    'horaedb_tombstones_applied_total{table="metrics/data",context="scan"',
    "horaedb_tombstones_created_total",
    "horaedb_cardinality_rejected_samples_total",
    "horaedb_cardinality_rejected_series_total",
    "horaedb_cardinality_limited_requests_total",
    # query-path admission control (server/admission.py): gauges +
    # shed/deadline counters render from boot (children pre-registered),
    # and queue wait is a first-class scan stage
    "horaedb_query_inflight",
    "horaedb_query_queued",
    "horaedb_query_shed_total",
    'horaedb_query_shed_total{reason="queue_full"',
    'horaedb_query_shed_total{reason="stall"',
    'horaedb_query_shed_total{reason="client_disconnect"',
    "horaedb_query_deadline_exceeded_total",
    'horaedb_scan_stage_seconds_bucket{stage="queue_wait"',
    # serving tier (horaedb_tpu/serving): all families render from boot
    # (children pre-registered); the repeated-query flow below moves the
    # hit/miss counters and the write moves the invalidation counter
    "horaedb_serving_cache_requests_total",
    'horaedb_serving_cache_requests_total{result="hit"',
    'horaedb_serving_cache_requests_total{result="miss"',
    'horaedb_serving_cache_requests_total{result="bypass"',
    "horaedb_serving_cache_bytes",
    "horaedb_serving_cache_entries",
    "horaedb_serving_cache_evictions_total",
    "horaedb_serving_invalidations_total",
    'horaedb_serving_invalidations_total{reason="flush"',
    'horaedb_serving_invalidations_total{reason="compact"',
    'horaedb_serving_invalidations_total{reason="delete"',
    "horaedb_serving_rollups_built_total",
    "horaedb_serving_rollup_substitutions_total",
    "horaedb_serving_rollup_rows_total",
    "horaedb_serving_resident_bytes",
    "horaedb_serving_resident_blocks",
    "horaedb_serving_residency_total",
    # streaming rule engine (horaedb_tpu/rules): families render from
    # boot (zero states pre-registered); the rule flow below moves the
    # eval/tick/transition counters
    "horaedb_rules_registered",
    'horaedb_rules_registered{kind="recording"',
    'horaedb_rules_registered{kind="alert"',
    "horaedb_rules_eval_seconds_bucket",
    "horaedb_rules_evals_total",
    'horaedb_rules_evals_total{kind="recording",result="ok"',
    "horaedb_rules_dirty_skips_total",
    "horaedb_rules_ticks_total",
    "horaedb_rules_eval_lag_seconds",
    "horaedb_rules_samples_written_total",
    "horaedb_rules_write_degraded_total",
    "horaedb_rules_alert_transitions_total",
    'horaedb_rules_alert_transitions_total{transition="firing"',
    "horaedb_rules_alerts_active",
    # self-telemetry pipeline (horaedb_tpu/telemetry): the per-tenant
    # usage funnel's families carry the default tenant from the traffic
    # above and `_system` from the forced self-scrape tick; the
    # telemetry meta-families render from boot
    "horaedb_tenant_rows_ingested_total",
    'horaedb_tenant_rows_ingested_total{tenant="default"',
    'horaedb_tenant_rows_ingested_total{tenant="_system"',
    "horaedb_tenant_samples_rejected_total",
    "horaedb_tenant_bytes_scanned_total",
    'horaedb_tenant_bytes_scanned_total{tenant="default"',
    "horaedb_tenant_queue_wait_seconds_total",
    "horaedb_tenant_queries_total",
    "horaedb_tenant_sheds_total",
    "horaedb_tenant_deadline_exceeded_total",
    "horaedb_telemetry_ticks_total",
    'horaedb_telemetry_ticks_total{result="ok"',
    "horaedb_telemetry_samples_total",
    "horaedb_telemetry_series",
    "horaedb_telemetry_dropped_series_total",
    "horaedb_telemetry_scrape_seconds_bucket",
    # query batcher (server/batching.py): every family renders from boot
    # (pre-registered children); the same-shape panel burst below moves
    # the batched counter and the group-size/pad-waste histograms
    "horaedb_batch_group_size_bucket",
    "horaedb_batch_pad_waste_ratio_bucket",
    "horaedb_batch_window_wait_seconds_bucket",
    "horaedb_batch_queries_total",
    'horaedb_batch_queries_total{mode="batched"',
    'horaedb_batch_queries_total{mode="solo_lone"',
    'horaedb_batch_queries_total{mode="solo_deadline"',
    'horaedb_batch_queries_total{mode="solo_off"',
    "horaedb_batch_launches_total",
    'horaedb_scan_stage_seconds_bucket{stage="batch_window"',
    # memory observatory (common/memtrace.py + common/bytebudget.py):
    # lineage counters pre-register every (stage, kind) child and the
    # pool registry pre-registers all five byte-budgeted caches, so
    # every family renders the zero state from boot
    "horaedb_mem_bytes_total",
    'horaedb_mem_bytes_total{stage="host_prep",kind="copy"',
    'horaedb_mem_bytes_total{stage="materialize",kind="view"',
    "horaedb_mem_events_total",
    'horaedb_mem_events_total{stage="decode",kind="alloc"',
    "horaedb_mem_device_staging_bytes_total",
    "horaedb_pool_bytes",
    'horaedb_pool_bytes{pool="scan"',
    'horaedb_pool_bytes{pool="sidecar"',
    'horaedb_pool_bytes{pool="result"',
    'horaedb_pool_bytes{pool="residency"',
    'horaedb_pool_bytes{pool="rollup"',
    "horaedb_pool_entries",
    "horaedb_pool_capacity_bytes",
    'horaedb_pool_capacity_bytes{pool="result"',
    "horaedb_pool_evictions_total",
    'horaedb_pool_evictions_total{pool="scan"',
)


def make_payload() -> bytes:
    from horaedb_tpu.pb import remote_write_pb2

    req = remote_write_pb2.WriteRequest()
    for host, samples in (("a", [(1000, 1.5), (2000, 2.5)]),
                          ("b", [(1500, 7.0)])):
        ts = req.timeseries.add()
        for k, v in ((b"__name__", b"smoke_cpu"), (b"host", host.encode())):
            lab = ts.labels.add()
            lab.name = k
            lab.value = v
        for t, v in samples:
            s = ts.samples.add()
            s.timestamp = t
            s.value = v
    return req.SerializeToString()


def make_payload_named(metric: str) -> bytes:
    """One-sample payload under a FRESH metric name, so ingest cannot be
    served from caches — registration must touch the object store."""
    from horaedb_tpu.pb import remote_write_pb2

    req = remote_write_pb2.WriteRequest()
    ts = req.timeseries.add()
    for k, v in ((b"__name__", metric.encode()), (b"host", b"shed")):
        lab = ts.labels.add()
        lab.name = k
        lab.value = v
    s = ts.samples.add()
    s.timestamp = 1000
    s.value = 1.0
    return req.SerializeToString()


def make_bulk_payload(n_series: int, n_samples: int) -> bytes:
    """Enough rows to cross the ingest buffer threshold, so at least one
    BACKGROUND flush runs and the pipeline stage histograms get fed."""
    from horaedb_tpu.pb import remote_write_pb2

    req = remote_write_pb2.WriteRequest()
    for s in range(n_series):
        ts = req.timeseries.add()
        for k, v in ((b"__name__", b"smoke_bulk"),
                     (b"host", f"bulk-{s:03d}".encode())):
            lab = ts.labels.add()
            lab.name = k
            lab.value = v
        for i in range(n_samples):
            smp = ts.samples.add()
            smp.timestamp = 1000 + i * 1000
            smp.value = float(s + i)
    return req.SerializeToString()


async def run() -> int:
    import aiohttp
    from aiohttp import web

    from horaedb_tpu.objstore.fake_s3 import FakeS3
    from horaedb_tpu.server.config import Config
    from horaedb_tpu.server.main import build_app

    failures: list[str] = []

    def check(ok: bool, msg: str) -> None:
        print(("ok   " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    import tempfile

    scratch = tempfile.mkdtemp(prefix="horaedb-smoke-")
    fake = FakeS3()
    url = await fake.start()
    cfg = Config.from_dict({
        "metric_engine": {
            "storage": {"object_store": {
                "type": "S3Like", "endpoint": url, "bucket": fake.bucket,
                "region": "smoke", "key_id": "smoke", "key_secret": "smoke",
                # fresh local scratch: the slowlog spool must start empty so
                # "the recorded request comes back" proves THIS process
                # wrote it
                "data_dir": scratch,
            }},
            # small buffer + explicit executor sizing: the bulk write must
            # cross the threshold and take the BACKGROUND flush path
            "ingest_buffer_rows": 64,
            "ingest": {"flush_workers": 2, "flush_queue_max": 4},
            # series-cardinality limit ([metric_engine.limits]): high
            # enough for the base traffic (~44 series), crossed by the
            # card_fill flood below so the partial-accept 503 fires
            "limits": {"max_series": 60},
        },
    })
    app = await build_app(cfg)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{base}/api/v1/write",
                              data=make_payload()) as r:
                body = await r.json()
                check(r.status == 200 and body.get("samples") == 3,
                      f"remote-write accepted: {body}")
            # bulk write: 40 series x 4 samples = 160 rows vs the 64-row
            # buffer -> the threshold seals a memtable to the background
            # flush executor (queue depth / stall / stage families)
            async with s.post(f"{base}/api/v1/write",
                              data=make_bulk_payload(40, 4)) as r:
                body = await r.json()
                check(r.status == 200 and body.get("samples") == 160,
                      f"bulk remote-write accepted: {body}")
            async with s.post(f"{base}/api/v1/query", json={
                "metric": "smoke_bulk", "start_ms": 0, "end_ms": 10_000,
            }) as r:
                body = await r.json()
                check(r.status == 200 and body.get("rows") == 160,
                      f"bulk rows visible after background flush: {body}")
            async with s.post(f"{base}/api/v1/query", json={
                "metric": "smoke_cpu", "start_ms": 0, "end_ms": 10_000,
            }) as r:
                body = await r.json()
                trace_id = r.headers.get("X-Horaedb-Trace-Id", "")
                check(r.status == 200 and body.get("rows") == 3,
                      f"raw query answered: {body}")
                check(bool(trace_id), "query echoed X-Horaedb-Trace-Id")
            # ---- per-tenant usage metering: the ledger must match the
            # requests THIS smoke actually issued so far — 3 + 160
            # ingested samples, exactly 2 admitted queries, and a real
            # bytes-scanned figure from the SST reads above
            async with s.get(f"{base}/api/v1/usage?tenant=default"
                             f"&window=5m") as r:
                u = ((await r.json()).get("data") or {})
                boot = u.get("since_boot") or {}
                check(r.status == 200 and boot.get("rows_ingested") == 163,
                      f"usage rows_ingested matches issued writes "
                      f"(3+160): {boot}")
                check(boot.get("queries") == 2,
                      f"usage queries matches admitted queries: {boot}")
                check(boot.get("bytes_scanned", 0) > 0,
                      f"usage bytes_scanned moved: {boot}")
                win = u.get("window") or {}
                check(win.get("rows_ingested") == 163,
                      f"windowed usage agrees since boot < window: {win}")
            async with s.post(f"{base}/api/v1/query?explain=1", json={
                "metric": "smoke_cpu", "start_ms": 0, "end_ms": 4000,
                "bucket_ms": 2000,
            }) as r:
                body = await r.json()
                check(r.status == 200, "downsample query answered")
                plan = body.get("explain") or {}
                check(plan.get("mode") == "downsample"
                      and bool(plan.get("agg_impl")),
                      f"explain carries the dispatcher impl: "
                      f"{plan.get('agg_impl')!r}")
                lanes = plan.get("lanes_s") or {}
                check(
                    {"io", "transfer", "kernel", "compile", "host"}
                    <= set(lanes),
                    f"explain carries per-lane stage seconds: {lanes}",
                )
                check("compile_s" in plan and "steady_s" in plan
                      and plan.get("bound") is not None,
                      f"explain carries the compile/steady split + bound: "
                      f"compile_s={plan.get('compile_s')} "
                      f"steady_s={plan.get('steady_s')} "
                      f"bound={plan.get('bound')}")
                adm = plan.get("admission") or {}
                check(adm.get("admitted") is True
                      and "queue_wait_s" in adm,
                      f"explain carries the admission verdict: {adm}")
            # ---- serving tier: a repeated query flips the EXPLAIN cache
            # verdict miss -> hit; a write to the table invalidates so the
            # third run is a miss again (the result cache can never serve
            # across a data change)
            srv_q = {"metric": "smoke_cpu", "start_ms": 0, "end_ms": 8000,
                     "bucket_ms": 1000}
            verdicts = []
            for step in ("first", "repeat"):
                async with s.post(f"{base}/api/v1/query?explain=1",
                                  json=srv_q) as r:
                    body = await r.json()
                    check(r.status == 200, f"serving {step} query answered")
                    verdicts.append(
                        (body.get("explain") or {}).get("serving") or {}
                    )
            check(verdicts[0].get("cache") == "miss",
                  f"first serving query is a cache miss: {verdicts[0]}")
            check(verdicts[1].get("cache") == "hit",
                  f"repeated serving query is a cache hit: {verdicts[1]}")
            async with s.post(f"{base}/api/v1/write",
                              data=make_payload()) as r:
                check(r.status == 200, "invalidating write accepted")
            async with s.post(f"{base}/api/v1/query?explain=1",
                              json=srv_q) as r:
                body = await r.json()
                srv = (body.get("explain") or {}).get("serving") or {}
                check(srv.get("cache") == "miss",
                      f"post-write re-query is a miss again (invalidation "
                      f"funnel fired): {srv}")
            # ---- query batcher: a concurrent burst of same-shape panels
            # (distinct host filters -> distinct cache keys, all misses)
            # must coalesce into a stacked launch (EXPLAIN batched_with >
            # 1), while a lone query afterwards stays batched_with=1 with
            # ZERO window hold — the 1-client p50 contract
            async def one_panel(host: str) -> dict:
                async with s.post(f"{base}/api/v1/query?explain=1", json={
                    "metric": "smoke_bulk", "start_ms": 0,
                    "end_ms": 4000, "bucket_ms": 1000,
                    "filters": {"host": host},
                }) as r:
                    body = await r.json()
                    return ((body.get("explain") or {}).get("batching")
                            or {})
            burst = await asyncio.gather(
                *(one_panel(f"bulk-{i:03d}") for i in range(8))
            )
            widths = [b.get("batched_with") for b in burst]
            check(any(w and w > 1 for w in widths),
                  f"concurrent same-shape burst coalesced "
                  f"(batched_with mix {widths})")
            coalesced = next(b for b in burst
                             if (b.get("batched_with") or 0) > 1)
            check(coalesced.get("shape_class") is not None,
                  f"EXPLAIN carries the shape class: {coalesced}")
            check("pad_waste_pct" in coalesced,
                  f"EXPLAIN carries pad waste: {coalesced}")
            lone = await one_panel("bulk-009")
            check(lone.get("batched_with") == 1
                  and lone.get("window_wait_s") == 0.0,
                  f"lone query stays batched_with=1 with no window "
                  f"penalty: {lone}")
            # ---- streaming rule engine: register a recording rule + an
            # alert rule over HTTP, drive a threshold-crossing write,
            # force a tick, and assert the rule series is queryable, the
            # alert reached firing, and the families moved
            from horaedb_tpu.common.time_ext import now_ms as _now_ms

            now = _now_ms()
            r_reg = {
                "kind": "recording", "name": "smoke:sig:sum",
                "expr": "sum by (host) (sum_over_time(smoke_sig[1m]))",
                "interval": "1m", "since_ms": now - 600_000,
            }
            async with s.post(f"{base}/api/v1/rules", json=r_reg) as r:
                check(r.status == 200, f"recording rule registered "
                                       f"({r.status})")
            a_reg = {
                "kind": "alert", "name": "SmokeSignal",
                "expr": 'smoke_sig{host="sig"}', "for": 0,
                "labels": {"severity": "smoke"},
            }
            async with s.post(f"{base}/api/v1/rules", json=a_reg) as r:
                check(r.status == 200, f"alert rule registered ({r.status})")
            # the threshold-crossing write: recent samples so the alert's
            # instant evaluation (5m lookback) sees them
            from horaedb_tpu.pb import remote_write_pb2

            sig = remote_write_pb2.WriteRequest()
            tser = sig.timeseries.add()
            for k, v in ((b"__name__", b"smoke_sig"), (b"host", b"sig")):
                lab = tser.labels.add()
                lab.name = k
                lab.value = v
            for i in range(5):
                smp = tser.samples.add()
                smp.timestamp = now - (5 - i) * 60_000
                smp.value = float(10 + i)
            async with s.post(f"{base}/api/v1/write",
                              data=sig.SerializeToString()) as r:
                check(r.status == 200, "rule-signal write accepted")
            async with s.post(f"{base}/api/v1/rules/tick") as r:
                tick = (await r.json()).get("data") or {}
                check(r.status == 200 and tick.get("errors") == 0
                      and tick.get("evaluated", 0) >= 2,
                      f"forced rule tick evaluated both rules: {tick}")
                check(tick.get("samples_written", 0) > 0,
                      f"recording rule wrote output samples: {tick}")
            async with s.post(f"{base}/api/v1/query?explain=1", json={
                "metric": "smoke:sig:sum", "start_ms": now - 900_000,
                "end_ms": now + 60_000,
            }) as r:
                body = await r.json()
                check(r.status == 200 and body.get("rows", 0) > 0,
                      f"rule-produced series is queryable: "
                      f"rows={body.get('rows')}")
                rp = ((body.get("explain") or {}).get("rules")
                      or {}).get("rule_produced") or {}
                check("smoke:sig:sum" in rp,
                      f"EXPLAIN carries rule provenance: {rp}")
            async with s.get(f"{base}/api/v1/alerts") as r:
                alerts = ((await r.json()).get("data") or {}).get(
                    "alerts") or []
                firing = [a for a in alerts
                          if a["labels"].get("alertname") == "SmokeSignal"]
                check(bool(firing) and firing[0]["state"] == "firing",
                      f"alert reached firing: {alerts}")
            async with s.get(f"{base}/api/v1/rules") as r:
                body = await r.json()
                groups = (body.get("data") or {}).get("groups") or []
                check(r.status == 200 and {g["name"] for g in groups}
                      == {"recording", "alerting"},
                      f"/api/v1/rules lists both groups "
                      f"({[g.get('name') for g in groups]})")
            async with s.get(f"{base}/debug/kernels") as r:
                cat = await r.json()
                check(
                    r.status == 200 and isinstance(cat.get("kernels"), list)
                    and len(cat["kernels"]) > 0,
                    f"/debug/kernels serves the catalog "
                    f"({len(cat.get('kernels', []))} kernels)",
                )
            async with s.get(f"{base}/debug/slowlog") as r:
                slog = await r.json()
                ids = [e.get("trace_id") for e in slog.get("entries", [])]
                check(
                    r.status == 200 and slog.get("enabled") is True
                    and trace_id in ids,
                    f"/debug/slowlog recorded the query "
                    f"({len(ids)} entries)",
                )
            async with s.get(f"{base}/debug/traces/{trace_id}") as r:
                t = await r.json()
                check(
                    r.status == 200 and t.get("trace_id") == trace_id
                    and t.get("root") is not None,
                    "/debug/traces/{id} round-trips the span tree",
                )
            # ---- overload shedding: with the store's circuit breaker
            # forced open, a write that must touch the store (fresh
            # metric name -> registration) answers 503 + Retry-After —
            # the graceful-degradation contract (server/errors.py)
            from horaedb_tpu.server.main import STATE_KEY

            store = app[STATE_KEY].engine._store
            store.breaker.force_open()
            try:
                async with s.post(f"{base}/api/v1/write",
                                  data=make_payload_named("smoke_shed")) as r:
                    check(r.status == 503,
                          f"breaker-open write answers 503 (got {r.status})")
                    check(r.headers.get("Retry-After", "").isdigit(),
                          f"503 carries Retry-After "
                          f"({r.headers.get('Retry-After')!r})")
            finally:
                store.breaker.reset()
            async with s.post(f"{base}/api/v1/write",
                              data=make_payload_named("smoke_shed")) as r:
                check(r.status == 200, "write recovers after breaker reset")
            # ---- cardinality defense: flood past max_series, then a
            # write carrying one EXISTING series + new ones must answer
            # the counted 503/Retry-After partial-accept
            # ~43 series exist (smoke_cpu a/b + 40 smoke_bulk hosts +
            # smoke_shed); 22 more cross the 60 limit (the gate engages on
            # the NEXT new series, not retroactively)
            async with s.post(f"{base}/api/v1/write",
                              data=make_bulk_payload(62, 1)) as r:
                check(r.status == 200, "flood crossing the limit accepted")
            over = make_bulk_payload(64, 1)  # 62 exist + 2 brand-new hosts
            async with s.post(f"{base}/api/v1/write", data=over) as r:
                body = await r.json()
                check(r.status == 503 and body.get("partial_accept") is True,
                      f"cardinality breach answers 503 partial-accept "
                      f"(got {r.status}: {body})")
                check(body.get("rejected_series") == 2
                      and body.get("accepted_samples") == 62,
                      f"partial-accept accounting exact ({body})")
                check(r.headers.get("Retry-After", "").isdigit(),
                      "cardinality 503 carries Retry-After")
            # in-budget traffic still flows at the limit
            async with s.post(f"{base}/api/v1/write",
                              data=make_bulk_payload(40, 1)) as r:
                check(r.status == 200,
                      "existing-series write still 200 at the limit")
            # ---- query admission shedding: with the scheduler forced
            # full, a query answers 503 + Retry-After (never a hang);
            # reset restores service. A tiny per-request timeout= must
            # answer 504 with the deadline taxonomy.
            adm_ctl = app[STATE_KEY].admission
            adm_ctl.force_full()
            try:
                async with s.post(f"{base}/api/v1/query", json={
                    "metric": "smoke_cpu", "start_ms": 0, "end_ms": 10_000,
                }) as r:
                    check(r.status == 503,
                          f"forced queue-full query answers 503 "
                          f"(got {r.status})")
                    check(r.headers.get("Retry-After", "").isdigit(),
                          f"admission 503 carries Retry-After "
                          f"({r.headers.get('Retry-After')!r})")
            finally:
                adm_ctl.reset_forced()
            async with s.post(f"{base}/api/v1/query", json={
                "metric": "smoke_cpu", "start_ms": 0, "end_ms": 10_000,
            }) as r:
                check(r.status == 200, "query recovers after admission reset")
            async with s.post(f"{base}/api/v1/query", json={
                "metric": "smoke_cpu", "start_ms": 0, "end_ms": 10_000,
                "timeout": 1e-9,
            }) as r:
                body = await r.json()
                check(r.status == 504
                      and body.get("deadline_exceeded") is True,
                      f"tiny timeout= answers 504 deadline-exceeded "
                      f"(got {r.status}: {body})")
            check(adm_ctl.inflight == 0,
                  f"admission slots all freed (inflight="
                  f"{adm_ctl.inflight})")
            # ---- self-telemetry: a SECOND server over a fresh store
            # (this one's 60-series cardinality cap would reject the
            # ~400-series self-scrape) proves the closed loop: a forced
            # scrape tick writes the registry through the ingest path,
            # and a PromQL range query over the self-written series
            # returns the snapshot BIT-EQUAL
            tel_scratch = tempfile.mkdtemp(prefix="horaedb-smoke-tel-")
            tel_cfg = Config.from_dict({
                "metric_engine": {
                    "storage": {"object_store": {
                        "type": "Local", "data_dir": tel_scratch,
                    }},
                    "telemetry": {"scrape_interval": "1h"},
                },
            })
            tel_app = await build_app(tel_cfg)
            tel_runner = web.AppRunner(tel_app)
            await tel_runner.setup()
            tel_site = web.TCPSite(tel_runner, "127.0.0.1", 0)
            await tel_site.start()
            tel_port = tel_site._server.sockets[0].getsockname()[1]
            tel = f"http://127.0.0.1:{tel_port}"
            try:
                fam = "horaedb_remote_write_samples_total"
                async with s.post(
                    f"{tel}/api/v1/telemetry/scrape?include={fam}"
                ) as r:
                    data = (await r.json()).get("data") or {}
                    check(r.status == 200 and data.get("written", 0) > 100,
                          f"forced self-scrape wrote the registry "
                          f"({data.get('written')} samples)")
                    check(data.get("dropped") == 0,
                          f"no series dropped by the budget: {data}")
                    matched = data.get("matched") or []
                    check(len(matched) == 1,
                          f"scrape echoed the {fam} snapshot: {matched}")
                    snap_v = matched[0]["value"]
                    ts_s = data["ts_ms"] / 1000.0
                async with s.get(
                    f"{tel}/api/v1/query_range?query={fam}"
                    f"&start={ts_s}&end={ts_s}&step=15"
                ) as r:
                    body = await r.json()
                    res = ((body.get("data") or {}).get("result") or [])
                    check(r.status == 200 and len(res) == 1,
                          f"range query over the self-series answered: "
                          f"{body}")
                    vals = res[0].get("values") or [] if res else []
                    check(
                        bool(vals) and float(vals[0][1]) == float(snap_v),
                        f"self-scraped value BIT-EQUAL to the registry "
                        f"snapshot ({vals[:1]} vs {snap_v})",
                    )
                async with s.get(f"{tel}/api/v1/usage?tenant=_system") as r:
                    u = ((await r.json()).get("data") or {}).get(
                        "since_boot") or {}
                    check(u.get("rows_ingested", 0) > 100,
                          f"_system tenant metered the scrape's rows: {u}")
            finally:
                await tel_runner.cleanup()
                import shutil as _shutil

                _shutil.rmtree(tel_scratch, ignore_errors=True)
            # ---- OpenMetrics negotiation: # EOF-terminated, exemplar-
            # carrying, and clean under the OpenMetrics validator
            async with s.get(f"{base}/metrics", headers={
                "Accept": "application/openmetrics-text",
            }) as r:
                om = await r.text()
                check("openmetrics-text" in r.headers.get(
                    "Content-Type", ""),
                    f"openmetrics content type negotiated "
                    f"({r.headers.get('Content-Type')!r})")
                check(om.rstrip().endswith("# EOF"),
                      "openmetrics body ends with # EOF")
                check('# {trace_id="' in om,
                      "openmetrics carries trace-id exemplars")
                om_errors = validate_openmetrics(om)
                for e in om_errors[:10]:
                    print(f"FAIL promcheck[openmetrics]: {e}")
                check(not om_errors,
                      f"openmetrics body passes the validator "
                      f"({len(om.splitlines())} lines)")
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
        errors = validate(text)
        for e in errors[:20]:
            print(f"FAIL promcheck: {e}")
        check(not errors,
              f"/metrics passes the exposition-format validator "
              f"({len(text.splitlines())} lines)")
        for fam in REQUIRED_FAMILIES:
            check(fam in text, f"/metrics exposes {fam}")
    finally:
        await runner.cleanup()
        await fake.stop()
        import shutil

        shutil.rmtree(scratch, ignore_errors=True)
    print(f"smoke-metrics: {len(failures)} failure(s)")
    return 1 if failures else 0


def main() -> None:
    import os
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # cold aggregation-calibration cache: the first downsample then pays
    # the registry micro-A/B, which drives the instrumented device kernels
    # and guarantees horaedb_jit_compile_total carries labeled kernels
    os.environ["HORAEDB_AGG_CACHE"] = os.path.join(
        tempfile.mkdtemp(prefix="horaedb-smoke-calib-"), "agg_calib.json"
    )
    raise SystemExit(asyncio.run(run()))


if __name__ == "__main__":
    main()
