"""Observability smoke gate (`make smoke-metrics`).

Boots the real server (build_app) against the in-process fake S3 object
store, pushes one remote-write batch, runs one raw and one downsample
query, then fails loudly unless:

- every /metrics line passes the Prometheus text-format validator
  (tools/promcheck.py);
- the expected metric families are present (per-stage scan histograms,
  ingest/flush/storage/compaction families, HTTP latency);
- the query response echoed an X-Horaedb-Trace-Id whose span tree
  round-trips through GET /debug/traces/{id}.

This is the end-to-end check the unit tests can't give: the families are
registered at import time across six modules, and only a live request
drives them all through one process.

Run: python tools/smoke_metrics.py
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from promcheck import validate  # noqa: E402

REQUIRED_FAMILIES = (
    "horaedb_scan_stage_seconds_bucket",
    'horaedb_scan_stage_seconds_bucket{stage="io_decode"',
    'horaedb_scan_stage_seconds_bucket{stage="transfer"',
    'horaedb_scan_stage_seconds_bucket{stage="kernel"',
    'horaedb_scan_stage_seconds_bucket{stage="host_prep"',
    "horaedb_scan_path_total",
    "horaedb_agg_impl_total",
    "horaedb_remote_write_samples_total",
    "horaedb_remote_write_batch_samples_bucket",
    "horaedb_ingest_parse_seconds_bucket",
    "horaedb_storage_write_seconds_bucket",
    "horaedb_storage_scan_seconds_bucket",
    "horaedb_sst_bytes_bucket",
    "horaedb_compaction_queue_depth",
    "horaedb_compaction_seconds_bucket",
    "horaedb_http_request_seconds_bucket",
    "horaedb_ingest_flush_seconds_bucket",
    "horaedb_uptime_seconds",
)


def make_payload() -> bytes:
    from horaedb_tpu.pb import remote_write_pb2

    req = remote_write_pb2.WriteRequest()
    for host, samples in (("a", [(1000, 1.5), (2000, 2.5)]),
                          ("b", [(1500, 7.0)])):
        ts = req.timeseries.add()
        for k, v in ((b"__name__", b"smoke_cpu"), (b"host", host.encode())):
            lab = ts.labels.add()
            lab.name = k
            lab.value = v
        for t, v in samples:
            s = ts.samples.add()
            s.timestamp = t
            s.value = v
    return req.SerializeToString()


async def run() -> int:
    import aiohttp
    from aiohttp import web

    from horaedb_tpu.objstore.fake_s3 import FakeS3
    from horaedb_tpu.server.config import Config
    from horaedb_tpu.server.main import build_app

    failures: list[str] = []

    def check(ok: bool, msg: str) -> None:
        print(("ok   " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    fake = FakeS3()
    url = await fake.start()
    cfg = Config.from_dict({
        "metric_engine": {"storage": {"object_store": {
            "type": "S3Like", "endpoint": url, "bucket": fake.bucket,
            "region": "smoke", "key_id": "smoke", "key_secret": "smoke",
        }}},
    })
    app = await build_app(cfg)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{base}/api/v1/write",
                              data=make_payload()) as r:
                body = await r.json()
                check(r.status == 200 and body.get("samples") == 3,
                      f"remote-write accepted: {body}")
            async with s.post(f"{base}/api/v1/query", json={
                "metric": "smoke_cpu", "start_ms": 0, "end_ms": 10_000,
            }) as r:
                body = await r.json()
                trace_id = r.headers.get("X-Horaedb-Trace-Id", "")
                check(r.status == 200 and body.get("rows") == 3,
                      f"raw query answered: {body}")
                check(bool(trace_id), "query echoed X-Horaedb-Trace-Id")
            async with s.post(f"{base}/api/v1/query", json={
                "metric": "smoke_cpu", "start_ms": 0, "end_ms": 4000,
                "bucket_ms": 2000,
            }) as r:
                check(r.status == 200, "downsample query answered")
            async with s.get(f"{base}/debug/traces/{trace_id}") as r:
                t = await r.json()
                check(
                    r.status == 200 and t.get("trace_id") == trace_id
                    and t.get("root") is not None,
                    "/debug/traces/{id} round-trips the span tree",
                )
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
        errors = validate(text)
        for e in errors[:20]:
            print(f"FAIL promcheck: {e}")
        check(not errors,
              f"/metrics passes the exposition-format validator "
              f"({len(text.splitlines())} lines)")
        for fam in REQUIRED_FAMILIES:
            check(fam in text, f"/metrics exposes {fam}")
    finally:
        await runner.cleanup()
        await fake.stop()
    print(f"smoke-metrics: {len(failures)} failure(s)")
    return 1 if failures else 0


def main() -> None:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    raise SystemExit(asyncio.run(run()))


if __name__ == "__main__":
    main()
