"""Per-file JIT-surface rules: J001 host sync, J002 retrace hazards,
J003 dtype drift, J005 host timers under jit, J006 ad-hoc aggregation
lanes, J007 naked jit. Moved verbatim from the single-file linter;
rationale and examples live in docs/static-analysis.md."""

from __future__ import annotations

import ast

from tools.jaxlint.base import Finding, dotted, walk_no_nested_defs

# Modules whose host-side code is ALSO held to the no-silent-sync bar
# (the columnar scan/merge/aggregate surface PAPERS.md budgets):
HOT_MODULES = (
    "horaedb_tpu/ops/",
    "horaedb_tpu/parallel/",
    "horaedb_tpu/storage/read.py",
)
# Engine-code scope for the dtype rule (J003):
DTYPE_MODULES = (
    "horaedb_tpu/ops/",
    "horaedb_tpu/parallel/",
    "horaedb_tpu/engine/",
    "horaedb_tpu/storage/",
)

JIT_WRAPPERS = {
    "jit", "jax.jit", "pjit", "jax.pjit",
    "jax.experimental.pjit.pjit",
    "shard_map", "jax.experimental.shard_map.shard_map",
    # the instrumented wrapper (common/xprof.py) IS a jit wrapper: bodies
    # it traces stay under the J001/J002/J005/J006 in-jit rules
    "xjit", "xprof.xjit", "common.xprof.xjit",
}
PARTIAL_NAMES = {"partial", "functools.partial"}

# J007: jit spellings that bypass xprof's compile telemetry. Scope below
# (J007_MODULES); `shard_map` alone is fine — the telemetry hook is the
# OUTER jit wrapper, which must be xjit.
NAKED_JIT = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
J007_MODULES = (
    "horaedb_tpu/ops/",
    "horaedb_tpu/parallel/",
    "horaedb_tpu/promql/",
)

# device -> host syncs, unambiguous even outside jit
SYNC_METHODS = {"item", "block_until_ready"}
SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
# additionally wrong inside a traced function
TRACE_SYNC_METHODS = SYNC_METHODS | {"tolist"}
TRACE_SYNC_CALLS = SYNC_CALLS | {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.block_until_ready",
}
CONCRETIZING_BUILTINS = {"float", "int", "bool"}

# trace-time-frozen calls: evaluated ONCE at trace time, silently stale
# on every cached-trace call after that
FROZEN_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.perf_counter",
    "time.process_time", "datetime.now", "datetime.datetime.now",
    "datetime.utcnow", "datetime.datetime.utcnow",
}
FROZEN_PREFIXES = ("np.random.", "numpy.random.", "random.")

JNP_DTYPE_CTORS = {
    "jnp.array": 1, "jnp.full": 2,          # positional index of dtype
    "jax.numpy.array": 1, "jax.numpy.full": 2,
}

# Host-wall-clock timer / span context managers (J005): legitimate on the
# host side of a kernel boundary, a lie inside a traced body. Bare names
# cover `from ... import stage` style; dotted forms match only when the
# module component is literally `scanstats`/`tracing` — an alias like
# `import ... as ss; ss.stage(...)` evades the rule (the cost of not
# flagging every unrelated `.trace()`/`.stage()` method, e.g. the linalg
# `jnp.trace`). The tree imports these modules by their real names.
TIMER_FUNCS = {"stage", "scan_stats", "span", "start_trace"}
TIMER_MODULES = {"scanstats", "tracing"}

# J006 scope: modules allowed to hold aggregation lanes (the registry and
# its execution module); everything else in engine code must go through
# them. Host-ufunc prong matches (np|numpy).<ufunc>.(at|reduceat).
AGG_LANE_MODULES = (
    "horaedb_tpu/ops/agg_registry.py",
    "horaedb_tpu/ops/blockagg.py",
)
ONE_HOT_CALLS = {"jax.nn.one_hot", "nn.one_hot"}
ONE_HOT_CLASS_THRESHOLD = 64
IOTA_CALLS = {"jax.lax.broadcasted_iota", "lax.broadcasted_iota"}


def _is_timer_cm(fd: str | None) -> bool:
    if fd is None:
        return False
    parts = fd.split(".")
    tail = parts[-1]
    if tail not in TIMER_FUNCS and not (tail == "trace" and len(parts) > 1):
        return False
    if len(parts) == 1:
        return True
    return parts[-2] in TIMER_MODULES or parts[0] in TIMER_MODULES


def _is_host_ufunc_lane(fd: str | None) -> bool:
    if fd is None:
        return False
    parts = fd.split(".")
    return (
        len(parts) == 3
        and parts[0] in ("np", "numpy")
        and parts[-1] in ("at", "reduceat")
    )


def _is_jit_expr(node: ast.expr) -> bool:
    """True for `jax.jit`, `partial(jax.jit, ...)`, `shard_map`, and
    calls of those (e.g. the decorator `@partial(jax.jit, ...)`)."""
    d = dotted(node)
    if d in JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        fd = dotted(node.func)
        if fd in JIT_WRAPPERS:
            return True
        if fd in PARTIAL_NAMES and node.args and _is_jit_expr(node.args[0]):
            return True
    return False


def _jit_call_static(call: ast.Call) -> bool:
    """Does this jit/partial(jit) call carry static_argnums/argnames?"""
    kws = {kw.arg for kw in call.keywords}
    if {"static_argnums", "static_argnames"} & kws:
        return True
    # partial(jax.jit, static_argnames=...) nests one level
    if dotted(call.func) in PARTIAL_NAMES and call.args:
        inner = call.args[0]
        if isinstance(inner, ast.Call):
            return _jit_call_static(inner)
    return False


class JitIndex(ast.NodeVisitor):
    """First pass: which defs/lambdas run under a jit trace, and which
    NAMES are bound to bare (no-static) jit wrappers — for the J002
    call-site check."""

    def __init__(self) -> None:
        self.jit_defs: set[ast.AST] = set()       # FunctionDef/Lambda nodes
        self.wrapped_names: set[str] = set()       # names passed to jit/shard_map
        self.bare_jit_names: set[str] = set()      # jit-wrapped, no statics
        self._defs_by_name: dict[str, list[ast.AST]] = {}

    def visit_FunctionDef(self, node):  # noqa  (shared handler)
        self._defs_by_name.setdefault(node.name, []).append(node)
        for dec in node.decorator_list:
            if _is_jit_expr(dec):
                self.jit_defs.add(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        fd = dotted(node.func)
        is_wrap = fd in JIT_WRAPPERS or (
            fd in PARTIAL_NAMES and node.args and _is_jit_expr(node.args[0])
        )
        if is_wrap and node.args:
            pos = 1 if fd in PARTIAL_NAMES else 0
            target = node.args[pos] if len(node.args) > pos else None
            if isinstance(target, ast.Lambda):
                self.jit_defs.add(target)
            elif isinstance(target, ast.Name):
                self.wrapped_names.add(target.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # `kernel = jax.jit(fn)` without statics: calls to `kernel` with
        # untraceable literal args are J002 call-site findings
        if (
            isinstance(node.value, ast.Call)
            and dotted(node.value.func) in JIT_WRAPPERS
            and not _jit_call_static(node.value)
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.bare_jit_names.add(t.id)
        self.generic_visit(node)

    def finish(self) -> None:
        # names handed to jit()/shard_map() mark their local defs traced
        for name in self.wrapped_names:
            for d in self._defs_by_name.get(name, []):
                self.jit_defs.add(d)
        # a def decorated @jax.jit with NO statics is also a bare-jit name
        for defs in self._defs_by_name.values():
            for d in defs:
                if d in self.jit_defs and isinstance(
                    d, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    for dec in d.decorator_list:
                        if _is_jit_expr(dec) and not (
                            isinstance(dec, ast.Call) and _jit_call_static(dec)
                        ):
                            self.bare_jit_names.add(d.name)


def check_traced_body(fn, findings: list[Finding]) -> None:
    """J001 + J002 inside one jit-traced function body."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for node in walk_no_nested_defs(body):
        if isinstance(node, ast.JoinedStr):
            findings.append(Finding(
                node.lineno, "J002",
                "f-string under jit runs at trace time only (and "
                "concretizes tracers); move formatting outside the kernel "
                "or use jax.debug.print",
            ))
            continue
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        if _is_host_ufunc_lane(fd):
            findings.append(Finding(
                node.lineno, "J006",
                f"host ufunc lane `{fd}(...)` inside a jit-traced function "
                "— concretizes tracers AND bypasses the calibrated "
                "aggregation dispatcher; register the strategy in "
                "ops/agg_registry.py and call it outside jit",
            ))
        elif _is_timer_cm(fd):
            findings.append(Finding(
                node.lineno, "J005",
                f"host timer/span `{fd}(...)` inside a jit-traced function "
                "— the block measures trace time, not device execution "
                "(kernels dispatch asynchronously); time at the kernel call "
                "boundary outside jit",
            ))
        elif fd in TRACE_SYNC_CALLS:
            findings.append(Finding(
                node.lineno, "J001",
                f"host sync `{fd}(...)` inside a jit-traced function — "
                "forces a device->host transfer (or trace-time "
                "concretization) on the hot path",
            ))
        elif fd in CONCRETIZING_BUILTINS and node.args and not isinstance(
            node.args[0], ast.Constant
        ):
            findings.append(Finding(
                node.lineno, "J001",
                f"`{fd}()` on a traced value inside jit concretizes the "
                "tracer (ConcretizationTypeError at best, a silent host "
                "sync at worst)",
            ))
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in TRACE_SYNC_METHODS
            and not node.args
        ):
            findings.append(Finding(
                node.lineno, "J001",
                f"host sync `.{node.func.attr}()` inside a jit-traced "
                "function — forces a device->host transfer on the hot path",
            ))
        elif fd == "print":
            findings.append(Finding(
                node.lineno, "J002",
                "print() under jit runs at trace time only (silent on "
                "cached traces); use jax.debug.print",
            ))
        elif fd in FROZEN_CALLS or (
            fd is not None and fd.startswith(FROZEN_PREFIXES)
        ):
            findings.append(Finding(
                node.lineno, "J002",
                f"`{fd}()` under jit is evaluated once at trace time and "
                "frozen into the compiled graph — every later call reuses "
                "the stale value",
            ))


def check_host_hot(tree: ast.Module, jit_defs: set, findings: list) -> None:
    """J001 outside jit, hot modules only: unambiguous device syncs."""
    # collect nodes inside traced defs so we don't double-report them
    traced: set[ast.AST] = set()
    for d in jit_defs:
        body = d.body if isinstance(d.body, list) else [d.body]
        for stmt in body:
            traced.update(ast.walk(stmt))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or node in traced:
            continue
        fd = dotted(node.func)
        if fd in SYNC_CALLS:
            findings.append(Finding(
                node.lineno, "J001",
                f"`{fd}(...)` in a hot module — an explicit device->host "
                "sync on the scan/merge path; move it behind the kernel "
                "boundary or suppress with the measured justification",
            ))
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in SYNC_METHODS
            and not node.args
        ):
            findings.append(Finding(
                node.lineno, "J001",
                f"`.{node.func.attr}()` in a hot module — an explicit "
                "device->host sync on the scan/merge path",
            ))


def check_jit_call_sites(tree, bare_jit_names: set[str], findings) -> None:
    """J002: untraceable literal args to bare-jit callables."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Name)
                and node.func.id in bare_jit_names):
            continue
        exprs = list(node.args) + [kw.value for kw in node.keywords]
        for a in exprs:
            bad = None
            if isinstance(a, ast.Constant) and isinstance(a.value, (str, bytes)):
                bad = f"{type(a.value).__name__} literal"
            elif isinstance(a, ast.Set):
                bad = "set literal"
            if bad:
                findings.append(Finding(
                    node.lineno, "J002",
                    f"{bad} passed to jit-wrapped `{node.func.id}` with no "
                    "static_argnums/static_argnames — untraceable types "
                    "must be static (and each distinct value retraces)",
                ))


def check_dtype(tree: ast.Module, findings: list[Finding]) -> None:
    """J003: bare float literals into jnp.array/jnp.full without dtype."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        if fd not in JNP_DTYPE_CTORS:
            continue
        dtype_pos = JNP_DTYPE_CTORS[fd]
        if len(node.args) > dtype_pos:
            continue  # positional dtype given
        if any(kw.arg == "dtype" for kw in node.keywords):
            continue
        value_args = node.args[:dtype_pos]
        has_float = any(
            isinstance(sub, ast.Constant) and isinstance(sub.value, float)
            for a in value_args
            for sub in ast.walk(a)
        )
        if has_float:
            findings.append(Finding(
                node.lineno, "J003",
                f"bare float literal into `{fd}` without dtype= — weak-type "
                "promotion decides the lane width (f32 vs f64) from context; "
                "pin it explicitly in engine code",
            ))


def check_onehot(tree: ast.Module, findings: list[Finding]) -> None:
    """J006 prong 2: one-hot materializations in engine code outside the
    registry modules. Two idioms: `jax.nn.one_hot(x, N)` with N above the
    size threshold (a literal N <= 64 is a small embedding, not an
    aggregation one-hot; a non-literal N is flagged — it can be anything),
    and the `rank == broadcasted_iota(..., rank-3+ shape, ...)` compare
    this codebase's block compaction uses."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fd = dotted(node.func)
            if fd in ONE_HOT_CALLS:
                n_arg = None
                if len(node.args) > 1:
                    n_arg = node.args[1]
                else:
                    for kw in node.keywords:
                        if kw.arg == "num_classes":
                            n_arg = kw.value
                if (
                    isinstance(n_arg, ast.Constant)
                    and isinstance(n_arg.value, int)
                    and n_arg.value <= ONE_HOT_CLASS_THRESHOLD
                ):
                    continue
                findings.append(Finding(
                    node.lineno, "J006",
                    f"`{fd}` materialization above {ONE_HOT_CLASS_THRESHOLD} "
                    "classes outside ops/blockagg.py / ops/agg_registry.py — "
                    "one-hot traffic is the aggregate path's roofline "
                    "(ROOFLINE §1); register the kernel so the calibrated "
                    "dispatcher can measure it",
                ))
        elif isinstance(node, ast.Compare):
            sides = [node.left, *node.comparators]
            for side in sides:
                if not (isinstance(side, ast.Call)
                        and dotted(side.func) in IOTA_CALLS):
                    continue
                shape = side.args[1] if len(side.args) > 1 else None
                if isinstance(shape, (ast.Tuple, ast.List)) \
                        and len(shape.elts) < 3:
                    continue  # rank-2 iota compares are index masks, not
                    # materialized one-hots
                findings.append(Finding(
                    node.lineno, "J006",
                    "one-hot materialization via `== broadcasted_iota` "
                    "(rank-3+ shape) outside ops/blockagg.py / "
                    "ops/agg_registry.py — register the kernel in the "
                    "aggregation registry instead of an ad-hoc lane",
                ))
                break


def check_naked_jit(tree: ast.Module, findings: list[Finding]) -> None:
    """J007, hot modules only: any use of `jax.jit`/`jax.pjit` — call,
    decorator, or `partial(jax.jit, ...)` (all contain the `jax.jit`
    attribute node this walks for) — plus the import-alias escape hatch
    `from jax import jit`. The instrumented wrapper (common/xprof.xjit)
    is the only sanctioned jit spelling here: a naked jit silently drops
    the kernel out of compile telemetry, /debug/kernels, and EXPLAIN's
    compile/steady split."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            fd = dotted(node)
            if fd in NAKED_JIT:
                findings.append(Finding(
                    node.lineno, "J007",
                    f"naked `{fd}` in a hot module bypasses compile "
                    "telemetry (horaedb_jit_* families, /debug/kernels, "
                    "EXPLAIN compile split); route through "
                    "common/xprof.xjit",
                ))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax" and any(
                a.name in ("jit", "pjit") for a in node.names
            ):
                findings.append(Finding(
                    node.lineno, "J007",
                    "`from jax import jit` in a hot module — importing the "
                    "uninstrumented wrapper invites naked jit call sites; "
                    "use common/xprof.xjit",
                ))
