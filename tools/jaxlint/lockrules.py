"""J004 lock discipline (per-file): a class that owns a `*lock`
attribute but mutates lock-guarded `self._*` state in a PUBLIC method
outside any `with self._lock:` block. Moved verbatim from the
single-file linter; docs/static-analysis.md has the rationale."""

from __future__ import annotations

import ast

from tools.jaxlint.base import Finding, dotted

LOCK_FACTORIES = ("Lock", "RLock", "Semaphore", "Condition")
MUTATORS = {
    "append", "appendleft", "add", "update", "pop", "popitem", "clear",
    "extend", "remove", "discard", "insert", "setdefault",
}


def lock_attrs_of(cls: ast.ClassDef) -> set[str]:
    """Attribute names of locks this class OWNS (self._lock = Lock())."""
    out: set[str] = set()
    for node in ast.walk(cls):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        else:
            continue
        name = None
        if isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ) and target.value.id in ("self", "cls"):
            name = target.attr
        elif isinstance(target, ast.Name) and node in cls.body:
            name = target.id
        if name is None or not name.endswith("lock"):
            continue
        if isinstance(value, ast.Call):
            vd = dotted(value.func) or ""
            if vd.rsplit(".", 1)[-1] in LOCK_FACTORIES:
                out.add(name)
    return out


def _self_underscore_target(expr: ast.expr, bound: str) -> str | None:
    """Resolve (possibly subscripted) `<bound>._x...` store targets to
    the owning attribute name `_x` (`bound` is the method's receiver
    parameter: self or cls)."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == bound
        and expr.attr.startswith("_")
    ):
        return expr.attr
    return None


def check_lock_discipline(tree: ast.Module, findings: list[Finding]) -> None:
    """J004 per class, two passes: (1) which `self._*` attrs does ANY
    method mutate under a `with self.<lock>:` block — that set IS the
    lock-guarded state, declared by the code itself; (2) a PUBLIC method
    mutating one of those attrs outside the lock is the finding. Attrs
    the lock never guards anywhere (event-loop-confined counters next
    to a lock that serializes something else) are not flagged — the
    class never claimed the lock covers them."""
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = lock_attrs_of(cls)
        if not locks:
            continue
        guarded: set[str] = set()
        for meth in cls.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_method_locking(meth, locks, guarded, None)
        if not guarded:
            continue
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if meth.name.startswith("_"):
                continue  # private/dunder: callers hold the lock
            _scan_method_locking(meth, locks, guarded, findings)


def _scan_method_locking(meth, locks, guarded, findings) -> None:
    """findings=None: COLLECT attrs mutated under a lock into `guarded`.
    Otherwise: FLAG unlocked mutations of guarded attrs."""
    # only the method's FIRST parameter names the shared instance; `self`
    # as a plain local (the `self = object.__new__(cls)` constructor
    # idiom inside classmethods) is a not-yet-published object and its
    # attribute writes race with nobody
    params = meth.args.posonlyargs + meth.args.args
    bound = params[0].arg if params else None
    if bound not in ("self", "cls"):
        return

    def held_by(with_node) -> bool:
        for item in with_node.items:
            ctx = item.context_expr
            if (
                isinstance(ctx, ast.Attribute)
                and isinstance(ctx.value, ast.Name)
                and ctx.value.id == bound
                and ctx.attr in locks
            ):
                return True
        return False

    def visit(nodes, locked: bool) -> None:
        for node in nodes:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                visit(node.body, locked or held_by(node))
                continue
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                       ast.ClassDef)
            ):
                continue  # nested scopes have their own call discipline
            mut = _mutation_of(node, bound)
            if mut is not None:
                attr, verb = mut
                if findings is None:
                    if locked:
                        guarded.add(attr)
                elif not locked and attr in guarded:
                    findings.append(Finding(
                        node.lineno, "J004",
                        f"public method {verb} `self.{attr}` outside "
                        f"`with self.{'/'.join(sorted(locks))}:` — other "
                        "methods mutate this attribute under the lock, so "
                        "unlocked writes race them; take the lock or make "
                        "the method private",
                    ))
            visit(ast.iter_child_nodes(node), locked)

    visit(meth.body, False)


def _mutation_of(node, bound: str) -> tuple[str, str] | None:
    """(attr, verb) when `node` mutates `<bound>._x` state, else None.
    Bare annotations (`self._x: int` with no value) declare, not write."""
    attr = None
    verb = None
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        if isinstance(node, ast.AnnAssign) and node.value is None:
            return None
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            a = _self_underscore_target(t, bound)
            if a:
                attr, verb = a, "assigns"
                break
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            a = _self_underscore_target(t, bound)
            if a:
                attr, verb = a, "deletes"
                break
    elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr in MUTATORS:
        a = _self_underscore_target(node.func.value, bound)
        if a:
            attr, verb = a, f"mutates (.{node.func.attr})"
    if attr is None or attr.endswith("lock"):
        return None  # lazy lock creation is the lock's own lifecycle
    return attr, verb
