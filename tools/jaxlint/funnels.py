"""Per-file funnel-boundary rules J008-J017: every subsystem with ONE
sanctioned choke point (flush executor, ResilientStore, visibility
helper, admission scheduler, decode funnel, serving tier, invalidation
subscribers, metering, query batcher, cluster meta plane) gets a rule
that flags the second path. Moved verbatim from the single-file
linter; docs/static-analysis.md has per-rule rationale."""

from __future__ import annotations

import ast
import re

from tools.jaxlint.base import Finding, arg_identifiers, dotted

# J008: the append hot path (ingest decode + the engine write layers)
# must not reach blocking flush work directly — parquet encodes and
# object-store puts belong behind the flush executor
# (engine/flush_executor.py) and the storage layer it drives.
J008_MODULES = (
    "horaedb_tpu/ingest/",
    "horaedb_tpu/engine/",
)
J008_EXEMPT = ("horaedb_tpu/engine/flush_executor.py",)

# J009: the resilience boundary (objstore/resilient.py). Concrete store
# constructors outside objstore/ must be immediate arguments of a
# ResilientStore(...) call. tests/ and benchmarks/tools harnesses are out
# of scope — they deliberately build raw stores to inject faults.
J009_MODULES = ("horaedb_tpu/",)
J009_EXEMPT = ("horaedb_tpu/objstore/",)

# J011: the query-admission boundary (server/admission.py). Server-layer
# code must reach the engine's query surface only through the admission
# helpers; the owner-name heuristic (`engine`/`_engine` receiver) matches
# this codebase's handler idiom (`state.engine.query(...)`) without
# flagging unrelated `.query()` methods on other objects.
J011_MODULES = ("horaedb_tpu/server/",)
J011_EXEMPT = ("horaedb_tpu/server/admission.py",)
QUERY_ENTRY_ATTRS = {"query", "query_exemplars"}
ENGINE_RECEIVERS = {"engine", "_engine"}

# J010: tombstone/retention filtering is ONE shared helper
# (storage/visibility.py, funneled through ParquetReader.read_sst); any
# other engine code touching the visibility state's row-filtering fields
# is an ad-hoc reader filter waiting to diverge. The manifest package is
# the record STORE (load/persist/GC) and is exempt.
J010_MODULES = ("horaedb_tpu/",)
J010_EXEMPT = (
    "horaedb_tpu/storage/visibility.py",
    "horaedb_tpu/storage/manifest/",
)
VISIBILITY_FIELDS = {"tombstones", "retention_floor_ms"}

# J012: the encoded-lane decode funnel (storage/encoding.py host codecs,
# ops/decode.py device kernels) and the one reader that drives it
# (storage/read.py's encoded path). Everything else in engine code must
# not decode encoded buffers by hand.
J012_MODULES = ("horaedb_tpu/",)
J012_EXEMPT = (
    "horaedb_tpu/storage/encoding.py",
    "horaedb_tpu/ops/decode.py",
    "horaedb_tpu/storage/read.py",
)
# the funnel's own decode entry points (dotted-name tail match)
DECODE_FUNNEL_FUNCS = {
    "decode_lane", "decode_blob", "decode_page_device", "unpack_bits",
    "unzigzag",
}
# decode-shaped primitives that, applied to an encoded buffer, are an
# ad-hoc decode path (tail match; `.accumulate` covers ufunc scans like
# np.bitwise_xor.accumulate)
DECODE_SHAPED_TAILS = {"cumsum", "unpackbits", "associative_scan", "accumulate"}
_ENC_NAME_RE = re.compile(r"(^|_)enc(oded)?(_|$)|encoded|^payload$")

# J013: the serving-tier funnel (horaedb_tpu/serving + storage/rollup.py).
# READ side: cache lookups / rollup planning / residency probes belong at
# the planner choke point (engine/data.py) and in the tier's own modules
# (storage/read.py hosts the residency hooks). WRITE side: cache/residency
# mutation belongs to the invalidation funnel — the storage write commit,
# the compaction commit, the tombstone path (all in storage/storage.py /
# compaction/executor.py), the manifest's record store, and the reader's
# eviction hooks.
J013_MODULES = ("horaedb_tpu/",)
J013_READ_EXEMPT = (
    "horaedb_tpu/serving/",
    "horaedb_tpu/engine/data.py",
    "horaedb_tpu/storage/rollup.py",
    "horaedb_tpu/storage/read.py",
)
J013_WRITE_EXEMPT = (
    "horaedb_tpu/serving/",
    "horaedb_tpu/storage/storage.py",
    "horaedb_tpu/storage/compaction/executor.py",
    "horaedb_tpu/storage/manifest/",
    "horaedb_tpu/storage/rollup.py",
    "horaedb_tpu/storage/read.py",
    # the replica's snapshot swap IS its flush/delete commit — the swap
    # routes through serving_invalidate with the mutation's time range
    "horaedb_tpu/cluster/replica.py",
)
SERVING_READ_FUNCS = {
    "serving_get", "serving_single_flight", "plan_rollups", "read_rollup",
    "resident_block",
}
SERVING_WRITE_FUNCS = {
    "serving_put", "serving_invalidate", "note_fetch", "evict_sst",
    "evict_rollup",
}

# J014: the invalidation funnel's CONSUMER set. serving_subscribe /
# serving_unsubscribe (serving/cache.py) hand out a synchronous callback
# inside every mutation commit; the audited consumers are the cache
# itself (serving/) and the rule evaluator (rules/ — the streaming rule
# engine's dirty sets). Anything else subscribing is a second standing-
# query engine growing outside the one whose exactness is tested.
J014_MODULES = ("horaedb_tpu/",)
J014_EXEMPT = (
    "horaedb_tpu/serving/",
    "horaedb_tpu/rules/",
)
FUNNEL_SUBSCRIBE_FUNCS = {"serving_subscribe", "serving_unsubscribe"}

# J015: the per-tenant usage funnel (telemetry/metering.py). Tenant
# accounting registered anywhere else forks the ledger.
J015_MODULES = ("horaedb_tpu/",)
J015_EXEMPT = ("horaedb_tpu/telemetry/",)
METRIC_REGISTER_VERBS = {"counter", "gauge", "histogram"}
TENANT_FAMILY_PREFIX = "horaedb_tenant_"

# J016: the stacked-execution funnel (server/batching.py pads/stacks the
# coalesced query lanes; ops/aggregate.py hosts the sanctioned stacked
# kernels). Stack/pad-shaped calls over batched-query-lane names anywhere
# else are a second stacking path (same heuristic class as J012's
# encoded-buffer prong: primitive tail + argument naming idiom).
J016_MODULES = ("horaedb_tpu/",)
J016_EXEMPT = (
    "horaedb_tpu/server/batching.py",
    "horaedb_tpu/ops/aggregate.py",
)
STACK_SHAPED_TAILS = {
    "stack", "vstack", "hstack", "dstack", "column_stack", "pad",
}
_BATCH_LANE_RE = re.compile(
    r"(^|_)(stacked?|padded|batch(ed)?|grids?|lanes?)(_|$)"
)

# J017: the cluster funnel (horaedb_tpu/cluster). Prong 1: manifest
# snapshot views belong to the manifest package + the replica funnel.
# Prong 2: assignment records mutate only through assignment.py's
# fenced CAS (put_if_absent-arbitrated versions).
J017_MODULES = ("horaedb_tpu/",)
J017_VIEW_EXEMPT = (
    "horaedb_tpu/storage/manifest/",
    "horaedb_tpu/cluster/replica.py",
)
J017_ASSIGN_EXEMPT = ("horaedb_tpu/cluster/assignment.py",)
MANIFEST_VIEW_FUNCS = {"read_snapshot", "read_folded_view"}
STORE_MUTATION_TAILS = {"put", "put_if_absent", "put_stream", "delete"}
_ASSIGNMENT_NAME_RE = re.compile(
    r"cluster/assignment|assignment_path|assignment_dir|ASSIGNMENT_DIR"
)

# J022: the traced cluster-client funnel (cluster/router.traced_request).
# Every outbound cluster-tier HTTP hop — write forwards, split-write
# fan-out, read offload, hedged failover, status probes, federation
# scrapes — goes through the ONE funnel that injects the cross-node
# trace headers, grafts the peer's shipped-back span subtree, and feeds
# peer-health/probe metrics. A second client path ships invisible hops.
J022_MODULES = ("horaedb_tpu/cluster/", "horaedb_tpu/server/")
J022_EXEMPT = ("horaedb_tpu/cluster/router.py",)
HTTP_VERB_TAILS = {
    "get", "post", "put", "delete", "head", "options", "patch",
    "request", "ws_connect",
}
SESSION_RECEIVERS = {"session", "_session", "client_session",
                     "http_session"}

# J023: the partial-grid funnel (cluster/partial.py). The scatter-gather
# wire codec and the coordinator merge are the load-bearing half of the
# distributed bit-exactness promise: ONE encode/decode pair so every
# fragment ships the same dtype-preserving LE layout, ONE merge with the
# fixed canonical-region fold order. A second encoder or an ad-hoc
# in-place fold (np.add.at / np.minimum.at / np.maximum.at on grids) in
# server/cluster code silently reorders float addition and the
# distributed answer stops matching single-node bit-for-bit.
J023_MODULES = ("horaedb_tpu/cluster/", "horaedb_tpu/server/")
J023_EXEMPT = ("horaedb_tpu/cluster/partial.py",)
PARTIAL_GRID_FUNNEL_DEFS = {
    "encode_partials", "decode_partials", "merge_partials", "merge_grids",
}
GRID_FOLD_UFUNC_HEADS = {"add", "minimum", "maximum"}

# J024: the memtrace funnel (common/memtrace.py). The data-plane modules
# account every buffer hand-off — copies vs views per stage — through
# the tracked_* helpers; a raw `pa.concat_tables` / `.combine_chunks()`
# / `np.concatenate` / `np.ascontiguousarray` / lane `.copy()` in scope
# is an invisible copy the EXPLAIN memory verdict, the copy-tax table,
# and the mem-smoke regression gate all silently miss. jnp.concatenate
# (traced device math) is NOT a host copy and stays out of scope.
J024_MODULES = (
    "horaedb_tpu/storage/read.py",
    "horaedb_tpu/storage/rollup.py",
    "horaedb_tpu/serving/",
    "horaedb_tpu/engine/data.py",
    "horaedb_tpu/cluster/partial.py",
    "horaedb_tpu/ingest/",
    "horaedb_tpu/parallel/mesh.py",
)
J024_EXEMPT = ("horaedb_tpu/common/memtrace.py",)
MEMTRACE_CONCAT_TAILS = {"concat_tables", "combine_chunks"}
MEMTRACE_NUMPY_CALLS = {"np.concatenate", "np.ascontiguousarray",
                        "numpy.concatenate", "numpy.ascontiguousarray"}
# zero-arg `.copy()` receivers that look like data-plane lanes; scoped
# to lane-ish names so dict/config `.copy()` bookkeeping stays quiet
_LANE_NAME_RE = re.compile(
    r"(^|_)(ts|tsid|sid|val(ue)?s?|mask|lane|lanes|grid|grids|arr|"
    r"cols?|table|tables|buf)(_|$|\d*$)"
)

# J025: the column-block contract (common/colblock.py). The zero-copy
# spine hands column blocks BY REFERENCE across the data plane; a fresh
# numpy array materialized from a block's lanes (`np.array`/`np.asarray`
# /`np.frombuffer`/`np.copy` over a `.lane(...)` accessor or a
# block-named buffer) outside colblock.py's sanctioned APIs is a
# re-materialization the lineage ledger files nowhere — the copy-tax
# verdict reads "view"/"reuse" while real bytes moved. colblock.as_lane
# / ColBlock.copy_lane / the memtrace tracked_* helpers are the
# sanctioned ways to coerce or duplicate a lane.
J025_MODULES = J024_MODULES + (
    "horaedb_tpu/storage/storage.py",
    "horaedb_tpu/parallel/scan.py",
)
J025_EXEMPT = (
    "horaedb_tpu/common/colblock.py",
    "horaedb_tpu/common/memtrace.py",
)
BLOCK_MATERIALIZE_CALLS = {
    "np.array", "np.asarray", "np.frombuffer", "np.copy",
    "numpy.array", "numpy.asarray", "numpy.frombuffer", "numpy.copy",
}
BLOCK_LANE_ATTRS = {"lane", "lanes", "writable_lane"}
_BLOCK_NAME_RE = re.compile(r"(^|_)(col_?block|blocks?)(_|$|\d*$)")
# colblock's own constructors/coercers + the memtrace helpers sanction
# every call nested inside them (the J024 wrapped-subtree technique)
COLBLOCK_SANCTIONED_TAILS = {
    "ColBlock", "GrowableColBlock", "ArrowLanes", "aligned_empty",
    "as_lane", "adopt_spare", "wrap", "copy_lane", "to_device",
    "to_arrow_batch",
}

RAW_STORE_CTORS = {"MemStore", "LocalStore", "S3LikeStore"}
STORE_BOUNDARY_WRAPPERS = {"ResilientStore", "ChaosStore"}
PARQUET_ENCODE_CALLS = {
    "pq.ParquetWriter", "pq.write_table", "pq.write_to_dataset",
    "pyarrow.parquet.ParquetWriter", "pyarrow.parquet.write_table",
    "parquet.ParquetWriter", "parquet.write_table",
}
OBJSTORE_PUT_VERBS = {"put", "put_stream", "put_if_absent"}


def check_append_hot_path(tree: ast.Module, findings: list[Finding]) -> None:
    """J008, append-hot modules only: direct parquet-encode calls and
    direct object-store put verbs. The storage layer (`storage.write`)
    is the sanctioned durability path — it runs on the flush executor's
    workers with encode offloaded to the SST pool; a call site here
    would drag that work back onto the append path. Control-plane writes
    (region descriptors, index sidecars) carry reasoned suppressions."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        if fd in PARQUET_ENCODE_CALLS:
            findings.append(Finding(
                node.lineno, "J008",
                f"parquet encode `{fd}(...)` reachable from the append hot "
                "path — flush encode belongs behind the flush executor "
                "(engine/flush_executor.py) via the storage layer",
            ))
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in OBJSTORE_PUT_VERBS
        ):
            findings.append(Finding(
                node.lineno, "J008",
                f"direct object-store `.{node.func.attr}()` reachable from "
                "the append hot path — route durability through the "
                "storage layer / flush executor, or suppress with the "
                "control-plane justification",
            ))


def check_store_boundary(tree: ast.Module, findings: list[Finding]) -> None:
    """J009: concrete ObjectStore constructors outside objstore/ that are
    not immediate arguments of a ResilientStore(...) (or ChaosStore(...)
    — the chaos harness wraps before resilience does). One pass collects
    the wrapped argument nodes; a second flags naked constructions."""
    wrapped: set[ast.AST] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        if fd and fd.rsplit(".", 1)[-1] in STORE_BOUNDARY_WRAPPERS:
            wrapped.update(node.args)
            wrapped.update(kw.value for kw in node.keywords)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or node in wrapped:
            continue
        fd = dotted(node.func)
        if fd and fd.rsplit(".", 1)[-1] in RAW_STORE_CTORS:
            findings.append(Finding(
                node.lineno, "J009",
                f"concrete object store `{fd}(...)` constructed outside "
                "objstore/ without the ResilientStore boundary — the "
                "receiver gets single-naked-attempt semantics (no retry/"
                "backoff, deadlines, breaker, or horaedb_objstore_* "
                "attribution); wrap it in objstore/resilient.ResilientStore "
                "at the construction site or suppress with the reason",
            ))


def check_admission_boundary(tree: ast.Module, findings: list[Finding]) -> None:
    """J011: `<...>.engine.query(...)` / `.query_exemplars(...)` in server
    code outside server/admission.py. The receiver must be named
    `engine`/`_engine` (directly or as the last attribute before the
    verb) — the handler idiom this tree uses — so `registry.query(...)`
    on unrelated objects never trips the rule."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in QUERY_ENTRY_ATTRS):
            continue
        owner = f.value
        owner_name = None
        if isinstance(owner, ast.Attribute):
            owner_name = owner.attr
        elif isinstance(owner, ast.Name):
            owner_name = owner.id
        if owner_name in ENGINE_RECEIVERS:
            findings.append(Finding(
                node.lineno, "J011",
                f"direct engine `.{f.attr}(...)` in server code bypasses "
                "the admission scheduler (no concurrency cap, queue/stall "
                "backpressure, end-to-end deadline, tenant fairness, or "
                "shed metrics); route through server/admission.run_query"
                "/run_query_exemplars, or suppress with the reason",
            ))


def check_decode_funnel(tree: ast.Module, findings: list[Finding]) -> None:
    """J012, two prongs: (1) calls of the funnel's decode primitives
    outside the funnel; (2) decode-shaped ops (cumsum/unpackbits/
    associative_scan/ufunc .accumulate) whose arguments name an encoded
    buffer (`*_enc`, `enc_*`, `*encoded*`, `payload`) — the naming idiom
    of every encoded-buffer variable in this tree, same heuristic class
    as J011's `engine` receiver match."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if tail in DECODE_FUNNEL_FUNCS:
            findings.append(Finding(
                node.lineno, "J012",
                f"`{tail}(...)` called outside the sanctioned decode "
                "funnel (storage/encoding.py / ops/decode.py / the "
                "encoded reader in storage/read.py) — ad-hoc decode paths "
                "diverge from the funnel's bit-exactness contract and "
                "skip the calibrated host/device dispatcher; route "
                "through the reader, or suppress with the reason",
            ))
        elif tail in DECODE_SHAPED_TAILS and any(
            _ENC_NAME_RE.search(name) for name in arg_identifiers(node)
        ):
            findings.append(Finding(
                node.lineno, "J012",
                f"decode-shaped `{tail}(...)` over an encoded buffer "
                "outside the sanctioned funnel — hand-rolled prefix-sum/"
                "unpack of encoded lanes belongs in storage/encoding.py "
                "(host) or ops/decode.py (device kernels); suppress with "
                "the reason for harnesses measuring the funnel itself",
            ))


def check_serving_funnel(
    tree: ast.Module, findings: list[Finding],
    check_reads: bool, check_writes: bool,
) -> None:
    """J013: serving-tier read primitives outside the planner choke point,
    or mutation primitives outside the invalidation funnel (dotted-name
    tail match, the J011/J012 heuristic class)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if check_reads and tail in SERVING_READ_FUNCS:
            findings.append(Finding(
                node.lineno, "J013",
                f"serving-tier read `{tail}(...)` outside the planner "
                "choke point (engine/data.py's query methods) — a second "
                "lookup path can serve results the invalidation funnel "
                "already declared stale; route through the choke point, "
                "or suppress with the reason",
            ))
        elif check_writes and tail in SERVING_WRITE_FUNCS:
            findings.append(Finding(
                node.lineno, "J013",
                f"serving-tier mutation `{tail}(...)` outside the "
                "invalidation funnel (storage write commit / compaction "
                "commit / tombstone path / reader eviction hooks) — cache "
                "state must only change with the commit that justifies "
                "it; route through the funnel, or suppress with the "
                "reason",
            ))


def check_stacking_funnel(tree: ast.Module,
                          findings: list[Finding]) -> None:
    """J016: stack/pad-shaped primitives over query result lanes outside
    the batcher and the sanctioned stacked kernels. A call fires when its
    dotted tail is a stacking/padding primitive AND any argument
    identifier names a batched query lane (`stacked_*`, `padded_*`,
    `batch_*`, `*_grids`, `*_lanes` — the naming idiom of every stacked
    buffer in this tree, the J011/J012 heuristic class)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if tail in STACK_SHAPED_TAILS and any(
            _BATCH_LANE_RE.search(name) for name in arg_identifiers(node)
        ):
            findings.append(Finding(
                node.lineno, "J016",
                f"stacking/padding `{tail}(...)` over a query result lane "
                "outside the query batcher (server/batching.py) / the "
                "sanctioned stacked kernels (ops/aggregate.py) — a second "
                "stacked-execution path dodges the batcher's power-of-two "
                "shape classes (retraces escape the shared compiled "
                "shapes), its pad-waste accounting, and the bit-exact "
                "demux contract; route through the batcher, or suppress "
                "with the reason for harnesses measuring the stacked "
                "lane itself",
            ))


def check_cluster_funnel(
    tree: ast.Module, findings: list[Finding],
    check_views: bool, check_assign: bool,
) -> None:
    """J017: manifest-view consumption outside the replica funnel, and
    assignment-record mutation outside the fenced CAS API (dotted-tail +
    argument-naming heuristics, the J012/J016 class)."""
    def _arg_names_and_strings(node: ast.Call):
        for name in arg_identifiers(node):
            yield name
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    yield sub.value
                elif isinstance(sub, ast.JoinedStr):
                    for v in sub.values:
                        if isinstance(v, ast.Constant) and isinstance(v.value, str):
                            yield v.value

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if check_views and tail in MANIFEST_VIEW_FUNCS:
            findings.append(Finding(
                node.lineno, "J017",
                f"manifest view `{tail}(...)` consumed outside the "
                "manifest package / the cluster replica funnel "
                "(cluster/replica.py) — a second snapshot consumer is a "
                "second replication path with no staleness token, swap "
                "invalidation, or watch backoff; open the storage "
                "read-only (read_only=True) or go through ReplicaEngine, "
                "or suppress with the reason",
            ))
        elif check_assign and tail in STORE_MUTATION_TAILS and any(
            _ASSIGNMENT_NAME_RE.search(s)
            for s in _arg_names_and_strings(node)
        ):
            findings.append(Finding(
                node.lineno, "J017",
                f"assignment-record mutation `{tail}(...)` outside the "
                "fenced CAS API (cluster/assignment.py) — an unversioned "
                "write forks the meta plane and can reroute writes to a "
                "deposed owner; use propose_assignment/claim_regions/"
                "takeover_region, or suppress with the reason",
            ))


def check_funnel_subscribers(tree: ast.Module,
                             findings: list[Finding]) -> None:
    """J014: the invalidation funnel's consumer set is pinned — only the
    cache (serving/) and the rule evaluator (rules/) may subscribe. A
    third subscriber is a standing-query engine growing outside the one
    whose dirty-set exactness is chaos-tested."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if tail in FUNNEL_SUBSCRIBE_FUNCS:
            findings.append(Finding(
                node.lineno, "J014",
                f"invalidation-funnel subscription `{tail}(...)` outside "
                "the audited consumer set (serving/cache.py internals and "
                "the rule evaluator, horaedb_tpu/rules) — mutation-commit "
                "callbacks are a standing-query surface; consume the rule "
                "engine's dirty sets instead, or suppress with the reason",
            ))


def check_metering_funnel(tree: ast.Module, findings: list[Finding]) -> None:
    """J015: per-tenant accounting goes through telemetry/metering.py —
    three prongs: (1) a metric family registered under the reserved
    `horaedb_tenant_*` namespace; (2) a family registered with a
    `tenant` labelname; (3) a legacy string-API name literal embedding a
    `tenant="..."` label."""
    def _str_const(node):
        return node.value if (isinstance(node, ast.Constant)
                              and isinstance(node.value, str)) else None

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        name_arg = None
        if node.args:
            name_arg = _str_const(node.args[0])
        for kw in node.keywords:
            if kw.arg == "name" and name_arg is None:
                name_arg = _str_const(kw.value)
        if f.attr in METRIC_REGISTER_VERBS:
            if name_arg and name_arg.startswith(TENANT_FAMILY_PREFIX):
                findings.append(Finding(
                    node.lineno, "J015",
                    f"metric family {name_arg!r} registered outside the "
                    "metering funnel (horaedb_tpu/telemetry/) — the "
                    "horaedb_tenant_* namespace is the usage ledger's; "
                    "account through telemetry.metering.GLOBAL_METER, or "
                    "suppress with the reason",
                ))
                continue
            for kw in node.keywords:
                if kw.arg != "labelnames":
                    continue
                if isinstance(kw.value, (ast.Tuple, ast.List)) and any(
                    _str_const(e) == "tenant" for e in kw.value.elts
                ):
                    findings.append(Finding(
                        node.lineno, "J015",
                        "metric family registered with a `tenant` "
                        "labelname outside the metering funnel — ad-hoc "
                        "per-tenant series fork the usage ledger; route "
                        "the accounting through telemetry.metering."
                        "GLOBAL_METER, or suppress with the reason",
                    ))
        elif f.attr in ("inc", "set") and node.args:
            legacy = _str_const(node.args[0])
            if legacy and "tenant=\"" in legacy:
                findings.append(Finding(
                    node.lineno, "J015",
                    f"legacy metric name {legacy!r} embeds a tenant "
                    "label outside the metering funnel; route through "
                    "telemetry.metering.GLOBAL_METER, or suppress with "
                    "the reason",
                ))


def check_traced_client_funnel(tree: ast.Module,
                               findings: list[Finding]) -> None:
    """J022, two prongs: (1) an `aiohttp.ClientSession` constructed in
    cluster/server code outside the router (the funnel owns the ONE
    outbound session); (2) an HTTP verb called on a session-named
    receiver (`session`/`_session`/`client_session`/`http_session` —
    the naming idiom of every client session in this tree, the J011
    receiver-match heuristic class)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fd = dotted(node.func)
        tail = fd.rsplit(".", 1)[-1] if fd else None
        if tail == "ClientSession":
            findings.append(Finding(
                node.lineno, "J022",
                f"HTTP client session `{fd}(...)` constructed outside the "
                "traced cluster-client funnel (cluster/router."
                "traced_request) — a second outbound session ships hops "
                "with no X-Horaedb-Trace-Id injection, no span grafting, "
                "and no peer-health/probe metrics; route the call through "
                "the router funnel, or suppress with the reason",
            ))
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in HTTP_VERB_TAILS):
            continue
        owner = f.value
        owner_name = None
        if isinstance(owner, ast.Attribute):
            owner_name = owner.attr
        elif isinstance(owner, ast.Name):
            owner_name = owner.id
        if owner_name in SESSION_RECEIVERS:
            findings.append(Finding(
                node.lineno, "J022",
                f"outbound HTTP `.{f.attr}(...)` on a client session "
                "outside the traced cluster-client funnel — the hop is "
                "invisible to cross-node tracing (no trace-header "
                "injection, no shipped-back span graft) and to the "
                "peer-health view; route through cluster/router."
                "traced_request, or suppress with the reason",
            ))


def check_partial_grid_funnel(tree: ast.Module,
                              findings: list[Finding]) -> None:
    """J023, two prongs: (1) a function DEFINITION reusing a partial-grid
    funnel name (`encode_partials`/`decode_partials`/`merge_partials`/
    `merge_grids`) outside cluster/partial.py — a shadow codec or merge
    forks the wire format / fold order; calling the funnel is fine.
    (2) an in-place ufunc grid fold (`np.add.at`, `np.minimum.at`,
    `np.maximum.at`) in cluster/server code — that is merge math, and
    merge math outside the funnel loses the canonical-region fold order
    the bit-exactness property test pins down."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in PARTIAL_GRID_FUNNEL_DEFS:
                findings.append(Finding(
                    node.lineno, "J023",
                    f"partial-grid funnel name `{node.name}` redefined "
                    "outside cluster/partial.py — a second wire codec or "
                    "merge forks the fragment format and the canonical "
                    "fold order behind the distributed bit-exactness "
                    "guarantee; import it from cluster/partial.py, or "
                    "suppress with the reason",
                ))
            continue
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "at"):
            continue
        owner = f.value
        if (isinstance(owner, ast.Attribute)
                and owner.attr in GRID_FOLD_UFUNC_HEADS):
            findings.append(Finding(
                node.lineno, "J023",
                f"in-place ufunc fold `{dotted(node.func)}(...)` in "
                "cluster/server code — partial-grid merge math belongs "
                "in cluster/partial.merge_grids, where the fold runs in "
                "the fixed canonical-region order that keeps the "
                "distributed answer bit-exact vs single-node; call the "
                "funnel, or suppress with the reason",
            ))


def check_memtrace_funnel(tree: ast.Module,
                          findings: list[Finding]) -> None:
    """J024, three prongs over the data-plane modules: (1) a raw
    `...concat_tables(...)` / `....combine_chunks()` arrow copy; (2) a
    raw `np.concatenate` / `np.ascontiguousarray` host-lane copy (exact
    numpy head — `jnp.concatenate` is traced device math, not a host
    buffer move); (3) a zero-arg `.copy()` on a lane-named receiver
    (`ts`/`vals`/`mask`/`grids`/...). Each belongs behind the
    common/memtrace tracked_* helpers so the bytes land in the per-query
    memory verdict and the copy-tax accounting; calls already wrapped by
    a memtrace helper in the same expression are sanctioned."""
    # sanctioned: any call nested inside a memtrace.tracked_*/track(...)
    # call expression — collect those subtree nodes first
    wrapped: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name and ("memtrace." in name or name.startswith("tracked_")
                     or name in ("track", "memtrace")):
            for sub in ast.walk(node):
                wrapped.add(id(sub))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or id(node) in wrapped:
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        name = dotted(f) or ""
        if f.attr in MEMTRACE_CONCAT_TAILS:
            findings.append(Finding(
                node.lineno, "J024",
                f"raw `.{f.attr}(...)` in a data-plane module — this "
                "arrow copy is invisible to the memory observatory "
                "(EXPLAIN memory verdict, horaedb_mem_* families, the "
                "mem-smoke copy-count gate); route it through "
                "memtrace.tracked_combine / tracked_concat_tables, or "
                "suppress with the reason",
            ))
        elif name in MEMTRACE_NUMPY_CALLS:
            findings.append(Finding(
                node.lineno, "J024",
                f"raw `{name}(...)` in a data-plane module — a host-lane "
                "copy the memory observatory cannot see; route it "
                "through memtrace.tracked_concat / tracked_contiguous "
                "(same array out, bytes accounted), or suppress with "
                "the reason",
            ))
        elif (f.attr == "copy" and not node.args and not node.keywords
                and isinstance(f.value, ast.Name)
                and _LANE_NAME_RE.search(f.value.id)):
            findings.append(Finding(
                node.lineno, "J024",
                f"lane `.copy()` on `{f.value.id}` in a data-plane "
                "module — an unaccounted buffer duplication; use "
                "memtrace.tracked_copy(arr, stage), or suppress with "
                "the reason",
            ))


def check_colblock_contract(tree: ast.Module,
                            findings: list[Finding]) -> None:
    """J025, over the zero-copy data-plane modules: a fresh numpy array
    (`np.array`/`np.asarray`/`np.frombuffer`/`np.copy`) materialized
    from a column block's data — either a `.lane(...)`-accessor argument
    or a block-named buffer — outside colblock.py's sanctioned APIs.
    Such a call silently re-materializes bytes the block already holds
    contiguous and aligned, and the lineage ledger never sees the copy.
    Calls nested inside colblock constructors/coercers or memtrace
    tracked_* helpers are sanctioned (the J024 wrapped-subtree
    technique)."""
    wrapped: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        tail = name.rsplit(".", 1)[-1]
        if ("colblock." in name or "memtrace." in name
                or name.startswith("tracked_")
                or tail in COLBLOCK_SANCTIONED_TAILS):
            for sub in ast.walk(node):
                wrapped.add(id(sub))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or id(node) in wrapped:
            continue
        name = dotted(node.func) or ""
        if name not in BLOCK_MATERIALIZE_CALLS:
            continue
        hit = None
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in BLOCK_LANE_ATTRS):
                    hit = f"a `.{sub.func.attr}(...)` accessor"
                    break
            if hit:
                break
        if hit is None and any(
            _BLOCK_NAME_RE.search(n) for n in arg_identifiers(node)
        ):
            hit = "a block-named buffer"
        if hit:
            findings.append(Finding(
                node.lineno, "J025",
                f"fresh numpy array `{name}(...)` materialized from "
                f"{hit} — the column block already holds those bytes "
                "contiguous and 64-byte aligned, and this duplication is "
                "invisible to the lineage ledger (the copy-tax verdict "
                "still reads view/reuse); consume the lane by reference, "
                "coerce through colblock.as_lane, duplicate through "
                "ColBlock.copy_lane / memtrace.tracked_copy, or suppress "
                "with the reason",
            ))


def check_visibility_boundary(tree: ast.Module,
                              findings: list[Finding]) -> None:
    """J010: attribute access on the visibility state's row-filtering
    fields (`.tombstones`, `.retention_floor_ms`) outside the shared
    helper. Keyword construction (`Visibility(tombstones=...)`) and the
    manifest's accessor methods (`all_tombstones()`) are deliberately NOT
    flagged — building/storing the state is fine; CONSUMING it for row
    filtering belongs in storage/visibility.apply_visibility alone."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in VISIBILITY_FIELDS:
            findings.append(Finding(
                node.lineno, "J010",
                f"`.{node.attr}` consumed outside storage/visibility.py — "
                "tombstone/retention row filtering must go through the "
                "shared apply_visibility helper (one funnel for every "
                "scan route, the downsample pushdown, and compaction), "
                "or deletes diverge between readers; suppress with the "
                "reason for harness introspection",
            ))
