"""Whole-program concurrency passes over the shared ProgramIndex.

J018 — event-loop blocking: a blocking primitive whose enclosing
function can run ON the event loop (async-reachability), reported at
the blocking site with the witness call chain back to a coroutine.

J019 — lock-order deadlock: (a) cycles among distinct lock identities
in the held-while-acquiring graph (every edge of a cyclic SCC gets a
finding, so both sides of an AB/BA inversion are visible); (b)
re-acquiring a non-reentrant lock through a pure `self.` call chain;
(c) `await` while holding a sync `threading` lock — the loop thread
parks inside the critical section and every other thread contending
for that lock stalls behind a suspended coroutine.

J020 — deadline-propagation completeness: loops in query-reachable
code that do heavy work (await, blocking op, kernel dispatch within
FRAME_DEPTH frames) but reach no `deadline.check`/`deadline_scope`
checkpoint within the same depth. Only the INNERMOST offending loop is
reported — placing a check there covers the enclosing loops too.
"""

from __future__ import annotations

from tools.jaxlint.base import Finding
from tools.jaxlint.program import LoopInfo, ProgramIndex

FRAME_DEPTH = 3
QUERY_SEEDS = {"query", "query_exemplars", "run_query",
               "run_query_exemplars"}


def check_event_loop_blocking(
        index: ProgramIndex) -> dict[str, list[Finding]]:
    """J018: blocking ops in on-loop functions -> {path: findings}."""
    out: dict[str, list[Finding]] = {}
    seen: set[tuple[str, int, str]] = set()
    for qname in index.on_loop:
        fi = index.functions[qname]
        for lineno, desc in fi.blocking:
            key = (fi.path, lineno, desc)
            if key in seen:
                continue
            seen.add(key)
            chain = index.witness_chain(qname)
            via = " <- ".join(q.rsplit(".", 1)[-1] for q in chain)
            out.setdefault(fi.path, []).append(Finding(
                lineno, "J018",
                f"{desc} blocks the event loop (reachable from a "
                f"coroutine: {via}); offload via asyncio.to_thread / "
                "run_in_executor or move off the async path",
            ))
    return out


def _sccs(nodes: set[str],
          edges: dict[tuple[str, str], tuple]) -> list[set[str]]:
    """Tarjan SCCs, iterative (lock graphs are tiny but cycles are the
    whole point, so no recursion-depth surprises)."""
    adj: dict[str, list[str]] = {n: [] for n in nodes}
    for (a, b) in edges:
        if a in adj and b in nodes:
            adj[a].append(b)
    idx: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    out: list[set[str]] = []

    for root in sorted(nodes):
        if root in idx:
            continue
        work: list[tuple[str, int]] = [(root, 0)]
        while work:
            node, child_i = work.pop()
            if child_i == 0:
                idx[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            for i in range(child_i, len(adj[node])):
                nxt = adj[node][i]
                if nxt not in idx:
                    work.append((node, i + 1))
                    work.append((nxt, 0))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], idx[nxt])
            if advanced:
                continue
            if low[node] == idx[node]:
                scc: set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                out.append(scc)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return out


def check_lock_order(index: ProgramIndex) -> dict[str, list[Finding]]:
    """J019 -> {path: findings}."""
    out: dict[str, list[Finding]] = {}
    nodes = {n for e in index.lock_edges for n in e}
    for scc in _sccs(nodes, index.lock_edges):
        if len(scc) < 2:
            continue
        cycle = " -> ".join(sorted(scc))
        for (a, b), (path, lineno, via) in sorted(
                index.lock_edges.items(),
                key=lambda kv: (kv[1][0], kv[1][1])):
            if a in scc and b in scc:
                out.setdefault(path, []).append(Finding(
                    lineno, "J019",
                    f"lock-order cycle {{{cycle}}}: this site acquires "
                    f"`{b}` while holding `{a}` (via {via}); another "
                    "path acquires them in the opposite order — fix a "
                    "global order or collapse to one lock",
                ))
    for lock, path, lineno, via in sorted(
            set(index.self_reacquires), key=lambda t: (t[1], t[2])):
        out.setdefault(path, []).append(Finding(
            lineno, "J019",
            f"re-acquires non-reentrant `{lock}` already held by this "
            f"call chain (via {via}) — self-deadlock; use the _locked "
            "variant of the callee or an RLock",
        ))
    for qname, fi in sorted(index.functions.items()):
        for lineno, lock in fi.awaits_under_sync_lock:
            out.setdefault(fi.path, []).append(Finding(
                lineno, "J019",
                f"`await` while holding sync threading lock `{lock}` — "
                "the event loop parks inside the critical section and "
                "other threads stall; release before awaiting or use "
                "asyncio.Lock",
            ))
    return out


def _query_reachable(index: ProgramIndex) -> set[str]:
    seeds = [q for q, fi in index.functions.items()
             if fi.name in QUERY_SEEDS]
    seen = set(seeds)
    queue = list(seeds)
    while queue:
        q = queue.pop()
        for cs in index.functions[q].calls:
            t = cs.target
            if cs.offload == "detached" or cs.deadline_free:
                continue  # spawned / deliberately shielded work is off
                # the query's deadline path
            if t and t in index.functions and t not in seen:
                if index.functions[t].detaches_deadline:
                    continue  # callee opts out (deadline_ctx.detach())
                seen.add(t)
                queue.append(t)
    return seen


def _loop_heavy(index: ProgramIndex, lp: LoopInfo) -> bool:
    if lp.has_await or lp.blocking:
        return True
    return any(
        cs.target and cs.offload != "detached"
        and index.reaches_heavy_work(cs.target, FRAME_DEPTH)
        for cs in lp.calls
    )


def _loop_checked(index: ProgramIndex, lp: LoopInfo) -> bool:
    if lp.has_check:
        return True
    return any(
        cs.target and cs.offload != "detached"
        and index.reaches_checkpoint(cs.target, FRAME_DEPTH)
        for cs in lp.calls
    )


def check_deadline_propagation(
        index: ProgramIndex) -> dict[str, list[Finding]]:
    """J020 -> {path: findings}."""
    out: dict[str, list[Finding]] = {}
    reachable = _query_reachable(index)
    for qname in sorted(reachable):
        fi = index.functions[qname]
        offending: list[LoopInfo] = [
            lp for lp in fi.loops
            if _loop_heavy(index, lp) and not _loop_checked(index, lp)
        ]
        offending_set = set(id(lp) for lp in offending)

        def has_offending_child(lp: LoopInfo) -> bool:
            return any(
                id(c) in offending_set or has_offending_child(c)
                for c in lp.children
            )

        for lp in offending:
            if has_offending_child(lp):
                continue  # report the innermost loop only
            out.setdefault(fi.path, []).append(Finding(
                lp.lineno, "J020",
                f"query-reachable loop in {fi.name}() does heavy work "
                "but no deadline checkpoint within "
                f"{FRAME_DEPTH} frames; add deadline_ctx.check(...) so "
                "slow queries cancel instead of running to completion",
            ))
    return out
