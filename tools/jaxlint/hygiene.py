"""J021 suppression hygiene, computed LAST from the raw (pre-
suppression) findings of every other pass: a `# jaxlint: disable=J0xx`
whose code no longer fires on the line it covers is stale — the
underlying finding was fixed (or the rule retired) and the suppression
now hides nothing except FUTURE regressions of unknown provenance.
Reported at the suppression comment's line. J000 (missing reason) also
lives here since it is a property of the suppression table, not of any
pass's findings."""

from __future__ import annotations

from tools.jaxlint.base import Finding, Suppressions
from tools.jaxlint.registry import BY_CODE


def check_suppression_hygiene(
        sup: Suppressions, raw: list[Finding]) -> list[Finding]:
    out: list[Finding] = []
    for lineno in sup.malformed:
        out.append(Finding(
            lineno, "J000",
            "suppression without a reason: write "
            "`# jaxlint: disable=J0xx <why this is intentional>`",
        ))
    fired: set[tuple[int, str]] = {(f.lineno, f.code) for f in raw}
    for lineno, (codes, reason) in sorted(sup.by_line.items()):
        if not reason:
            continue  # J000 above already demands a rewrite
        for code in sorted(codes):
            if code not in BY_CODE:
                out.append(Finding(
                    lineno, "J021",
                    f"suppression names unknown code {code} — "
                    "not in the check inventory",
                ))
                continue
            # a suppression on line L covers findings at L and L+1
            if (lineno, code) in fired or (lineno + 1, code) in fired:
                continue
            out.append(Finding(
                lineno, "J021",
                f"stale suppression: {code} does not fire here any "
                "more — delete the disable comment (fixed findings "
                "must not leave blanket immunity behind)",
            ))
    return out
