"""Shared primitives for every jaxlint pass: findings, suppressions,
dotted-name resolution, scope matching.

Kept dependency-free (stdlib `ast`/`re` only) so both the per-file
passes and the whole-program index build on one vocabulary.
"""

from __future__ import annotations

import ast
import re

SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=((?:J\d{3})(?:\s*,\s*J\d{3})*)(?:\s+(.+))?"
)


class Finding:
    __slots__ = ("lineno", "code", "msg")

    def __init__(self, lineno: int, code: str, msg: str):
        self.lineno, self.code, self.msg = lineno, code, msg

    def as_tuple(self) -> tuple[int, str, str]:
        return (self.lineno, self.code, self.msg)


class Suppressions:
    """Per-file `# jaxlint: disable=...` map (same line or line above).

    ``by_line`` maps comment line -> (codes, reason); ``malformed``
    lists reason-less comments (J000). The hygiene pass (J021) walks
    ``by_line`` directly to find suppressions whose line no longer
    triggers the named check.
    """

    def __init__(self, lines: list[str]):
        self.by_line: dict[int, tuple[set[str], str]] = {}
        self.malformed: list[int] = []
        for i, line in enumerate(lines, 1):
            m = SUPPRESS_RE.search(line)
            if not m:
                continue
            codes = {c.strip() for c in m.group(1).split(",")}
            reason = (m.group(2) or "").strip()
            if not reason:
                self.malformed.append(i)
            self.by_line[i] = (codes, reason)

    def covers(self, lineno: int, code: str) -> bool:
        for ln in (lineno, lineno - 1):
            ent = self.by_line.get(ln)
            if ent and code in ent[0] and ent[1]:
                return True
        return False

    def as_dict(self) -> dict:
        """JSON-serializable form for the incremental cache."""
        return {
            "by_line": {
                str(ln): [sorted(codes), reason]
                for ln, (codes, reason) in self.by_line.items()
            },
            "malformed": self.malformed,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Suppressions":
        self = cls([])
        self.by_line = {
            int(ln): (set(codes), reason)
            for ln, (codes, reason) in d.get("by_line", {}).items()
        }
        self.malformed = list(d.get("malformed", []))
        return self


def dotted(node: ast.AST) -> str | None:
    """`jax.numpy.full` -> "jax.numpy.full"; None for non-name chains."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_no_nested_defs(body: list[ast.stmt]):
    """Yield nodes of a function body WITHOUT descending into nested
    function/class definitions (those are visited separately, with
    their own context flags)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def arg_identifiers(node: ast.Call):
    """Every Name/Attribute identifier reachable from a call's args."""
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name):
                yield sub.id
            elif isinstance(sub, ast.Attribute):
                yield sub.attr


def in_scope(posix: str, prefixes: tuple[str, ...]) -> bool:
    """Path-scope test shared by every module-scoped rule: a prefix
    ending in "/" matches a directory component anywhere in the path;
    otherwise the path's tail must match exactly."""
    return any(
        (h.endswith("/") and f"/{h}" in f"/{posix}") or posix.endswith(h)
        for h in prefixes
    )


def scoped(posix: str, modules: tuple[str, ...],
           exempt: tuple[str, ...] = ()) -> bool:
    return in_scope(posix, modules) and not in_scope(posix, exempt)
