"""Per-file pass dispatcher: parses one file, applies every
path-scoped per-file rule (J001-J017, J022-J025), and returns RAW findings
plus
the file's suppression table. Suppression filtering happens in the
orchestrator (tools/jaxlint/__main__.py) AFTER the whole-program
passes run, so the hygiene pass (J021) can see which suppressions
actually fire."""

from __future__ import annotations

import ast
from pathlib import Path

from tools.jaxlint import funnels, jitrules, lockrules
from tools.jaxlint.base import Finding, Suppressions, in_scope, scoped


def parse_file(path: Path) -> tuple[str, ast.Module | None, Finding | None]:
    """(text, tree, syntax_finding). A syntax error yields tree=None and
    one J999 finding — the file is skipped by every other pass
    (including the whole-program index build)."""
    text = path.read_bytes().decode("utf-8", errors="replace")
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        return text, None, Finding(
            e.lineno or 1, "J999", f"syntax error: {e.msg}"
        )
    return text, tree, None


def run_perfile(path: Path, text: str,
                tree: ast.Module) -> tuple[list[Finding], Suppressions]:
    """All per-file rules over one parsed file -> raw findings."""
    sup = Suppressions(text.split("\n"))
    posix = path.as_posix()

    is_hot = in_scope(posix, jitrules.HOT_MODULES)
    in_dtype_scope = in_scope(posix, jitrules.DTYPE_MODULES)
    in_j007_scope = in_scope(posix, jitrules.J007_MODULES)
    in_j008_scope = scoped(posix, funnels.J008_MODULES, funnels.J008_EXEMPT)
    in_j009_scope = scoped(posix, funnels.J009_MODULES, funnels.J009_EXEMPT)
    in_j010_scope = scoped(posix, funnels.J010_MODULES, funnels.J010_EXEMPT)
    in_j011_scope = scoped(posix, funnels.J011_MODULES, funnels.J011_EXEMPT)
    in_j012_scope = scoped(posix, funnels.J012_MODULES, funnels.J012_EXEMPT)
    in_j013_base = in_scope(posix, funnels.J013_MODULES)
    j013_reads = in_j013_base and not in_scope(
        posix, funnels.J013_READ_EXEMPT)
    j013_writes = in_j013_base and not in_scope(
        posix, funnels.J013_WRITE_EXEMPT)
    in_j014_scope = scoped(posix, funnels.J014_MODULES, funnels.J014_EXEMPT)
    in_j015_scope = scoped(posix, funnels.J015_MODULES, funnels.J015_EXEMPT)
    in_j016_scope = scoped(posix, funnels.J016_MODULES, funnels.J016_EXEMPT)
    in_j017_base = in_scope(posix, funnels.J017_MODULES)
    j017_views = in_j017_base and not in_scope(
        posix, funnels.J017_VIEW_EXEMPT)
    j017_assign = in_j017_base and not in_scope(
        posix, funnels.J017_ASSIGN_EXEMPT)
    in_j022_scope = scoped(posix, funnels.J022_MODULES, funnels.J022_EXEMPT)
    in_j023_scope = scoped(posix, funnels.J023_MODULES, funnels.J023_EXEMPT)
    in_j024_scope = scoped(posix, funnels.J024_MODULES, funnels.J024_EXEMPT)
    in_j025_scope = scoped(posix, funnels.J025_MODULES, funnels.J025_EXEMPT)

    idx = jitrules.JitIndex()
    idx.visit(tree)
    idx.finish()

    findings: list[Finding] = []
    for fn in idx.jit_defs:
        jitrules.check_traced_body(fn, findings)
    if is_hot:
        jitrules.check_host_hot(tree, idx.jit_defs, findings)
    jitrules.check_jit_call_sites(tree, idx.bare_jit_names, findings)
    if in_dtype_scope:
        jitrules.check_dtype(tree, findings)
        if not any(posix.endswith(m) for m in jitrules.AGG_LANE_MODULES):
            jitrules.check_onehot(tree, findings)
    if in_j007_scope:
        jitrules.check_naked_jit(tree, findings)
    if in_j008_scope:
        funnels.check_append_hot_path(tree, findings)
    if in_j009_scope:
        funnels.check_store_boundary(tree, findings)
    if in_j010_scope:
        funnels.check_visibility_boundary(tree, findings)
    if in_j011_scope:
        funnels.check_admission_boundary(tree, findings)
    if in_j012_scope:
        funnels.check_decode_funnel(tree, findings)
    if j013_reads or j013_writes:
        funnels.check_serving_funnel(tree, findings, j013_reads, j013_writes)
    if in_j014_scope:
        funnels.check_funnel_subscribers(tree, findings)
    if in_j015_scope:
        funnels.check_metering_funnel(tree, findings)
    if in_j016_scope:
        funnels.check_stacking_funnel(tree, findings)
    if j017_views or j017_assign:
        funnels.check_cluster_funnel(tree, findings, j017_views, j017_assign)
    if in_j022_scope:
        funnels.check_traced_client_funnel(tree, findings)
    if in_j023_scope:
        funnels.check_partial_grid_funnel(tree, findings)
    if in_j024_scope:
        funnels.check_memtrace_funnel(tree, findings)
    if in_j025_scope:
        funnels.check_colblock_contract(tree, findings)
    lockrules.check_lock_discipline(tree, findings)
    return findings, sup
