"""Whole-program index shared by the graph passes (J018-J020).

Built ONCE per run over every analyzed file under a `horaedb_tpu`
package root, then handed to each graph pass:

- **module map** — file path -> dotted module name, per-module import
  aliases (absolute + relative), top-level symbols;
- **call graph** — call sites resolved to in-tree functions through
  plain names, module attributes, `self.`/`cls.` method dispatch,
  `self._attr.` dispatch via inferred attribute types
  (`self._attr = SomeClass(...)`), local-variable types
  (`x = SomeClass(...)`), nested `def` scopes, and the `xjit`/`jit`
  wrapper boundary (`kernel = xjit(fn)` calls resolve to `fn`);
- **offload edges** — callables handed to `asyncio.to_thread` /
  `run_in_executor` (awaited: the caller blocks but the callee runs
  OFF the event loop) and `executor.submit` / `threading.Thread`
  (detached: fire-and-forget);
- **async-reachability** — which functions can run ON the event loop
  (coroutines plus everything they call through non-offload edges);
- **lock-acquisition graph** — `with self._lock:` / module-level lock
  blocks resolved to class-qualified lock identities, direct nesting
  edges plus transitive held-while-acquiring edges through the call
  graph (awaited offloads included: the caller still holds the lock
  in wall-clock terms while the worker runs);
- **loop inventory** — every for/while/async-for with the calls,
  awaits, blocking ops, and deadline checkpoints its body contains.

Static identity notes (documented precision choices):
- A lock identity is `(Class, attr)` or `(module, name)` — instances
  collapse. Self-deadlock (re-acquiring the SAME identity) is only
  reported when every hop is a `self.` call in one class, so two
  *different* instances of one class locking each other are out of
  scope for the static pass (the dynamic lockwitness covers them).
- `.acquire()` calls are not tracked — the tree's idiom is the `with`
  block; a hand-rolled acquire/release pair evades the pass.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tools.jaxlint.base import dotted
from tools.jaxlint.jitrules import _is_jit_expr

LOCK_FACTORY_KINDS = {
    "Lock": ("threading", False),
    "RLock": ("threading", True),
    "Condition": ("threading", False),
    "Semaphore": ("threading", False),
    "BoundedSemaphore": ("threading", False),
}
OFFLOAD_AWAITED_TAILS = {"to_thread", "run_in_executor"}
OFFLOAD_DETACHED_TAILS = {"submit"}
# `asyncio.create_task(coro())` / `get_running_loop().create_task(...)`
# detaches: the spawned work is OFF the spawner's critical path (no lock
# holding, no deadline propagation — flush_executor._run detaches its
# deadline for exactly this reason). `tg.create_task(...)` TaskGroup
# children are awaited at scope exit and stay on the caller's path.
SPAWN_TAILS = {"create_task", "ensure_future"}

PARQUET_TAILS = {"read_table", "write_table", "write_to_dataset"}
PARQUET_CTORS = {"ParquetWriter", "ParquetFile"}
PARQUET_HEADS = {"pq", "parquet", "pyarrow"}
FILE_BLOCKING_CALLS = {
    "os.fsync", "os.replace", "os.rename", "os.link",
    "shutil.copyfile", "shutil.move", "shutil.rmtree",
}
PATH_IO_TAILS = {"read_bytes", "write_bytes", "read_text", "write_text"}
BLOCKING_PREFIXES = ("subprocess.", "urllib.request.", "requests.")
# deadline checkpoints, syntactic form: the `deadline_ctx.check(...)` /
# `deadline_scope(...)` idiom of horaedb_tpu/common/deadline.py
DEADLINE_MODULE_NAMES = {"deadline", "deadline_ctx"}


def module_name(path: Path) -> str | None:
    """Dotted module name for files under a `horaedb_tpu` package root;
    None for everything else (graph passes only see the engine tree —
    tools/ and benchmarks/ harnesses are per-file-pass territory)."""
    parts = list(path.with_suffix("").parts)
    if "horaedb_tpu" not in parts:
        return None
    parts = parts[parts.index("horaedb_tpu"):]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def blocking_desc(node: ast.Call, fd: str | None) -> str | None:
    """Event-loop-blocking primitives, syntactic prong (J018). The
    resolution-dependent prong (calls into the CPU-heavy codec funnel)
    is added in ProgramIndex.finish()."""
    if fd == "time.sleep":
        return "time.sleep()"
    if fd == "open":
        return "open()"
    if fd in FILE_BLOCKING_CALLS:
        return f"{fd}()"
    if fd:
        parts = fd.split(".")
        tail = parts[-1]
        if (tail in PARQUET_TAILS or tail in PARQUET_CTORS) and \
                parts[0] in PARQUET_HEADS:
            return f"parquet IO `{fd}(...)`"
        if fd.startswith(BLOCKING_PREFIXES):
            return f"`{fd}(...)`"
        if len(parts) > 1 and tail in PATH_IO_TAILS:
            return f"file IO `.{tail}()`"
        if tail == "result" and len(parts) > 1 and "fut" in parts[-2].lower():
            return "Future.result()"
    f = node.func
    if (
        isinstance(f, ast.Attribute) and f.attr == "join"
        and isinstance(f.value, ast.Constant)
        and isinstance(f.value.value, bytes)
    ):
        return "b''.join() accumulation"
    return None


class CallSite:
    __slots__ = ("lineno", "raw", "target", "offload", "held", "receiver",
                 "deadline_free")

    def __init__(self, lineno: int, raw: str | None, *,
                 offload: str | None = None,
                 held: tuple[str, ...] = (), receiver: str | None = None,
                 deadline_free: bool = False):
        self.lineno = lineno
        self.raw = raw                  # dotted call text, pre-resolution
        self.target: str | None = None  # resolved function qname
        self.offload = offload          # None | "awaited" | "detached"
        self.held = held                # lock ids held at the site
        self.receiver = receiver        # "self"/"cls" for self-dispatch
        # inside `with deadline_scope(None):` — the caller DELIBERATELY
        # shields this work from the request deadline (flush barriers):
        # J020 must not demand checkpoints below such a call
        self.deadline_free = deadline_free


class LoopInfo:
    __slots__ = ("lineno", "depth", "calls", "has_await", "has_check",
                 "blocking", "children")

    def __init__(self, lineno: int, depth: int):
        self.lineno = lineno
        self.depth = depth              # loop nesting depth in function
        self.calls: list[CallSite] = []
        self.has_await = False
        self.has_check = False
        self.blocking: list[tuple[int, str]] = []
        self.children: list[LoopInfo] = []


class Acquisition:
    __slots__ = ("lock", "lineno", "held", "via_self")

    def __init__(self, lock: str, lineno: int, held: tuple[str, ...],
                 via_self: bool):
        self.lock, self.lineno = lock, lineno
        self.held, self.via_self = held, via_self


class FuncInfo:
    __slots__ = (
        "qname", "module", "path", "node", "is_async", "cls_qname",
        "is_kernel", "is_checkpoint", "calls", "blocking", "acquires",
        "awaits_under_sync_lock", "loops", "has_check", "name",
        "detaches_deadline",
    )

    def __init__(self, qname: str, module: str, path: str, node,
                 cls_qname: str | None):
        self.qname = qname
        self.module = module
        self.path = path
        self.node = node
        self.name = node.name
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.cls_qname = cls_qname
        self.is_kernel = any(_is_jit_expr(d) for d in node.decorator_list)
        self.is_checkpoint = False
        self.calls: list[CallSite] = []
        self.blocking: list[tuple[int, str]] = []
        self.acquires: list[Acquisition] = []
        self.awaits_under_sync_lock: list[tuple[int, str]] = []
        self.loops: list[LoopInfo] = []
        self.has_check = False
        self.detaches_deadline = False  # calls deadline_ctx.detach()


class ClassInfo:
    __slots__ = ("qname", "module", "methods", "bases", "base_qnames",
                 "attr_types_raw", "attr_types", "lock_attrs",
                 "lock_returning_methods")

    def __init__(self, qname: str, module: str):
        self.qname = qname
        self.module = module
        self.methods: dict[str, str] = {}           # name -> func qname
        self.bases: list[str] = []                  # raw dotted names
        self.base_qnames: list[str] = []            # resolved, in-tree
        self.attr_types_raw: dict[str, str] = {}    # attr -> raw ctor name
        self.attr_types: dict[str, str] = {}        # attr -> class qname
        # attr -> (kind, reentrant): threading vs asyncio, RLock or not
        self.lock_attrs: dict[str, tuple[str, bool]] = {}
        self.lock_returning_methods: dict[str, str] = {}  # meth -> attr


class ModuleInfo:
    __slots__ = ("name", "path", "imports", "symbols", "aliases", "locks")

    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path
        self.imports: dict[str, str] = {}   # alias -> absolute dotted target
        self.symbols: dict[str, str] = {}   # top-level def/class -> qname
        self.aliases: dict[str, str] = {}   # name -> raw dotted (xjit(fn))
        self.locks: dict[str, tuple[str, bool]] = {}  # module-level locks


def _lock_kind(call: ast.Call) -> tuple[str, bool] | None:
    """(kind, reentrant) for `threading.Lock()` / `asyncio.Lock()` etc."""
    fd = dotted(call.func)
    if not fd:
        return None
    parts = fd.split(".")
    factory = parts[-1]
    if factory not in LOCK_FACTORY_KINDS:
        return None
    _, reentrant = LOCK_FACTORY_KINDS[factory]
    kind = "asyncio" if "asyncio" in parts or "aio" in parts else "threading"
    return kind, reentrant


class ProgramIndex:
    """The shared whole-program index. Build with add_file() per parsed
    module, then finish() resolves the call graph and derived maps."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        # lock id -> (kind, reentrant)
        self.locks: dict[str, tuple[str, bool]] = {}
        # lock-order edges: (holder, acquired) -> witness
        # witness: (path, lineno, via: str)
        self.lock_edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        # self-chain re-acquisitions: (lock, path, lineno, via)
        self.self_reacquires: list[tuple[str, str, int, str]] = []
        self.on_loop: dict[str, str | None] = {}  # qname -> predecessor

    # ------------------------------------------------------------ build

    def add_file(self, path: Path, tree: ast.Module) -> None:
        mod = module_name(path)
        if mod is None or mod in self.modules:
            return
        mi = ModuleInfo(mod, path.as_posix())
        self.modules[mod] = mi
        package = mod if path.stem == "__init__" else mod.rpartition(".")[0]

        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    mi.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg_parts = package.split(".") if package else []
                    up = node.level - 1
                    pkg_parts = pkg_parts[:len(pkg_parts) - up] if up else \
                        pkg_parts
                    base = ".".join(p for p in [".".join(pkg_parts), base]
                                    if p)
                for a in node.names:
                    if a.name == "*":
                        continue
                    tgt = f"{base}.{a.name}" if base else a.name
                    mi.imports[a.asname or a.name] = tgt
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(node, mi, None, [])
            elif isinstance(node, ast.ClassDef):
                self._add_class(node, mi)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if isinstance(node.value, ast.Call):
                    lk = _lock_kind(node.value)
                    if lk:
                        mi.locks[name] = lk
                        self.locks[f"{mod}.{name}"] = lk
                        continue
                    # `kernel = xjit(fn)` / `jax.jit(fn)` / partial(fn,..)
                    fv = node.value
                    if _is_jit_expr(fv.func) and fv.args:
                        inner = dotted(fv.args[0])
                        if inner:
                            mi.aliases[name] = inner
                    elif dotted(fv.func) in ("partial", "functools.partial") \
                            and fv.args:
                        inner = dotted(fv.args[0])
                        if inner:
                            mi.aliases[name] = inner
                elif isinstance(node.value, ast.Name):
                    mi.aliases[name] = node.value.id

    def _add_class(self, node: ast.ClassDef, mi: ModuleInfo) -> None:
        qname = f"{mi.name}.{node.name}"
        ci = ClassInfo(qname, mi.name)
        self.classes[qname] = ci
        mi.symbols[node.name] = qname
        for b in node.bases:
            bd = dotted(b)
            if bd:
                ci.bases.append(bd)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[item.name] = f"{qname}.{item.name}"
                self._add_function(item, mi, ci, [])
            elif isinstance(item, ast.Assign) and len(item.targets) == 1 \
                    and isinstance(item.targets[0], ast.Name) \
                    and isinstance(item.value, ast.Call):
                lk = _lock_kind(item.value)
                if lk:
                    ci.lock_attrs[item.targets[0].id] = lk
        # attribute inference over every method body: lock attrs, types,
        # and `return self._x` lock-returning accessors (the flush
        # executor's lazy `_condition()` idiom)
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # annotated params: `def __init__(self, storage: ObjectStore)`
            # followed by `self._store = storage` types the attribute
            ann: dict[str, str] = {}
            for a in (item.args.posonlyargs + item.args.args
                      + item.args.kwonlyargs):
                if a.annotation is None:
                    continue
                d = dotted(a.annotation)
                if d is None and isinstance(a.annotation, ast.Constant) \
                        and isinstance(a.annotation.value, str):
                    d = a.annotation.value  # string annotation
                if d:
                    ann[a.arg] = d
            for sub in ast.walk(item):
                t = value = None
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t, value = sub.targets[0], sub.value
                elif isinstance(sub, ast.AnnAssign) and \
                        sub.value is not None:
                    t, value = sub.target, sub.value
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if isinstance(value, ast.Call):
                    lk = _lock_kind(value)
                    if lk:
                        ci.lock_attrs[t.attr] = lk
                    else:
                        ctor = dotted(value.func)
                        if ctor:
                            ci.attr_types_raw.setdefault(t.attr, ctor)
                elif isinstance(value, ast.Name) and value.id in ann:
                    ci.attr_types_raw.setdefault(t.attr, ann[value.id])
            for stmt in item.body:
                if isinstance(stmt, ast.Return) and \
                        isinstance(stmt.value, ast.Attribute) and \
                        isinstance(stmt.value.value, ast.Name) and \
                        stmt.value.value.id == "self":
                    ci.lock_returning_methods[item.name] = stmt.value.attr

    def _add_function(self, node, mi: ModuleInfo, ci: ClassInfo | None,
                      outer: list[str]) -> FuncInfo:
        if ci is not None:
            qname = f"{ci.qname}.{node.name}"
        elif outer:
            qname = f"{outer[-1]}.<locals>.{node.name}"
        else:
            qname = f"{mi.name}.{node.name}"
            mi.symbols.setdefault(node.name, qname)
        fi = FuncInfo(qname, mi.name, mi.path, node, ci.qname if ci else None)
        if mi.name.endswith(".deadline") and \
                node.name in ("check", "deadline_scope"):
            fi.is_checkpoint = True
        self.functions[qname] = fi
        return fi

    # --------------------------------------------------------- resolve

    def _mro(self, cls_qname: str, _seen=None) -> list[str]:
        seen = _seen or set()
        if cls_qname in seen or cls_qname not in self.classes:
            return []
        seen.add(cls_qname)
        out = [cls_qname]
        for b in self.classes[cls_qname].base_qnames:
            out.extend(self._mro(b, seen))
        return out

    def _method(self, cls_qname: str, name: str) -> str | None:
        for c in self._mro(cls_qname):
            m = self.classes[c].methods.get(name)
            if m:
                return m
        return None

    def _attr_type(self, cls_qname: str, attr: str) -> str | None:
        for c in self._mro(cls_qname):
            t = self.classes[c].attr_types.get(attr)
            if t:
                return t
        return None

    def _lock_attr(self, cls_qname: str, attr: str) \
            -> tuple[str, bool] | None:
        for c in self._mro(cls_qname):
            lk = self.classes[c].lock_attrs.get(attr)
            if lk:
                return lk
        return None

    def _resolve_module_name(self, mod: str, raw: str) -> str | None:
        """Resolve a raw dotted name in a module's namespace to a
        function qname ("f") or class qname ("C" -> its __init__)."""
        parts = raw.split(".")
        mi = self.modules.get(mod)
        if mi is None:
            return None
        head, rest = parts[0], parts[1:]
        base: str | None = None
        if head in mi.symbols:
            base = mi.symbols[head]
        elif head in mi.aliases and head not in mi.imports:
            # one aliasing hop (`kernel = xjit(fn)`): resolve the inner
            inner = mi.aliases[head]
            return self._resolve_module_name(
                mod, ".".join([inner] + rest))
        elif head in mi.imports:
            base = mi.imports[head]
        else:
            return None
        full = ".".join([base] + rest)
        return self._canonical(full)

    def _canonical(self, full: str) -> str | None:
        """Map an absolute dotted name to a known function qname."""
        if full in self.functions:
            return full
        if full in self.classes:
            return self.classes[full].methods.get("__init__", full)
        head, _, tail = full.rpartition(".")
        if head in self.classes:
            return self._method(head, tail)
        # `from pkg import sym` where pkg re-exports: try one more level
        # through the imported module's own import table
        if head in self.modules:
            mi = self.modules[head]
            if tail in mi.imports:
                return self._canonical(mi.imports[tail])
            if tail in mi.aliases:
                return self._resolve_module_name(head, mi.aliases[tail])
        return None

    def _resolve_call(self, fi: FuncInfo, raw: str,
                      scopes: list[dict[str, str]],
                      local_types: dict[str, str]) -> str | None:
        parts = raw.split(".")
        head, rest = parts[0], parts[1:]
        if head in ("self", "cls") and fi.cls_qname:
            if not rest:
                return None
            if len(rest) == 1:
                return self._method(fi.cls_qname, rest[0])
            t = self._attr_type(fi.cls_qname, rest[0])
            if t and len(rest) == 2:
                return self._method(t, rest[1])
            return None
        for scope in reversed(scopes):
            if head in scope:
                return self._canonical(".".join([scope[head]] + rest)) \
                    or (scope[head] if not rest else None)
        t = local_types.get(head)
        if t and len(rest) == 1:
            return self._method(t, rest[0])
        return self._resolve_module_name(fi.module, raw)

    def _class_of(self, mod: str, raw: str) -> str | None:
        """Resolve a ctor name to a class qname (for type inference)."""
        mi = self.modules.get(mod)
        if mi is None:
            return None
        parts = raw.split(".")
        head, rest = parts[0], parts[1:]
        base = mi.symbols.get(head) or mi.imports.get(head)
        if base is None:
            return None
        full = ".".join([base] + rest)
        return full if full in self.classes else None

    # ----------------------------------------------------------- walk

    def finish(self) -> None:
        # resolve class bases + attribute types
        for ci in self.classes.values():
            for b in ci.bases:
                q = self._class_of(ci.module, b)
                if q:
                    ci.base_qnames.append(q)
        for ci in self.classes.values():
            for attr, raw in ci.attr_types_raw.items():
                q = self._class_of(ci.module, raw)
                if q:
                    ci.attr_types[attr] = q
            # lock-returning accessors must return an actual lock attr
            ci.lock_returning_methods = {
                m: a for m, a in ci.lock_returning_methods.items()
                if self._lock_attr(ci.qname, a)
            }
        # register lock identities
        for ci in self.classes.values():
            for attr, lk in ci.lock_attrs.items():
                self.locks[f"{ci.qname}.{attr}"] = lk
        # walk every function body: call sites, locks, loops, blocking
        for fi in list(self.functions.values()):
            if "<locals>" in fi.qname:
                continue  # walked by its parent
            self._walk_function(fi, [])
        # resolve call targets
        for fi in self.functions.values():
            local_types = self._infer_local_types(fi)
            scopes = self._scope_chain(fi)
            for cs in fi.calls:
                if cs.raw:
                    cs.target = self._resolve_call(
                        fi, cs.raw, scopes, local_types)
        self._propagate_async_reachability()
        self._build_lock_edges()

    def _scope_chain(self, fi: FuncInfo) -> list[dict[str, str]]:
        """Nested-def name maps from enclosing functions, outer first."""
        chain: list[dict[str, str]] = []
        parts = fi.qname.split(".<locals>.")
        for i in range(1, len(parts) + 1):
            prefix = ".<locals>.".join(parts[:i])
            scope = {
                q.rsplit(".", 1)[-1]: q
                for q in self.functions
                if q.startswith(prefix + ".<locals>.")
                and "<locals>" not in q[len(prefix) + len(".<locals>."):]
            }
            if scope:
                chain.append(scope)
        return chain

    def _infer_local_types(self, fi: FuncInfo) -> dict[str, str]:
        out: dict[str, str] = {}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                v = node.value
                if isinstance(v, ast.Call):
                    ctor = dotted(v.func)
                    if ctor:
                        q = self._class_of(fi.module, ctor)
                        if q:
                            out[name] = q
                elif isinstance(v, ast.Attribute) and \
                        isinstance(v.value, ast.Name) and \
                        v.value.id == "self" and fi.cls_qname:
                    t = self._attr_type(fi.cls_qname, v.attr)
                    if t:
                        out[name] = t
        return out

    def _lock_id_of(self, fi: FuncInfo, ctx: ast.expr) \
            -> tuple[str, str, bool, bool] | None:
        """(lock_id, kind, reentrant, via_self) for a with-item context
        expression, or None when it isn't a recognized lock."""
        if isinstance(ctx, ast.Attribute) and \
                isinstance(ctx.value, ast.Name) and \
                ctx.value.id in ("self", "cls") and fi.cls_qname:
            lk = self._lock_attr(fi.cls_qname, ctx.attr)
            if lk:
                return (f"{fi.cls_qname}.{ctx.attr}", lk[0], lk[1], True)
        elif isinstance(ctx, ast.Name):
            mi = self.modules.get(fi.module)
            if mi and ctx.id in mi.locks:
                lk = mi.locks[ctx.id]
                return (f"{fi.module}.{ctx.id}", lk[0], lk[1], False)
        elif isinstance(ctx, ast.Call):
            fd = dotted(ctx.func)
            if fd and fd.startswith(("self.", "cls.")) and fi.cls_qname:
                meth = fd.split(".")[1]
                for c in self._mro(fi.cls_qname):
                    attr = self.classes[c].lock_returning_methods.get(meth)
                    if attr:
                        lk = self._lock_attr(fi.cls_qname, attr)
                        if lk:
                            return (f"{fi.cls_qname}.{attr}",
                                    lk[0], lk[1], True)
        return None

    def _walk_function(self, fi: FuncInfo, outer_qnames: list[str]) -> None:
        loop_stack: list[LoopInfo] = []
        lock_stack: list[tuple[str, str, bool, bool]] = []
        detached_args: set[int] = set()  # Call nodes spawned detached
        dl_free = [0]  # nesting depth of `with deadline_scope(None):`
        # generator bindings: `gen = obj.scan(...)` or `async with
        # aclosing(obj.scan(...)) as gen:` — `async for _ in gen:` drives
        # the bound expression's calls PER-ITERATION, so those call sites
        # belong to the driving loop (their deadline checkpoints count)
        gen_bindings: dict[str, list[CallSite]] = {}

        def add_call(node: ast.Call) -> None:
            fd = dotted(node.func)
            held = tuple(lid for lid, _, _, _ in lock_stack)
            receiver = None
            if fd and fd.split(".")[0] in ("self", "cls"):
                receiver = "self"
            if fd:
                tail = fd.rsplit(".", 1)[-1]
            elif isinstance(node.func, ast.Attribute):
                tail = node.func.attr  # e.g. get_running_loop().create_task
            else:
                tail = None
            if tail in SPAWN_TAILS and (fd is None
                                        or fd.startswith("asyncio.")):
                for arg in node.args:
                    if isinstance(arg, ast.Call) and dotted(arg.func):
                        detached_args.add(id(arg))
            offload_args: list[tuple[ast.expr, str]] = []
            if tail in OFFLOAD_AWAITED_TAILS:
                pos = 0 if tail == "to_thread" else 1
                if len(node.args) > pos:
                    offload_args.append((node.args[pos], "awaited"))
            elif tail in OFFLOAD_DETACHED_TAILS and node.args:
                offload_args.append((node.args[0], "detached"))
            elif tail == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        offload_args.append((kw.value, "detached"))
            for expr, kind in offload_args:
                if isinstance(expr, ast.Call) and \
                        dotted(expr.func) in ("partial", "functools.partial") \
                        and expr.args:
                    expr = expr.args[0]
                od = dotted(expr)
                if od:
                    ocs = CallSite(node.lineno, od, offload=kind, held=held,
                                   deadline_free=dl_free[0] > 0)
                    fi.calls.append(ocs)
                    for lp in loop_stack:
                        lp.calls.append(ocs)
            cs = CallSite(node.lineno, fd, held=held, receiver=receiver,
                          offload="detached" if id(node) in detached_args
                          else None,
                          deadline_free=dl_free[0] > 0)
            fi.calls.append(cs)
            for lp in loop_stack:
                lp.calls.append(cs)
            desc = blocking_desc(node, fd)
            if desc is not None:
                fi.blocking.append((node.lineno, desc))
                for lp in loop_stack:
                    lp.blocking.append((node.lineno, desc))
            if fd:
                parts = fd.split(".")
                if (parts[-1] == "check"
                        and (len(parts) == 1
                             or parts[-2] in DEADLINE_MODULE_NAMES
                             or "deadline" in parts[-2] or parts[-2] == "dl")
                        ) or parts[-1] == "deadline_scope":
                    fi.has_check = True
                    for lp in loop_stack:
                        lp.has_check = True
                if parts[-1] == "detach" and len(parts) > 1 and \
                        "deadline" in parts[-2]:
                    fi.detaches_deadline = True

        def visit(nodes) -> None:
            for node in nodes:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    mi = self.modules[fi.module]
                    child = self._add_function(
                        node, mi, None, outer_qnames + [fi.qname])
                    # local defs start with no inherited lock/loop context
                    self._walk_function(child, outer_qnames + [fi.qname])
                    continue
                if isinstance(node, (ast.Lambda, ast.ClassDef)):
                    continue
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    acquired: list[tuple[str, str, bool, bool]] = []
                    shields = 0
                    for item in node.items:
                        ctx = item.context_expr
                        if isinstance(ctx, ast.Call):
                            before = len(fi.calls)
                            visit([ctx])
                            if isinstance(item.optional_vars, ast.Name):
                                gen_bindings[item.optional_vars.id] = \
                                    fi.calls[before:]
                            cfd = dotted(ctx.func) or ""
                            if cfd.rsplit(".", 1)[-1] == "deadline_scope" \
                                    and ctx.args \
                                    and isinstance(ctx.args[0], ast.Constant) \
                                    and ctx.args[0].value is None:
                                shields += 1
                        lid = self._lock_id_of(fi, ctx)
                        if lid:
                            held = tuple(
                                x[0] for x in lock_stack)
                            fi.acquires.append(Acquisition(
                                lid[0], node.lineno, held, lid[3]))
                            acquired.append(lid)
                            lock_stack.append(lid)
                    dl_free[0] += shields
                    visit(node.body)
                    dl_free[0] -= shields
                    for _ in acquired:
                        lock_stack.pop()
                    continue
                if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    lp = LoopInfo(node.lineno, len(loop_stack))
                    if loop_stack:
                        loop_stack[-1].children.append(lp)
                    fi.loops.append(lp)
                    if isinstance(node, ast.AsyncFor):
                        lp.has_await = True
                        for outer_lp in loop_stack:
                            outer_lp.has_await = True
                    loop_stack.append(lp)
                    if isinstance(node, ast.AsyncFor):
                        # an async generator's body runs per-iteration,
                        # interleaved with the loop — its calls (and any
                        # deadline checkpoints inside it) belong to the
                        # loop for J018/J020 purposes
                        visit([node.iter, node.target])
                        if isinstance(node.iter, ast.Name):
                            for bcs in gen_bindings.get(node.iter.id, ()):
                                for outer_lp in loop_stack:
                                    outer_lp.calls.append(bcs)
                    elif isinstance(node, ast.For):
                        # a plain iterable evaluates once, OUTSIDE
                        loop_stack.pop()
                        visit([node.iter])
                        loop_stack.append(lp)
                        visit([node.target])
                    else:
                        visit([node.test])
                    visit(node.body)
                    loop_stack.pop()
                    visit(node.orelse)
                    continue
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    before = len(fi.calls)
                    visit([node.value])
                    if len(fi.calls) > before:
                        gen_bindings[node.targets[0].id] = fi.calls[before:]
                    continue
                if isinstance(node, ast.Await):
                    for lid, kind, reentrant, _ in lock_stack:
                        if kind == "threading":
                            fi.awaits_under_sync_lock.append(
                                (node.lineno, lid))
                    for lp in loop_stack:
                        lp.has_await = True
                elif isinstance(node, ast.Call):
                    add_call(node)
                visit(ast.iter_child_nodes(node))

        visit(fi.node.body)

    # ------------------------------------------------- derived queries

    def _propagate_async_reachability(self) -> None:
        """on_loop: functions that can execute ON the event loop — every
        coroutine, plus everything reached through non-offload edges.
        Values form a predecessor map for witness chains."""
        queue: list[str] = []
        for q, fi in self.functions.items():
            if fi.is_async:
                self.on_loop[q] = None
                queue.append(q)
        while queue:
            q = queue.pop()
            for cs in self.functions[q].calls:
                t = cs.target
                if t is None or cs.offload is not None:
                    continue
                if t in self.functions and t not in self.on_loop:
                    self.on_loop[t] = q
                    queue.append(t)

    def witness_chain(self, qname: str, limit: int = 6) -> list[str]:
        """qname's call chain back to an async root, innermost first."""
        out = [qname]
        cur = self.on_loop.get(qname)
        while cur is not None and len(out) < limit:
            out.append(cur)
            cur = self.on_loop.get(cur)
        return out

    def _build_lock_edges(self) -> None:
        """Direct + transitive held-while-acquiring edges, and the
        self-chain re-acquisition list (same identity, same instance)."""
        # transitive lock sets: locks a call to f may acquire (via any
        # chain of calls, offload-awaited edges included)
        trans: dict[str, set[str]] = {
            q: {a.lock for a in fi.acquires}
            for q, fi in self.functions.items()
        }
        # self-chain variant: acquisitions via `self.` reached through
        # `self.` calls only (same instance by construction)
        self_trans: dict[str, set[str]] = {
            q: {a.lock for a in fi.acquires if a.via_self}
            for q, fi in self.functions.items()
        }
        changed = True
        while changed:
            changed = False
            for q, fi in self.functions.items():
                for cs in fi.calls:
                    t = cs.target
                    if t is None or t not in self.functions or \
                            cs.offload == "detached":
                        continue
                    add = trans[t] - trans[q]
                    if add:
                        trans[q] |= add
                        changed = True
                    if cs.receiver == "self" and \
                            self.functions[t].cls_qname and \
                            fi.cls_qname and \
                            self._same_class_family(fi.cls_qname,
                                                    self.functions[t]
                                                    .cls_qname):
                        sadd = self_trans[t] - self_trans[q]
                        if sadd:
                            self_trans[q] |= sadd
                            changed = True
        for q, fi in self.functions.items():
            # direct nesting edges
            for a in fi.acquires:
                for h in a.held:
                    if h == a.lock:
                        continue
                    self.lock_edges.setdefault(
                        (h, a.lock), (fi.path, a.lineno, fi.qname))
            # transitive edges through calls made while holding
            for cs in fi.calls:
                t = cs.target
                if t is None or t not in self.functions or \
                        cs.offload == "detached" or not cs.held:
                    continue
                for h in cs.held:
                    for acq in trans[t]:
                        if acq == h:
                            # same identity: only a real re-acquire when
                            # the whole chain stays on one instance
                            if cs.receiver == "self" and \
                                    acq in self_trans.get(t, ()):
                                kind, reentrant = self.locks.get(
                                    acq, ("threading", False))
                                if not reentrant:
                                    self.self_reacquires.append(
                                        (acq, fi.path, cs.lineno, t))
                            continue
                        self.lock_edges.setdefault(
                            (h, acq), (fi.path, cs.lineno, t))

    def _same_class_family(self, a: str, b: str) -> bool:
        return a == b or b in self._mro(a) or a in self._mro(b)

    # frame-bounded reachability helpers for J020
    def reaches_checkpoint(self, qname: str, depth: int) -> bool:
        fi = self.functions.get(qname)
        if fi is None:
            return False
        if fi.has_check or fi.is_checkpoint:
            return True
        if depth <= 0:
            return False
        return any(
            cs.target and cs.offload != "detached"
            and self.reaches_checkpoint(cs.target, depth - 1)
            for cs in fi.calls
        )

    def reaches_heavy_work(self, qname: str, depth: int) -> bool:
        fi = self.functions.get(qname)
        if fi is None:
            return False
        if fi.blocking or fi.is_kernel:
            return True
        if any(True for _ in fi.awaits_under_sync_lock):
            return True
        if fi.is_async and (fi.calls or fi.loops):
            return True
        if depth <= 0:
            return False
        return any(
            cs.target and cs.offload != "detached"
            and self.reaches_heavy_work(cs.target, depth - 1)
            for cs in fi.calls
        )
