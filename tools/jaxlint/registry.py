"""Check inventory: one row per rule, derived from the pass modules'
own scoping constants. This is the single source of truth for

- the `--check-index` CLI output (markdown) that docs/static-analysis.md
  embeds verbatim — tests/test_jaxlint_engine.py asserts the docs table
  matches, so docs cannot drift from the implementation;
- `inventory_digest()`, the cache key component that invalidates every
  cached lint result when any pass source changes.
"""

from __future__ import annotations

import hashlib
from pathlib import Path

from tools.jaxlint import funnels, jitrules


class Check:
    __slots__ = ("code", "title", "kind", "modules", "exempt", "summary")

    def __init__(self, code: str, title: str, kind: str,
                 modules: tuple[str, ...], exempt: tuple[str, ...],
                 summary: str):
        self.code = code
        self.title = title
        self.kind = kind          # perfile | graph | hygiene | meta
        self.modules = modules    # scope ("tree" rows apply everywhere)
        self.exempt = exempt
        self.summary = summary


def _t(x) -> tuple[str, ...]:
    return tuple(dict.fromkeys(x))  # display dedupe, order-preserving


CHECKS: list[Check] = [
    Check("J000", "suppression reason", "meta", ("tree",), (),
          "every `# jaxlint: disable=` must name codes AND a reason"),
    Check("J001", "host sync on hot path", "perfile", ("tree",), (),
          ".item()/device_get/block_until_ready inside jit bodies "
          "(tree-wide) and in the hot modules"),
    Check("J002", "retrace hazard", "perfile", ("tree",), (),
          "trace-time-frozen time/random/print under jit; untraceable "
          "static args without static_argnums"),
    Check("J003", "dtype drift", "perfile", _t(jitrules.DTYPE_MODULES), (),
          "bare float literal into jnp.array/jnp.full without dtype= "
          "in engine code"),
    Check("J004", "lock discipline", "perfile", ("tree",), (),
          "public method mutates lock-guarded state outside the lock"),
    Check("J005", "host timer in jit body", "perfile", ("tree",), (),
          "scanstats/tracing span opened inside a traced body — times "
          "the trace, not the kernel"),
    Check("J006", "agg lane registry", "perfile",
          _t(jitrules.DTYPE_MODULES), _t(jitrules.AGG_LANE_MODULES),
          "host ufunc lanes under jit / one-hot materializations "
          "outside the aggregation registry"),
    Check("J007", "naked jit", "perfile", _t(jitrules.J007_MODULES), (),
          "`jax.jit` used directly instead of the `xjit` wrapper"),
    Check("J008", "append hot path", "perfile", _t(funnels.J008_MODULES),
          _t(funnels.J008_EXEMPT),
          "parquet encode / object-store put on the append path "
          "outside the flush executor"),
    Check("J009", "store boundary", "perfile", _t(funnels.J009_MODULES),
          _t(funnels.J009_EXEMPT),
          "concrete store constructed outside a ResilientStore wrap"),
    Check("J010", "visibility funnel", "perfile",
          _t(funnels.J010_MODULES), _t(funnels.J010_EXEMPT),
          "tombstone/retention filtering outside apply_visibility"),
    Check("J011", "admission funnel", "perfile",
          _t(funnels.J011_MODULES), _t(funnels.J011_EXEMPT),
          "server handler calling engine.query without the admission "
          "scheduler"),
    Check("J012", "decode funnel", "perfile", _t(funnels.J012_MODULES),
          _t(funnels.J012_EXEMPT),
          "segment decode outside the storage codec funnel"),
    Check("J013", "serving funnel", "perfile", _t(funnels.J013_MODULES),
          _t(funnels.J013_READ_EXEMPT + funnels.J013_WRITE_EXEMPT),
          "serving-cache reads/writes outside the serving module"),
    Check("J014", "funnel subscribers", "perfile",
          _t(funnels.J014_MODULES), _t(funnels.J014_EXEMPT),
          "commit-event subscribers registered outside wiring modules"),
    Check("J015", "metering funnel", "perfile", _t(funnels.J015_MODULES),
          _t(funnels.J015_EXEMPT),
          "usage metering recorded outside the metering module"),
    Check("J016", "stacking funnel", "perfile", _t(funnels.J016_MODULES),
          _t(funnels.J016_EXEMPT),
          "grid stacking/padding outside the batcher funnel"),
    Check("J017", "cluster funnel", "perfile", _t(funnels.J017_MODULES),
          _t(funnels.J017_VIEW_EXEMPT + funnels.J017_ASSIGN_EXEMPT),
          "manifest views / assignment-record writes outside the "
          "cluster funnels"),
    Check("J018", "event-loop blocking", "graph", ("horaedb_tpu",), (),
          "blocking primitive (sleep, file/parquet IO, byte-join "
          "materialization) transitively reachable from a coroutine "
          "without to_thread/run_in_executor offload"),
    Check("J019", "lock-order deadlock", "graph", ("horaedb_tpu",), (),
          "cycle in the cross-module lock-acquisition graph, "
          "non-reentrant re-acquire through self-dispatch, or `await` "
          "while holding a sync threading lock"),
    Check("J020", "deadline propagation", "graph", ("horaedb_tpu",), (),
          "query-reachable loop doing heavy work with no "
          "deadline.check/deadline_scope within bounded frame depth"),
    Check("J021", "suppression hygiene", "hygiene", ("tree",), (),
          "suppression names a code that no longer fires on that line "
          "(stale) — delete it when the underlying finding is fixed"),
    Check("J022", "traced client funnel", "perfile",
          _t(funnels.J022_MODULES), _t(funnels.J022_EXEMPT),
          "outbound cluster-tier HTTP (client session construction or "
          "verb call) outside the router's traced_request funnel"),
    Check("J023", "partial-grid funnel", "perfile",
          _t(funnels.J023_MODULES), _t(funnels.J023_EXEMPT),
          "partial-grid wire codec/merge name redefined, or in-place "
          "ufunc grid fold, outside cluster/partial.py"),
    Check("J024", "memtrace funnel", "perfile",
          _t(funnels.J024_MODULES), _t(funnels.J024_EXEMPT),
          "raw concat_tables/combine_chunks/np.concatenate/"
          "np.ascontiguousarray or lane .copy() in data-plane modules "
          "outside the common/memtrace tracked_* accounting funnel"),
    Check("J025", "column-block contract", "perfile",
          _t(funnels.J025_MODULES), _t(funnels.J025_EXEMPT),
          "fresh numpy array materialized from a column block's lanes "
          "(np.array/np.asarray/np.frombuffer/np.copy over .lane(...) "
          "or block-named buffers) outside colblock.py's sanctioned "
          "accessors"),
    Check("J999", "syntax error", "meta", ("tree",), (),
          "file fails to parse; every other pass skips it"),
]

BY_CODE: dict[str, Check] = {c.code: c for c in CHECKS}


def check_index_markdown() -> str:
    """The check-index table embedded in docs/static-analysis.md."""
    lines = [
        "| code | title | kind | scope | exemptions |",
        "|------|-------|------|-------|------------|",
    ]
    for c in CHECKS:
        scope = ", ".join(f"`{m}`" for m in c.modules)
        exempt = ", ".join(f"`{e}`" for e in c.exempt) or "—"
        lines.append(
            f"| {c.code} | {c.title} | {c.kind} | {scope} | {exempt} |")
    return "\n".join(lines)


def check_index_json() -> list[dict]:
    return [
        {"code": c.code, "title": c.title, "kind": c.kind,
         "modules": list(c.modules), "exempt": list(c.exempt),
         "summary": c.summary}
        for c in CHECKS
    ]


def inventory_digest() -> str:
    """Digest over every pass source file in this package: ANY change to
    the linter invalidates ALL cached per-file and tree results."""
    h = hashlib.sha256()
    pkg = Path(__file__).resolve().parent
    for src in sorted(pkg.glob("*.py")):
        h.update(src.name.encode())
        h.update(src.read_bytes())
    return h.hexdigest()
