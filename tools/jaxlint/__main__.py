"""jaxlint orchestrator: `python -m tools.jaxlint [roots...] [flags]`.

Run order per invocation:
1. read every file once, hash it, consult the incremental cache;
2. per-file passes (J001-J017, J999) on cache misses only;
3. whole-program passes (J018-J020) over the shared ProgramIndex —
   skipped entirely when the tree digest matches the cached one;
4. suppression filtering LAST, so the hygiene pass (J021/J000) sees
   which suppressions actually cover a live finding.

Flags: --json (machine-readable findings), --changed (report only
files differing from git HEAD), --no-cache, --budget SECONDS (fail if
the run exceeds the wall-clock budget), --check-index (print the check
inventory and exit — docs/static-analysis.md embeds this table).

Exit code: min(number of findings, 125); 99 on budget breach.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

from tools.jaxlint import concurrency, hygiene, registry
from tools.jaxlint.base import Finding, Suppressions
from tools.jaxlint.cache import LintCache, file_digest, tree_digest
from tools.jaxlint.perfile import parse_file, run_perfile
from tools.jaxlint.program import ProgramIndex, module_name
from tools.lint import iter_py_files

DEFAULT_ROOTS = [
    # tests/ are deliberately out of the default roots: test corpora seed
    # the very defects this gate rejects (tests/test_jaxlint.py)
    "horaedb_tpu", "benchmarks", "tools",
    "bench.py", "__graft_entry__.py",
]
HYGIENE_CODES = {"J000", "J021", "J999"}  # never suppressible


def _changed_paths() -> set[str] | None:
    """Absolute posix paths of files differing from HEAD (tracked diff
    + untracked); None when git is unavailable."""
    out: set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if r.returncode != 0:
            return None
        for line in r.stdout.splitlines():
            if line.strip():
                out.add(Path(line.strip()).resolve().as_posix())
    return out


def run(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxlint", description="domain-aware lint gate")
    ap.add_argument("roots", nargs="*", default=None)
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--changed", action="store_true",
                    help="report findings only for files differing "
                         "from git HEAD (analysis still sees the whole "
                         "tree so graph passes stay sound)")
    ap.add_argument("--no-cache", action="store_true")
    ap.add_argument("--budget", type=float, default=None,
                    metavar="SECONDS",
                    help="fail (exit 99) if the run takes longer")
    ap.add_argument("--check-index", action="store_true",
                    help="print the check inventory and exit")
    args = ap.parse_args(argv)

    if args.check_index:
        if args.as_json:
            print(json.dumps(registry.check_index_json(), indent=2))
        else:
            print(registry.check_index_markdown())
        return 0

    t0 = time.monotonic()
    explicit_roots = bool(args.roots)
    files = iter_py_files(args.roots or DEFAULT_ROOTS)

    cache = None
    if not args.no_cache:
        cache = LintCache(registry.inventory_digest())
        cache.load()

    # ---- pass 1: read + hash + per-file passes (cached) --------------
    digests: dict[str, str] = {}
    texts: dict[str, str] = {}
    trees: dict[str, object] = {}       # parsed ASTs (cache misses only)
    perfile_raw: dict[str, list[Finding]] = {}
    sups: dict[str, Suppressions] = {}
    for f in files:
        posix = f.as_posix()
        data = f.read_bytes()
        digests[posix] = file_digest(data)
        cached = cache.get_file(posix, digests[posix]) if cache else None
        if cached is not None:
            perfile_raw[posix], sups[posix] = cached
            continue
        text, tree, syntax = parse_file(f)
        texts[posix] = text
        if syntax is not None:
            perfile_raw[posix] = [syntax]
            sups[posix] = Suppressions(text.split("\n"))
        else:
            trees[posix] = tree
            perfile_raw[posix], sups[posix] = run_perfile(f, text, tree)
        if cache:
            cache.put_file(posix, digests[posix], perfile_raw[posix],
                           sups[posix])

    # ---- pass 2: whole-program passes (tree-digest cached) -----------
    tdigest = tree_digest(digests)
    graph = cache.get_tree(tdigest) if cache else None
    if graph is None:
        index = ProgramIndex()
        for f in files:
            posix = f.as_posix()
            if module_name(f) is None:
                continue
            tree = trees.get(posix)
            if tree is None:
                _, tree, syntax = parse_file(f)
                if syntax is not None:
                    continue
            index.add_file(f, tree)
        index.finish()
        graph = {}
        for pass_fn in (concurrency.check_event_loop_blocking,
                        concurrency.check_lock_order,
                        concurrency.check_deadline_propagation):
            for posix, fs in pass_fn(index).items():
                graph.setdefault(posix, []).extend(fs)
        if cache:
            cache.put_tree(tdigest, graph)

    # ---- pass 3: suppression filter + hygiene ------------------------
    changed = _changed_paths() if args.changed else None
    report: list[tuple[str, Finding]] = []
    for f in files:
        posix = f.as_posix()
        raw = perfile_raw[posix] + graph.get(posix, [])
        sup = sups[posix]
        final = [x for x in raw
                 if x.code in HYGIENE_CODES
                 or not sup.covers(x.lineno, x.code)]
        final += hygiene.check_suppression_hygiene(sup, raw)
        if changed is not None and \
                f.resolve().as_posix() not in changed:
            continue
        for x in sorted(final, key=lambda x: (x.lineno, x.code)):
            report.append((str(f), x))

    if cache:
        if not explicit_roots:
            cache.prune(set(digests))
        cache.save()

    elapsed = time.monotonic() - t0
    n = len(report)
    if args.as_json:
        print(json.dumps({
            "findings": [
                {"path": p, "line": x.lineno, "code": x.code,
                 "msg": x.msg} for p, x in report
            ],
            "files": len(files),
            "count": n,
            "elapsed_s": round(elapsed, 3),
        }, indent=2))
    else:
        for p, x in report:
            print(f"{p}:{x.lineno}: {x.code} {x.msg}")
        print(f"jaxlint: {n} finding(s) in {len(files)} files")
    if args.budget is not None and elapsed > args.budget:
        print(f"jaxlint: budget exceeded: {elapsed:.2f}s > "
              f"{args.budget:.2f}s", file=sys.stderr)
        return 99
    return min(n, 125)


if __name__ == "__main__":
    raise SystemExit(run(sys.argv[1:]))
