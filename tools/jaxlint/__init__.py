"""jaxlint: the domain-aware lint gate, as a package.

Layout (one module per concern):
- base.py      findings, suppressions, dotted names, path scoping
- jitrules.py  J001-J003, J005-J007 (trace discipline, dtype hygiene)
- lockrules.py J004 (per-class lock discipline)
- funnels.py   J008-J017 (architectural funnel boundaries)
- perfile.py   per-file dispatcher (parse + scope + run J001-J017)
- program.py   the shared whole-program index (call graph, async
               reachability, lock graph, loop inventory)
- concurrency.py J018-J020 graph passes
- hygiene.py   J000/J021 suppression hygiene
- registry.py  check inventory (docs drift gate + cache key)
- cache.py     incremental lint cache
- __main__.py  CLI orchestrator (`python -m tools.jaxlint`)
"""
