"""Incremental lint cache (satellite 2).

Two entry kinds, both keyed on `inventory_digest()` so ANY change to
the linter's own source invalidates everything:

- per-file: (path, sha256 of file bytes) -> raw per-file findings +
  suppression table. A warm re-lint parses and re-checks only files
  whose bytes changed.
- tree: sha256 over every (path, file digest) pair -> the graph-pass
  findings (J018-J020). The whole-program index is only rebuilt when
  any analyzed file changed; an untouched tree re-lints from cache in
  well under the 2 s budget.

Same persistence convention as the engine's calibration caches
(common/calib_cache.py): `$TMPDIR/horaedb-tpu/jaxlint_cache.json`,
`HORAEDB_JAXLINT_CACHE` overrides with a full file path, writes are
atomic (tmp + os.replace). A corrupt or unreadable cache file is
treated as empty — the cache can never make lint fail."""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from pathlib import Path

from tools.jaxlint.base import Finding, Suppressions

_SCHEMA = 2


def cache_path() -> Path:
    env = os.environ.get("HORAEDB_JAXLINT_CACHE")
    if env:
        return Path(env)
    return Path(tempfile.gettempdir()) / "horaedb-tpu" / \
        "jaxlint_cache.json"


def file_digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def tree_digest(file_digests: dict[str, str]) -> str:
    h = hashlib.sha256()
    for path in sorted(file_digests):
        h.update(path.encode())
        h.update(file_digests[path].encode())
    return h.hexdigest()


def _findings_to_json(findings: list[Finding]) -> list[list]:
    return [list(f.as_tuple()) for f in findings]


def _findings_from_json(rows) -> list[Finding]:
    return [Finding(int(r[0]), str(r[1]), str(r[2])) for r in rows]


class LintCache:
    def __init__(self, inventory: str, path: Path | None = None):
        self.inventory = inventory
        self.path = path or cache_path()
        self._data: dict = {"schema": _SCHEMA, "inventory": inventory,
                            "files": {}, "tree": None}
        self._dirty = False

    def load(self) -> None:
        try:
            raw = json.loads(self.path.read_text())
        except (OSError, ValueError):
            return
        if not isinstance(raw, dict) or raw.get("schema") != _SCHEMA \
                or raw.get("inventory") != self.inventory:
            return  # linter source changed: start cold
        self._data = raw
        self._data.setdefault("files", {})
        self._data.setdefault("tree", None)

    def save(self) -> None:
        if not self._dirty:
            return
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            tmp = self.path.with_suffix(
                f".tmp.{os.getpid()}")
            tmp.write_text(json.dumps(self._data, separators=(",", ":")))
            os.replace(tmp, self.path)
        except OSError:
            pass  # best effort: a read-only tmpdir just means cold runs

    # ------------------------------------------------------- per-file

    def get_file(self, path: str, digest: str) \
            -> tuple[list[Finding], Suppressions] | None:
        entry = self._data["files"].get(path)
        if not entry or entry.get("digest") != digest:
            return None
        return (_findings_from_json(entry["findings"]),
                Suppressions.from_dict(entry["sup"]))

    def put_file(self, path: str, digest: str, findings: list[Finding],
                 sup: Suppressions) -> None:
        self._data["files"][path] = {
            "digest": digest,
            "findings": _findings_to_json(findings),
            "sup": sup.as_dict(),
        }
        self._dirty = True

    def prune(self, live_paths: set[str]) -> None:
        """Drop entries for files no longer analyzed (deleted/renamed)."""
        stale = [p for p in self._data["files"] if p not in live_paths]
        for p in stale:
            del self._data["files"][p]
            self._dirty = True

    # ----------------------------------------------------------- tree

    def get_tree(self, digest: str) -> dict[str, list[Finding]] | None:
        entry = self._data.get("tree")
        if not entry or entry.get("digest") != digest:
            return None
        return {p: _findings_from_json(rows)
                for p, rows in entry["findings"].items()}

    def put_tree(self, digest: str,
                 findings: dict[str, list[Finding]]) -> None:
        self._data["tree"] = {
            "digest": digest,
            "findings": {p: _findings_to_json(fs)
                         for p, fs in findings.items() if fs},
        }
        self._dirty = True
