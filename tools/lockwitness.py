"""Dynamic lock-order witness: the runtime complement to jaxlint's
static J019 pass.

While installed, the `threading.Lock` / `RLock` factories return
recording wrappers (`Condition()` is covered too: its default lock
comes from the patched `RLock`). Each wrapper's identity is its CREATION
site (file:line of the factory call) — instances created at one site
collapse to one node, mirroring the static pass's `(Class, attr)`
identity, and catching the cross-INSTANCE inversions the static pass
deliberately leaves to this tool. Every acquisition records
held-before edges into a process-wide digraph; `cycles()` reports
order inversions that actually happened, with a witness site per edge.

Usage in tests (the chaos soak wires this behind HORAEDB_LOCKWITNESS=1):

    with maybe_witness() as w:
        ... exercise the engine ...
    if w is not None:
        assert not w.cycles(), w.format_report()

Scope notes:
- only locks CREATED while installed are recorded (pytest/stdlib
  machinery constructed earlier is invisible — deliberate);
- re-acquiring an RLock already held by the thread adds no edge (it
  cannot deadlock against itself);
- asyncio locks are not recorded: they serialize tasks on ONE thread,
  and the static pass (await-under-sync-lock, asyncio lock graph)
  covers them.
"""

from __future__ import annotations

import os
import threading
import traceback
from contextlib import contextmanager

ENV_FLAG = "HORAEDB_LOCKWITNESS"
_SELF = __file__  # exact-match filter: "lockwitness" substring would
#                   also skip frames of tests/test_lockwitness.py


def _creation_site() -> str:
    for frame in reversed(traceback.extract_stack()):
        if frame.filename != _SELF:
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


def _acquire_site() -> str:
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename
        if fn != _SELF and not fn.endswith("threading.py"):
            return f"{fn}:{frame.lineno}"
    return "<unknown>"


class _RecordingLock:
    """Wraps a real lock; forwards everything (Condition pokes at
    `_is_owned`/`_release_save` etc. via __getattr__)."""

    def __init__(self, inner, site: str, witness: "LockWitness",
                 reentrant: bool):
        self._inner = inner
        self._site = site
        self._witness = witness
        self._reentrant = reentrant

    def acquire(self, *a, **kw):
        self._witness._note_acquire(self)
        ok = self._inner.acquire(*a, **kw)
        if not ok:
            self._witness._note_release(self)
        return ok

    def release(self):
        self._inner.release()
        self._witness._note_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class LockWitness:
    def __init__(self) -> None:
        self._held = threading.local()       # per-thread list of sites
        self._edges: dict[tuple[str, str], tuple[int, str, str]] = {}
        self._graph_lock = threading.Lock()  # the REAL factory's product
        self._orig: dict[str, object] = {}
        self._installed = False

    # ------------------------------------------------------- recording

    def _stack(self) -> list[str]:
        s = getattr(self._held, "sites", None)
        if s is None:
            s = self._held.sites = []
        return s

    def _note_acquire(self, lock: _RecordingLock) -> None:
        held = self._stack()
        if lock._reentrant and lock._site in held:
            held.append(lock._site)  # reentry: depth only, no edge
            return
        site = _acquire_site()
        # get_ident, NOT current_thread(): in a not-yet-registered
        # thread the latter constructs a _DummyThread whose Event goes
        # through the patched Lock factory -> infinite recursion
        thread = f"thread-{threading.get_ident()}"
        with self._graph_lock:
            for h in held:
                if h == lock._site:
                    continue
                key = (h, lock._site)
                if key in self._edges:
                    n, s0, t0 = self._edges[key]
                    self._edges[key] = (n + 1, s0, t0)
                else:
                    self._edges[key] = (1, site, thread)
        held.append(lock._site)

    def _note_release(self, lock: _RecordingLock) -> None:
        held = self._stack()
        if lock._site in held:  # non-LIFO release: drop last occurrence
            for i in range(len(held) - 1, -1, -1):
                if held[i] == lock._site:
                    del held[i]
                    break

    # ----------------------------------------------------- install/api

    def install(self) -> None:
        if self._installed:
            return
        self._orig = {
            "Lock": threading.Lock,
            "RLock": threading.RLock,
        }
        witness = self

        def make(factory, reentrant):
            def wrapped():
                return _RecordingLock(
                    factory(), _creation_site(), witness, reentrant)
            return wrapped

        threading.Lock = make(self._orig["Lock"], False)
        threading.RLock = make(self._orig["RLock"], True)
        self._installed = True

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = self._orig["Lock"]
        threading.RLock = self._orig["RLock"]
        self._installed = False

    def edges(self) -> dict[tuple[str, str], tuple[int, str, str]]:
        with self._graph_lock:
            return dict(self._edges)

    def cycles(self) -> list[list[str]]:
        """Elementary cycles (as node lists) in the recorded order
        graph — any cycle is a latent deadlock."""
        edges = self.edges()
        adj: dict[str, list[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        out: list[list[str]] = []
        seen_cycles: set[frozenset[str]] = set()
        for root in sorted(adj):
            # DFS from root; a path back to root is a cycle
            stack: list[tuple[str, list[str]]] = [(root, [root])]
            while stack:
                node, path = stack.pop()
                for nxt in adj[node]:
                    if nxt == root and len(path) > 1 or \
                            nxt == root == node:
                        key = frozenset(path)
                        if key not in seen_cycles:
                            seen_cycles.add(key)
                            out.append(path + [root])
                    elif nxt not in path and nxt > root:
                        # only walk nodes > root: each cycle found once,
                        # from its smallest node
                        stack.append((nxt, path + [nxt]))
        return out

    def format_report(self) -> str:
        lines = ["lockwitness: recorded lock-order graph"]
        for (a, b), (n, site, thread) in sorted(self.edges().items()):
            lines.append(f"  {a} -> {b}  (x{n}, first at {site} "
                         f"in {thread})")
        cyc = self.cycles()
        if cyc:
            lines.append("CYCLES (latent deadlocks):")
            for c in cyc:
                lines.append("  " + " -> ".join(c))
        else:
            lines.append("no cycles")
        return "\n".join(lines)


@contextmanager
def witness():
    w = LockWitness()
    w.install()
    try:
        yield w
    finally:
        w.uninstall()


@contextmanager
def maybe_witness():
    """The soak-test hook: records only when HORAEDB_LOCKWITNESS=1,
    yields None otherwise so the soak runs unchanged by default."""
    if os.environ.get(ENV_FLAG) == "1":
        with witness() as w:
            yield w
    else:
        yield None
