"""Repo tooling (lint gates, witnesses). Package so `python -m tools.jaxlint` works."""
