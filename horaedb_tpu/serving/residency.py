"""Hot-block device residency (serving tier layer c).

A byte-bounded cache of DECODED column blocks keyed
``(sst id, row group, column set)`` — the exact io_decode + host_prep +
transfer lanes ROOFLINE blames for the config-2/5 walls. It rides the
reader's row-group cache hooks (storage/read.py), one tier above the
host block cache:

- **admission is heat-gated**: a block is pinned only after the scan
  path has touched it ``admit_after`` times (default 2) — the same
  repeat-traffic signal the slowlog surfaces — so a one-off backfill
  scan cannot churn the hot set;
- **values are device-pinned**: each numeric lane is ``jax.device_put``
  at admission, so on accelerator backends the block lives in HBM and a
  repeat scan pays neither the object-store GET, the parquet decode,
  nor the H2D copy of those lanes. On the CPU backend the pin is a
  committed host buffer and the measured win is the IO+decode skip.
  Binary lanes (label blobs) stay host-side;
- **eviction funnels** through the reader's ``evict_cached`` (compaction
  deletes) plus LRU byte pressure — SSTs are immutable, so entries never
  go stale, they only die with their file.

Lookups return the assembled pyarrow table built ONCE at admission over
zero-copy views of the pinned lanes; per-hit cost is a dict probe.
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict

import numpy as np
import pyarrow as pa

from horaedb_tpu.common import colblock, memtrace
from horaedb_tpu.common.bytebudget import GLOBAL_POOLS
from horaedb_tpu.serving import RESIDENCY, RESIDENT_BLOCKS, RESIDENT_BYTES

logger = logging.getLogger(__name__)


def _device_put(arr: np.ndarray):
    """Pin one lane on the default device; None when no backend exists
    (the cache then holds the host copy only — still a decode skip)."""
    try:
        import jax

        return jax.device_put(arr)
    except Exception:  # noqa: BLE001 — backendless processes still cache
        return None


class DeviceBlockCache:
    """LRU of device-pinned decoded blocks with touch-count admission."""

    def __init__(self, capacity_bytes: int = 0, admit_after: int = 2):
        self._cap = capacity_bytes
        self._admit_after = max(1, admit_after)
        # (sst_id, rg, cols_key) -> (table, device_lanes dict, nbytes)
        self._blocks: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bytes = 0
        # heat: touch counts per block key, bounded FIFO so a long scan
        # history cannot grow it without bound
        self._heat: "OrderedDict[tuple, int]" = OrderedDict()
        self._heat_cap = 8192
        self._lock = threading.Lock()
        GLOBAL_POOLS.register_provider(
            "residency", self,
            lambda c: (c._bytes, len(c._blocks)),
        )

    def configure(self, capacity_bytes: int, admit_after: int = 2) -> None:
        with self._lock:
            self._cap = capacity_bytes
            self._admit_after = max(1, admit_after)
            self._shrink_locked()
        GLOBAL_POOLS.set_capacity("residency", capacity_bytes)
        self._export()

    @property
    def enabled(self) -> bool:
        return self._cap > 0

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    def _export(self) -> None:
        RESIDENT_BYTES.set(self._bytes)
        RESIDENT_BLOCKS.set(len(self._blocks))

    def _shrink_locked(self) -> None:
        while self._bytes > self._cap and self._blocks:
            _k, (_t, _d, nb) = self._blocks.popitem(last=False)
            self._bytes -= nb
            GLOBAL_POOLS.note_eviction("residency")

    # -- read side (reached only via storage/read.py's rg hooks) --------------
    def resident_block(self, sst_id: int, rg: int, cols_key: tuple):
        """The pinned block's assembled table, or None. LRU-touches."""
        key = (sst_id, rg, cols_key)
        with self._lock:
            ent = self._blocks.get(key)
            if ent is None:
                return None
            self._blocks.move_to_end(key)
            return ent[0]

    def device_lanes(self, sst_id: int, rg: int, cols_key: tuple):
        """The pinned jax arrays of a resident block (lane -> Array), for
        kernel paths that can consume device handles directly; None when
        not resident or no backend pinned them."""
        with self._lock:
            ent = self._blocks.get((sst_id, rg, cols_key))
            return ent[1] if ent is not None else None

    # -- admission (reached only via storage/read.py's rg hooks) --------------
    def note_fetch(
        self, sst_id: int, rg: int, cols_key: tuple, table: pa.Table,
    ) -> bool:
        """Record one non-resident touch of a block; admit it once the
        heat gate passes. Returns True when the block was admitted now."""
        if self._cap <= 0:
            return False
        size = table.nbytes
        if size > self._cap // 4:
            return False  # one block must not dominate the budget
        key = (sst_id, rg, cols_key)
        with self._lock:
            heat = self._heat.get(key, 0) + 1
            self._heat[key] = heat
            self._heat.move_to_end(key)
            while len(self._heat) > self._heat_cap:
                self._heat.popitem(last=False)
            if heat < self._admit_after or key in self._blocks:
                return False
        # pin outside the lock: device_put can be slow on first touch.
        # The decoded table itself is the served value (the IO+decode
        # skip); the device handles are the HBM pins kernel paths can
        # consume without an H2D copy. Binary lanes (labels) stay host.
        # The byte budget charges BOTH copies — on an accelerator the
        # device lanes are real HBM, and an uncounted second copy would
        # let the true footprint run to ~2x the configured budget.
        device_lanes: dict[str, object] = {}
        dev_bytes = 0
        # chunk-aware lane export (common/colblock.py): each numeric lane
        # stages to the device straight off its zero-copy arrow view — no
        # fresh host alloc between decode and pin, and the HBM transfer is
        # charged ONCE for the block below instead of once per lane
        # against a combine copy (the r19 double-charge)
        lanes = colblock.ArrowLanes(table, stage="residency_fill")
        for name in table.schema.names:
            try:
                arr = lanes.lane(name)
            except Exception:  # noqa: BLE001 — non-numeric lane (labels)
                continue
            if arr.dtype == object:
                continue
            dev = _device_put(arr)
            if dev is not None:
                device_lanes[name] = dev
                dev_bytes += int(arr.nbytes)
        if dev_bytes:
            # the HBM pin is a real second copy of the numeric lanes —
            # the staging odometer and the byte budget both charge it
            memtrace.device_staged(dev_bytes, "residency_fill")
        total = size + dev_bytes
        with self._lock:
            if key in self._blocks or total > self._cap // 4:
                return False
            self._blocks[key] = (table, device_lanes, total)
            self._bytes += total
            self._heat.pop(key, None)
            self._shrink_locked()
        RESIDENCY.labels("admitted").inc()
        self._export()
        return True

    # -- eviction funnel (storage/read.py evict_cached + tests) ---------------
    def evict_sst(self, sst_id: int) -> None:
        with self._lock:
            dead = [k for k in self._blocks if k[0] == sst_id]
            for k in dead:
                self._bytes -= self._blocks.pop(k)[2]
            for k in [k for k in self._heat if k[0] == sst_id]:
                del self._heat[k]
        if dead:
            self._export()

    def clear(self) -> None:
        with self._lock:
            self._blocks.clear()
            self._heat.clear()
            self._bytes = 0
        self._export()


RESIDENCY_CACHE = DeviceBlockCache()


def configure(capacity_bytes: int, admit_after: int = 2) -> None:
    RESIDENCY_CACHE.configure(capacity_bytes, admit_after=admit_after)
