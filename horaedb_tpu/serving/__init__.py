"""Serving tier for dashboard-scale repeat traffic.

Production dashboard traffic is ~99% repeated panels re-scanning the same
sealed SSTs every refresh interval. This package turns that repeat work
into O(1)-ish lookups with three stacked layers, each honest about its
shortcuts (EXPLAIN `serving` verdict, `horaedb_serving_*` families, and
the `HORAEDB_SERVING=off` forced-cold switch):

1. **Compaction-time rollups** (storage/rollup.py): compaction already
   rewrites every byte of a segment, so it additionally emits 1m/1h
   pre-aggregated SSTs (sum/count/min/max per series per bucket) — exact
   LWW-post-merge, tombstones and late data already reconciled. The
   planner (engine/data.py) substitutes a rollup for a raw segment scan
   only when the segment's live SST set EXACTLY equals the rollup's
   recorded source set, no newer tombstone overlaps it, and the query
   grid is resolution-aligned — so a rollup can never serve stale data;
   it simply stops being used the moment anything changes, until the
   next compaction re-emits it.

2. **Result cache** (serving/cache.py): a byte-bounded process-global
   LRU over finished query results. The key IS the invalidation
   contract: (normalized plan fingerprint, the sealed-SST id set
   covering the range, tombstone ids, retention component) — any flush,
   compaction, or delete changes the key, so a stale entry can never
   hit. Flush/compaction/delete events additionally purge the table's
   entries eagerly (the funnel: `serving_invalidate`), and concurrent
   same-key fills collapse to one computation (single-flight).

3. **Hot-block device residency** (serving/residency.py): a
   byte-bounded cache of decoded column blocks keyed
   (sst id, row group, column set), admission gated by a touch-count
   heat signal, pinned via `jax.device_put` — repeat scans of hot SSTs
   skip object-store IO + parquet decode, and on accelerator backends
   the pinned lanes are HBM-resident.

jaxlint J013 enforces the funnel discipline: result-cache/rollup READS
happen only at the planner choke point (engine/data.py) and the serving/
rollup modules themselves; cache MUTATION happens only through the
invalidation funnel (storage write/compaction commit/delete paths).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from horaedb_tpu.common.size_ext import ReadableSize
from horaedb_tpu.common.time_ext import ReadableDuration
from horaedb_tpu.server.metrics import GLOBAL_METRICS

# -- metric families (pre-registered zero states so /metrics shows them
# -- from boot, the PR2 convention) ------------------------------------------

CACHE_REQUESTS = GLOBAL_METRICS.counter(
    "horaedb_serving_cache_requests_total",
    help="Result-cache lookups at the planner choke point, by outcome: "
         "hit (served without scanning), miss (computed + stored), "
         "bypass (HORAEDB_SERVING=off or serving disabled).",
    labelnames=("result",),
)
CACHE_BYTES = GLOBAL_METRICS.gauge(
    "horaedb_serving_cache_bytes",
    help="Resident bytes in the query result cache (byte-bounded LRU).",
)
CACHE_ENTRIES = GLOBAL_METRICS.gauge(
    "horaedb_serving_cache_entries",
    help="Entries resident in the query result cache.",
)
CACHE_EVICTIONS = GLOBAL_METRICS.counter(
    "horaedb_serving_cache_evictions_total",
    help="Result-cache entries evicted by the LRU byte bound.",
)
INVALIDATIONS = GLOBAL_METRICS.counter(
    "horaedb_serving_invalidations_total",
    help="Result-cache invalidation events through the funnel, by "
         "reason: flush (new SST committed), compact (manifest "
         "rewrite), delete (tombstone created).",
    labelnames=("reason",),
)
ROLLUPS_BUILT = GLOBAL_METRICS.counter(
    "horaedb_serving_rollups_built_total",
    help="Rollup artifacts emitted at compaction time, by resolution.",
    labelnames=("resolution",),
)
ROLLUP_SUBSTITUTIONS = GLOBAL_METRICS.counter(
    "horaedb_serving_rollup_substitutions_total",
    help="Per-segment rollup substitutions the planner made (a raw "
         "segment scan replaced by a bucket-count-scale rollup read), "
         "by resolution.",
    labelnames=("resolution",),
)
ROLLUP_ROWS = GLOBAL_METRICS.counter(
    "horaedb_serving_rollup_rows_total",
    help="Pre-aggregated rollup rows read in place of raw rows.",
)
RESIDENT_BYTES = GLOBAL_METRICS.gauge(
    "horaedb_serving_resident_bytes",
    help="Decoded column-block bytes pinned in the device residency "
         "cache (HBM on accelerator backends).",
)
RESIDENT_BLOCKS = GLOBAL_METRICS.gauge(
    "horaedb_serving_resident_blocks",
    help="Column blocks pinned in the device residency cache.",
)
RESIDENCY = GLOBAL_METRICS.counter(
    "horaedb_serving_residency_total",
    help="Block reads by residency outcome: resident (served from the "
         "pinned tier, no IO/decode), fetched (decoded from store or "
         "host cache), admitted (block newly pinned by the heat gate).",
    labelnames=("result",),
)

for _r in ("hit", "miss", "bypass"):
    CACHE_REQUESTS.labels(_r)
for _r in ("flush", "compact", "delete"):
    INVALIDATIONS.labels(_r)
for _r in ("resident", "fetched", "admitted"):
    RESIDENCY.labels(_r)
for _r in ("1m", "1h"):
    ROLLUPS_BUILT.labels(_r)
    ROLLUP_SUBSTITUTIONS.labels(_r)


def serving_env_off() -> bool:
    """The honesty switch: HORAEDB_SERVING=off forces every query cold
    (no result cache, no rollup substitution, no residency) so serving
    answers can be asserted bit-exact against first-principles scans.
    Read per query, not at import, so tests and operators can flip it
    live."""
    return os.environ.get("HORAEDB_SERVING", "").lower() in (
        "off", "0", "false", "no",
    )


def resolution_label(ms: int) -> str:
    """Human resolution label for metrics/EXPLAIN ("1m", "1h", else ms)."""
    if ms == 60_000:
        return "1m"
    if ms == 3_600_000:
        return "1h"
    if ms % 3_600_000 == 0:
        return f"{ms // 3_600_000}h"
    if ms % 60_000 == 0:
        return f"{ms // 60_000}m"
    return f"{ms}ms"


def parse_resolution(v) -> int:
    """One rollup resolution: int ms, or a duration string ("1m", "1h")."""
    if isinstance(v, int):
        return v
    return ReadableDuration.parse(v).as_millis()


@dataclass
class ServingTierConfig:
    """Knobs of the serving tier ([metric_engine.serving] in TOML).

    Defaults are ON: the tier is invalidation-correct by construction
    (results are bit-exact vs forced-cold scans — regression-tested and
    chaos-soaked), so there is no correctness reason to opt in."""

    enabled: bool = True
    # compaction-time downsample rollups (data tables only; emitted when
    # a compaction merges a FULL segment)
    rollup_enabled: bool = True
    rollup_resolutions: list = field(
        default_factory=lambda: [60_000, 3_600_000]  # 1m, 1h
    )
    # result-cache byte budget (process-global LRU; 0 disables)
    result_cache: ReadableSize = field(
        default_factory=lambda: ReadableSize.mb(64)
    )
    # decoded rollup-artifact read cache (storage/rollup.py; 0 disables)
    rollup_cache: ReadableSize = field(
        default_factory=lambda: ReadableSize.mb(16)
    )
    # device residency byte budget (process-global; 0 disables)
    residency: ReadableSize = field(
        default_factory=lambda: ReadableSize.mb(64)
    )
    # touches of a block before the heat gate admits it to residency
    residency_admit_after: int = 2

    @classmethod
    def from_dict(cls, d: dict | None) -> "ServingTierConfig":
        if d is None:
            return cls()
        from horaedb_tpu.common.error import HoraeError

        known = {f for f in cls.__dataclass_fields__}
        unknown = set(d) - known
        if unknown:
            raise HoraeError(
                f"unknown config keys for ServingTierConfig: {sorted(unknown)}"
            )
        kwargs = dict(d)
        if "rollup_resolutions" in kwargs:
            kwargs["rollup_resolutions"] = [
                parse_resolution(v) for v in kwargs["rollup_resolutions"]
            ]
        for k in ("result_cache", "rollup_cache", "residency"):
            if k in kwargs:
                kwargs[k] = ReadableSize.parse(kwargs[k])
        return cls(**kwargs)


class ServingTier:
    """One engine's handle on the (process-global) serving tier: the
    config plus the shared result cache and residency cache, sized at
    engine open. Installed on each SampleManager as the planner's single
    entry into the tier."""

    def __init__(self, config: "ServingTierConfig | None" = None):
        from horaedb_tpu.serving import cache as cache_mod
        from horaedb_tpu.serving import residency as residency_mod

        self.config = config or ServingTierConfig()
        self.cache = cache_mod.RESULT_CACHE
        if self.config.enabled:
            from horaedb_tpu.storage import rollup as rollup_mod

            cache_mod.configure(self.config.result_cache.as_bytes())
            rollup_mod.configure_cache(self.config.rollup_cache.as_bytes())
            residency_mod.configure(
                self.config.residency.as_bytes(),
                admit_after=self.config.residency_admit_after,
            )

    def active(self) -> bool:
        """Serving layers may be consulted for this query (config on AND
        the HORAEDB_SERVING honesty switch not forcing cold)."""
        return self.config.enabled and not serving_env_off()

    @property
    def rollups_active(self) -> bool:
        return self.active() and self.config.rollup_enabled
