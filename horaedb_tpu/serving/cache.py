"""Invalidation-correct query result cache (serving tier layer b).

One process-global byte-bounded LRU over finished query results. Two
independent mechanisms keep it correct — belt and braces:

1. **The key is the invalidation contract.** A key is a digest over the
   normalized plan fingerprint PLUS the sealed-SST id set covering the
   range PLUS the visibility epoch (overlapping tombstone ids, retention
   component). Every flush commits a new SST id, every compaction
   replaces ids, every delete mints a tombstone id — so any mutation
   changes the key and a stale entry can never be LOOKED UP again. SST
   ids come from the process-wide monotonic allocator, so keys can never
   collide across tables or engine instances either.

2. **Events purge eagerly.** The mutation funnel (`serving_invalidate`,
   called from the storage write commit, the compaction commit, and the
   tombstone path — jaxlint J013 pins the call sites) drops a table's
   entries the moment its data changes, so dead entries do not squat on
   the byte budget until LRU pressure finds them.

The funnel is also the engine's ONE mutation broadcast: every event that
purges the cache names exactly the (root, reason, time range) that
changed, so standing-query consumers can ride it instead of polling.
`serving_subscribe` registers a callback `(root, reason, time_range)`
called synchronously on every invalidation, with error isolation (a
broken subscriber logs; it never fails the commit that fired the event).
jaxlint J014 pins the consumer set: only the cache itself and the rule
evaluator (horaedb_tpu/rules) may subscribe — a third consumer would be
a second standing-query engine growing outside the audited one.

Fills are **single-flight**: N concurrent queries with the same key pay
ONE computation (the leader's); followers await its future. A leader
failure resolves followers with a sentinel and they fall back to their
own fill — a poisoned future must never wedge every follower. Futures
are loop-bound; a caller on a different event loop duplicates the fill
rather than awaiting across loops (same policy as the PR 9 sidecar
single-flight this reuses).

Stored arrays are marked read-only: a caller mutating a shared cached
grid would silently corrupt every later hit — better a loud ValueError.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from collections import OrderedDict

import numpy as np

from horaedb_tpu.common import colblock, memtrace
from horaedb_tpu.common.bytebudget import GLOBAL_POOLS
from horaedb_tpu.serving import (
    CACHE_BYTES,
    CACHE_ENTRIES,
    CACHE_EVICTIONS,
    INVALIDATIONS,
)

logger = logging.getLogger(__name__)

# leader-failure sentinel for single-flight followers (see module doc)
_FILL_FAILED = object()


def _freeze(value) -> None:
    """Mark every numpy array reachable in a cached value read-only."""
    if isinstance(value, colblock.ColBlock):
        # a column block freezes as a unit: its mutability epoch guards
        # sharing, and its public lanes come back read-only already
        value.freeze()
        return
    if isinstance(value, np.ndarray):
        try:
            value.setflags(write=False)
        except ValueError:
            pass  # non-owning view; the base stays writable but shared
        return
    if isinstance(value, dict):
        for v in value.values():
            _freeze(v)
    elif isinstance(value, (list, tuple)):
        for v in value:
            _freeze(v)


def _share_blocks(value, stage: str) -> int:
    """`share()` every reachable frozen column block (files one `reuse`
    lineage event per block — by-reference pinning, zero bytes moved)
    and return their total bytes so the caller charges only the loose
    remainder as a view."""
    if isinstance(value, colblock.ColBlock):
        value.share(stage)
        return value.nbytes
    if isinstance(value, dict):
        return sum(_share_blocks(v, stage) for v in value.values())
    if isinstance(value, (list, tuple)):
        return sum(_share_blocks(v, stage) for v in value)
    return 0


class ResultCache:
    """Byte-bounded LRU keyed by opaque digests, with per-root indexing
    for the event purge and loop-aware single-flight fills."""

    def __init__(self, capacity_bytes: int = 0):
        self._cap = capacity_bytes
        # key -> (value, nbytes, root, notes)
        self._entries: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._bytes = 0
        self._by_root: dict[str, set] = {}
        self._lock = threading.Lock()
        # key -> (owning loop, future) for in-flight fills
        self._inflight: dict[bytes, tuple] = {}
        # invalidation subscribers: token -> callback(root, reason, range).
        # Registered ONLY by the funnel's audited consumers (jaxlint J014:
        # serving/ and the rule evaluator); called synchronously after the
        # purge with error isolation.
        self._subscribers: dict[int, object] = {}
        self._next_token = 1
        # unified pool registry (common/bytebudget.py): occupancy is read
        # back through a weakref provider, evictions route to the pool
        GLOBAL_POOLS.register_provider(
            "result", self,
            lambda c: (c._bytes, len(c._entries)),
        )

    # -- sizing ---------------------------------------------------------------
    def configure(self, capacity_bytes: int) -> None:
        with self._lock:
            self._cap = capacity_bytes
            self._shrink_locked()
        GLOBAL_POOLS.set_capacity("result", capacity_bytes)
        self._export()

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    def _export(self) -> None:
        CACHE_BYTES.set(self._bytes)
        CACHE_ENTRIES.set(len(self._entries))

    def _shrink_locked(self) -> None:
        while self._bytes > self._cap and self._entries:
            key, (_v, nb, root, _n) = self._entries.popitem(last=False)
            self._bytes -= nb
            keys = self._by_root.get(root)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._by_root[root]
            CACHE_EVICTIONS.inc()
            GLOBAL_POOLS.note_eviction("result")

    # -- the planner's read side (jaxlint J013: choke point only) -------------
    def serving_get(self, key: bytes):
        """(value, notes) on a hit, None on a miss. LRU-touches the
        entry. `notes` is the fill-time provenance dict the choke point
        replays into scanstats so EXPLAIN on a hit still names what the
        cached plan covered."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return None
            self._entries.move_to_end(key)
            return ent[0], ent[3]

    async def serving_single_flight(self, key: bytes, root: str, fill):
        """Run `fill` (async, returns (value, nbytes, notes)) exactly
        once per key across concurrent callers; store and return the
        value with its notes. Returns (value, notes, leader) — followers
        get the leader's stored notes to replay (their own collectors
        saw none of the fill's scan)."""
        loop = asyncio.get_running_loop()
        with self._lock:
            flight = self._inflight.get(key)
            if flight is None:
                fut = loop.create_future()
                self._inflight[key] = (loop, fut)
            else:
                fut = None
        if fut is None:
            f_loop, f_fut = flight
            if f_loop is loop:
                got = await f_fut
                if got is not _FILL_FAILED:
                    value, notes = got
                    return value, notes, False
            # leader failed, or cross-loop caller: compute independently
            value, nbytes, notes = await fill()
            self.serving_put(key, value, nbytes, root, notes)
            return value, notes, True
        try:
            value, nbytes, notes = await fill()
        except BaseException:
            with self._lock:
                if self._inflight.get(key, (None, None))[1] is fut:
                    del self._inflight[key]
            if not fut.done():
                # followers fall back to their own fill; never poison them
                fut.set_result(_FILL_FAILED)
            raise
        self.serving_put(key, value, nbytes, root, notes)
        with self._lock:
            if self._inflight.get(key, (None, None))[1] is fut:
                del self._inflight[key]
        if not fut.done():
            fut.set_result((value, notes))
        return value, notes, True

    # -- mutation (jaxlint J013: funnel call sites only) ----------------------
    def serving_put(
        self, key: bytes, value, nbytes: int, root: str, notes: dict,
    ) -> None:
        if self._cap <= 0 or nbytes > self._cap // 4:
            return  # one panel must not dominate the whole budget
        _freeze(value)
        # lineage: the cache retains the caller's result BY REFERENCE —
        # frozen column blocks file a `reuse` (their epoch guards COW),
        # loose arrays a `view`; either way no bytes move on a fill
        shared = _share_blocks(value, "result_fill")
        rest = max(0, int(nbytes) - shared)
        if rest:
            memtrace.track_bytes(rest, "result_fill", "view")
        with self._lock:
            if key in self._entries:
                return
            self._entries[key] = (value, nbytes, root, dict(notes))
            self._bytes += nbytes
            self._by_root.setdefault(root, set()).add(key)
            self._shrink_locked()
        self._export()

    def serving_invalidate(
        self, root: str, reason: str, time_range=None,
    ) -> int:
        """The invalidation funnel: drop every entry of `root` because
        its data changed (`reason` in flush|compact|delete). The keys
        would never hit again anyway (the SST set / tombstone epoch in
        the key changed) — this frees the bytes eagerly and feeds the
        horaedb_serving_invalidations_total signal the runbooks watch.

        `time_range` (storage TimeRange or None=unknown) names WHAT
        changed; the purge itself is root-granular either way, but
        subscribers (the rule evaluator's dirty sets) use the range to
        bound incremental recomputation."""
        with self._lock:
            keys = self._by_root.pop(root, None)
            dropped = 0
            if keys:
                for k in keys:
                    ent = self._entries.pop(k, None)
                    if ent is not None:
                        self._bytes -= ent[1]
                        dropped += 1
            subscribers = list(self._subscribers.values())
        INVALIDATIONS.labels(reason).inc()
        self._export()
        # notify outside the lock: a subscriber probing the cache (or
        # raising) must never deadlock/fail the commit that fired this
        for cb in subscribers:
            try:
                cb(root, reason, time_range)
            except Exception:  # noqa: BLE001 — error isolation: the
                # commit already happened; a broken consumer only logs
                logger.exception(
                    "serving invalidation subscriber failed "
                    "(root=%s reason=%s)", root, reason,
                )
        return dropped

    # -- the subscription hook (jaxlint J014: serving/ + rules/ only) ---------
    def serving_subscribe(self, callback) -> int:
        """Register `callback(root, reason, time_range)` on the purge
        funnel; returns an unsubscribe token. Callbacks run synchronously
        inside the mutation commit that fired the event (same task, no
        awaits), so they must be cheap — record the dirty fact and
        return; evaluation belongs to the consumer's own tick."""
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._subscribers[token] = callback
        return token

    def serving_unsubscribe(self, token: int) -> None:
        with self._lock:
            self._subscribers.pop(token, None)

    def clear(self) -> None:
        """Test hook: drop everything (not part of the funnel)."""
        with self._lock:
            self._entries.clear()
            self._by_root.clear()
            self._bytes = 0
        self._export()


# The process-global instance every engine shares (keys are globally
# unique — see module doc), sized by the LAST engine open's config.
RESULT_CACHE = ResultCache()


def configure(capacity_bytes: int) -> None:
    RESULT_CACHE.configure(capacity_bytes)
