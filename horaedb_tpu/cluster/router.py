"""Consistent-hash query router embedded in the HTTP tier.

Placement is rendezvous hashing (cluster/__init__.rendezvous_order):
every node computes the same ranking from (key, node-set) with no shared
state, and membership changes move only the keys the departed node
owned. Three routing decisions live here:

- **Writes** forward to the owning writer. A replica forwards the whole
  payload; a partial writer (assignment map splits regions) parses the
  payload once, splits the non-owned series per owner with the SAME
  subset machinery the regioned engine uses, re-encodes each subset to
  wire bytes (`encode_write_request` — exact inverse of the parser for
  the label/sample/exemplar surface), and forwards them while its own
  subset lands locally.
- **Reads** on a writer fan across healthy replicas (rendezvous on the
  query identity so one panel's repeats hit one replica's caches), with
  hedged failover: a replica error or non-2xx marks it unhealthy and the
  query serves from the local engine instead — never a user-visible
  failure because a replica died.
- **Health** comes from `/api/v1/cluster/status` probes on an interval
  plus request outcomes; a recovered probe restores the peer.

Forwarded requests carry `X-Horaedb-Forwarded: 1`; a node never re-routes
a forwarded request (loop guard).

Every outbound hop — write forwarding, split-write fan-out, read
offload, hedged failover, status probes — goes through ONE traced
client funnel (`traced_request`, jaxlint J022): it injects the
cross-node trace headers (X-Horaedb-Trace-Id + parent span) and grafts
the peer's shipped-back span subtree under a node-labeled client span,
so the origin's /debug/traces/{id} shows the whole cross-node tree.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time

import numpy as np

from horaedb_tpu.cluster import (
    FAILOVERS,
    FORWARDS,
    PEER_HEALTHY,
    PROBE_SECONDS,
    WIRE_BYTES,
    ClusterConfig,
    ClusterPeer,
    rendezvous_order,
)
from horaedb_tpu.common import tracing

logger = logging.getLogger(__name__)

FORWARD_HEADER = "X-Horaedb-Forwarded"
STALENESS_HEADER = "X-Horaedb-Staleness-Ms"
STATUS_PATH = "/api/v1/cluster/status"

# request headers that must not be copied onto a forwarded request (hop
# metadata; aiohttp recomputes them for the new body/connection)
_HOP_HEADERS = frozenset((
    "host", "content-length", "transfer-encoding", "connection",
    "accept-encoding",
))


def encode_write_request(req) -> bytes:
    """Re-encode a ParsedWriteRequest to remote-write wire bytes — the
    forwarding inverse of the parser for labels/samples/exemplars/
    metadata. Samples ride the parser's per-series grouping lanes
    (series_sample_start/count), so this is O(total rows), not
    O(series x samples)."""
    from horaedb_tpu.pb import remote_write_pb2

    pb = remote_write_pb2.WriteRequest()
    ex_by_series: dict[int, list[int]] = {}
    for i, s in enumerate(np.asarray(req.exemplar_series).tolist()):
        ex_by_series.setdefault(int(s), []).append(i)
    for s in range(req.n_series):
        ts = pb.timeseries.add()
        for k, v in req.series_labels(s):
            lab = ts.labels.add()
            lab.name = bytes(k)
            lab.value = bytes(v)
        start = int(req.series_sample_start[s])
        count = int(req.series_sample_count[s])
        for i in range(start, start + count):
            smp = ts.samples.add()
            smp.timestamp = int(req.sample_ts[i])
            smp.value = float(req.sample_value[i])
        for i in ex_by_series.get(s, ()):
            ex = ts.exemplars.add()
            ex.timestamp = int(req.exemplar_ts[i])
            ex.value = float(req.exemplar_value[i])
            for k, v in req.exemplar_labels(i):
                lab = ex.labels.add()
                lab.name = bytes(k)
                lab.value = bytes(v)
    for i in range(len(req.meta_type)):
        md = pb.metadata.add()
        md.type = int(req.meta_type[i])
        md.metric_family_name = bytes(req.meta_name(i))
    return pb.SerializeToString()


def split_by_owner(parsed, range_router, assignment, local_node: str):
    """Partial-writer write split: (local ParsedWriteRequest | None,
    {owner_node: wire payload}) — series whose region this node owns
    stay local; the rest group per owning node and re-encode for
    forwarding. Unassigned regions fall to the local node (better a
    ReplicaReadOnlyError naming the problem than a dropped batch)."""
    from horaedb_tpu.engine.region import RegionedEngine, _subset_request

    if parsed.n_series == 0:
        return parsed, {}
    # per-series region ids via the same lanes the regioned engine routes
    # by (recomputed when the native parser didn't supply them)
    need_tsids = range_router.granularity == "series"
    if parsed.series_metric_id is not None and (
        not need_tsids or parsed.series_tsid is not None
    ):
        mids = parsed.series_metric_id
        tsids = parsed.series_tsid if need_tsids else mids
    else:
        shim = object.__new__(RegionedEngine)
        mids, tsids = RegionedEngine._hash_lanes(shim, parsed, need_tsids)
    regions = range_router.regions_of_lanes(mids, tsids)
    owners = np.asarray([
        assignment.owner_of(int(r)) or local_node for r in regions.tolist()
    ])
    local_mask = owners == local_node
    local = None
    if bool(local_mask.all()):
        return parsed, {}
    if bool(local_mask.any()):
        local = _subset_request(parsed, np.flatnonzero(local_mask))
    remote: dict[str, bytes] = {}
    for node in sorted(set(owners.tolist()) - {local_node}):
        sub = _subset_request(parsed, np.flatnonzero(owners == node))
        remote[node] = encode_write_request(sub)
    return local, remote


class ClusterRouter:
    """Peer table + health + forwarding client for one node."""

    def __init__(self, config: ClusterConfig, node_id: str):
        self.config = config
        self.node_id = node_id
        # peers EXCLUDING self (a config listing every member everywhere
        # is the deployment-friendly shape)
        self.peers: dict[str, ClusterPeer] = {
            p.node: p for p in config.peers if p.node != node_id
        }
        self._healthy: dict[str, bool] = {n: True for n in self.peers}
        self._peer_status: dict[str, dict] = {}
        self._assignment = None  # cluster/assignment.Assignment | None
        self._session = None
        self._probe_task: "asyncio.Task | None" = None
        self._closing = False
        for n in self.peers:
            PEER_HEALTHY.labels(n).set(1)

    # -- membership views -----------------------------------------------------
    def replica_nodes(self) -> "list[str]":
        return sorted(
            n for n, p in self.peers.items()
            if p.role == "replica" and self._healthy.get(n)
        )

    def writer_nodes(self) -> "list[str]":
        return sorted(
            n for n, p in self.peers.items()
            if p.role == "writer" and self._healthy.get(n)
        )

    def set_assignment(self, assignment) -> None:
        self._assignment = assignment

    def _adopt_assignment(self, status_body: dict) -> None:
        """Converge on ownership changes made elsewhere: a peer's status
        payload carries its assignment view; a HIGHER version than ours
        is adopted, so a takeover on one node re-routes every other
        node's writes within one probe interval — without this, a
        deposed owner would be routed to forever."""
        from horaedb_tpu.cluster.assignment import Assignment

        try:
            asg = (status_body.get("data") or {}).get("assignment")
            if not asg:
                return
            version = int(asg.get("version", 0))
            if (self._assignment is not None
                    and version <= self._assignment.version):
                return
            self._assignment = Assignment(
                version=version,
                regions={int(r): str(n)
                         for r, n in dict(asg.get("regions") or {}).items()},
            )
            logger.info("adopted assignment v%d from peer status", version)
        except Exception:  # noqa: BLE001 — a malformed peer payload must
            # never kill the probe loop; the store remains ground truth
            logger.warning("ignoring malformed peer assignment payload",
                           exc_info=True)

    @property
    def assignment(self):
        return self._assignment

    def owner_node(self, region_id: int = 0) -> "str | None":
        if self._assignment is not None:
            owner = self._assignment.owner_of(region_id)
            if owner and owner != self.node_id:
                return owner
            if owner == self.node_id:
                return None  # we own it
        # no assignment state: any healthy writer peer is the best guess
        writers = self.writer_nodes()
        return writers[0] if writers else None

    def write_targets(self, region_id: int = 0) -> "list[str]":
        """Forward candidates for a whole-payload write, in order: the
        assigned owner first, then every other healthy writer — a dead
        owner must not 503 writes that any healthy writer could land or
        split-forward itself (partial writers re-split on arrival)."""
        out: list[str] = []
        owner = self.owner_node(region_id)
        if owner is not None:
            out.append(owner)
        for n in self.writer_nodes():
            if n not in out:
                out.append(n)
        return out

    def peer_url(self, node: str) -> "str | None":
        p = self.peers.get(node)
        return p.url or None if p is not None else None

    def pick_read_peer(self, key: bytes) -> "ClusterPeer | None":
        """Rendezvous-ranked healthy replica for this query identity, or
        None (serve locally). Keying by query identity keeps one panel's
        repeats on one replica — its result cache earns its hit rate."""
        nodes = self.replica_nodes()
        if not nodes:
            return None
        for node in rendezvous_order(key, nodes):
            p = self.peers.get(node)
            if p is not None and p.url:
                return p
        return None

    # -- distributed scatter-gather (cluster/partial.py carries the wire) -----
    def compute_nodes(self) -> "list[str]":
        """Peers eligible to compute query fragments: healthy,
        addressable replicas (writers keep their write bandwidth)."""
        return [n for n in self.replica_nodes()
                if (self.peers[n].url or "")]

    def plan_scatter(
        self, regions: "list[int]", max_fanout: int = 0,
    ) -> "dict[str, list[int]] | None":
        """Split `regions` across {self + computing peers}: per-region
        rendezvous preference (affinity-stable: a region keeps hitting
        the same node's caches across queries and routers) under a
        per-node cap of ceil(R/N) — pure rendezvous could hand one node
        everything, and a cap both balances the work and guarantees >= 2
        computing nodes whenever R >= 2. The coordinator always computes
        at least one shard (it holds the data locally and its admission
        slot anchors the EXPLAIN verdict). None = nothing to scatter
        (no eligible peer)."""
        # canonical iteration order: the greedy cap fill must not depend
        # on the caller's region ordering, or two routers would disagree
        regions = sorted({int(r) for r in regions})
        peers = self.compute_nodes()
        if max_fanout > 0:
            # keep the rendezvous-preferred peers for the region SET so
            # a capped fan-out stays affinity-stable too
            key = b",".join(str(r).encode() for r in regions)
            peers = rendezvous_order(key, peers)[:max(0, max_fanout - 1)]
        nodes = [self.node_id] + sorted(peers)
        if len(nodes) < 2 or len(regions) < 2:
            return None
        cap = -(-len(regions) // len(nodes))
        plan: dict[str, list[int]] = {n: [] for n in nodes}
        for r in regions:
            for node in rendezvous_order(str(int(r)).encode(), nodes):
                if len(plan[node]) < cap:
                    plan[node].append(int(r))
                    break
        if not plan[self.node_id]:
            donor = max(plan, key=lambda n: len(plan[n]))
            plan[self.node_id].append(plan[donor].pop())
        return {n: sorted(rs) for n, rs in plan.items() if rs}

    async def fetch_partials(
        self, node: str, body: bytes, headers=None, timeout_s=None,
    ):
        """Ship one fragment request to `node` and return its raw
        partial-grid payload (cluster/partial.py wire bytes), or None on
        any failure — the caller re-runs the shards locally and counts
        the fragment in the fleet `partial`, it never waits. Outcome
        feeds peer health; bytes feed the wire ledger both ways."""
        import aiohttp

        from horaedb_tpu.cluster.partial import WIRE_CONTENT_TYPE

        url = self.peer_url(node)
        if url is None:
            return None
        req_headers = {
            k: v for k, v in dict(headers or {}).items()
            if k.lower() not in _HOP_HEADERS
        }
        req_headers[FORWARD_HEADER] = "1"
        req_headers["Content-Type"] = "application/json"
        kw = {}
        if timeout_s is not None:
            kw["timeout"] = aiohttp.ClientTimeout(total=timeout_s)
        try:
            status, resp_headers, out = await self.traced_request(
                node, "POST", url.rstrip("/") + "/api/v1/query",
                headers=req_headers, body=body, kind="partial_grid", **kw,
            )
            FORWARDS.labels("partial_grid").inc()
            WIRE_BYTES.labels("partial_grid", "tx").inc(len(body))
            WIRE_BYTES.labels("partial_grid", "rx").inc(len(out or b""))
            if status >= 500:
                self.mark_unhealthy(node)
            ctype = (resp_headers.get("Content-Type") or "").split(";")[0]
            if status != 200 or ctype != WIRE_CONTENT_TYPE:
                return None
            return out
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — peer died mid-fragment
            self.mark_unhealthy(node)
            logger.warning("partial-grid fetch from %s failed: %s", node, e)
            return None

    # -- health ---------------------------------------------------------------
    def mark_unhealthy(self, node: str) -> None:
        if self._healthy.get(node):
            logger.warning("cluster peer %s marked unhealthy", node)
        self._healthy[node] = False
        PEER_HEALTHY.labels(node).set(0)

    def is_healthy(self, node: str) -> bool:
        return bool(self._healthy.get(node))

    def mark_healthy(self, node: str) -> None:
        if self._healthy.get(node) is False:
            logger.info("cluster peer %s recovered", node)
        self._healthy[node] = True
        PEER_HEALTHY.labels(node).set(1)

    def peer_status(self) -> dict:
        return {
            n: {
                "role": p.role,
                "url": p.url,
                "healthy": bool(self._healthy.get(n)),
                **({"manifest_epoch":
                        self._peer_status[n].get("manifest_epoch")}
                   if n in self._peer_status else {}),
            }
            for n, p in sorted(self.peers.items())
        }

    def peer_detail(self) -> dict:
        """peer_status() enriched with each peer's last probe body (the
        /debug/cluster fleet page): role as the PEER reports it, its
        manifest epoch / staleness token, its region count, and its load
        view (inflight, queued, breakers, sheds — cluster status carries
        it since the fleet-observability PR). A never-probed or dead
        peer keeps the bare health row — the page degrades, never 500s."""
        out = self.peer_status()
        for node, info in out.items():
            body = (self._peer_status.get(node) or {}).get("data") or {}
            if not isinstance(body, dict):
                continue
            for k in ("role", "standby", "partial", "manifest_epoch",
                      "staleness_ms", "stale", "load"):
                if k in body:
                    info[k] = body[k]
            regions = body.get("regions")
            if isinstance(regions, (dict, list)):
                info["regions"] = len(regions)
        return out

    async def _ensure_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=30, connect=5),
            )
        return self._session

    # -- the traced client funnel ---------------------------------------------
    async def traced_request(
        self,
        node: str,
        method: str,
        url: str,
        *,
        headers=None,
        body: "bytes | None" = None,
        kind: str = "forward",
        timeout=None,
    ):
        """THE outbound cluster HTTP call (jaxlint J022 pins every
        cluster-tier client request here). Opens a `cluster_<kind>`
        client span, injects the cross-node trace headers when a trace
        is active, and grafts the peer's shipped-back span subtree
        (SPANS_HEADER, stripped from the returned headers) under that
        span — the origin's tree gains the remote half, node-labeled.
        Returns (status, headers dict, body bytes); raises on transport
        failure so each caller keeps its own health/fallback policy."""
        session = await self._ensure_session()
        req_headers = dict(headers or {})
        with tracing.span(f"cluster_{kind}", node=node,
                          method=method) as sp:
            tid = tracing.current_trace_id()
            if tid is not None:
                req_headers[tracing.TRACE_HEADER] = tid
                parent = tracing.current_span_id()
                if parent is not None:
                    req_headers[tracing.PARENT_SPAN_HEADER] = str(parent)
            kw = {} if timeout is None else {"timeout": timeout}
            async with session.request(
                method, url, data=body, headers=req_headers, **kw,
            ) as resp:
                out = await resp.read()
                resp_headers = dict(resp.headers)
                shipped = None
                for k in list(resp_headers):
                    if k.lower() == tracing.SPANS_HEADER.lower():
                        shipped = resp_headers.pop(k)
                if sp is not None:
                    sp.attrs["status"] = resp.status
                    if shipped:
                        sp.attrs["remote_spans"] = tracing.graft_remote(
                            shipped, node
                        )
                return resp.status, resp_headers, out

    async def probe_once(self) -> None:
        """One health sweep: GET every peer's cluster status through the
        funnel, timing each probe into
        horaedb_cluster_probe_seconds{peer,outcome}."""
        import aiohttp

        for node, peer in self.peers.items():
            if not peer.url:
                continue
            t0 = time.perf_counter()
            try:
                status, _headers, out = await self.traced_request(
                    node, "GET", peer.url.rstrip("/") + STATUS_PATH,
                    kind="probe", timeout=aiohttp.ClientTimeout(total=5),
                )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — unreachable peer
                PROBE_SECONDS.labels(node, "unreachable").observe(
                    time.perf_counter() - t0
                )
                self.mark_unhealthy(node)
                continue
            outcome = "ok" if status == 200 else "error"
            PROBE_SECONDS.labels(node, outcome).observe(
                time.perf_counter() - t0
            )
            if status == 200:
                try:
                    status_body = json.loads(out)
                except (ValueError, UnicodeDecodeError):
                    status_body = {}
                self._peer_status[node] = status_body
                self.mark_healthy(node)
                self._adopt_assignment(status_body)
            else:
                self.mark_unhealthy(node)

    async def probe_loop(self) -> None:
        interval = self.config.probe_interval.seconds
        while not self._closing:
            try:
                await self.probe_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — keep probing
                logger.exception("cluster probe sweep failed")
            # same lost-cancel guard as the replica watch loop: a cancel
            # swallowed mid-probe must not leave close() waiting out the
            # full probe interval (or forever, on a re-armed loop)
            if self._closing:
                return
            await asyncio.sleep(interval)

    def start_probes(self) -> None:
        if self._probe_task is None and self.peers:
            self._probe_task = asyncio.create_task(
                self.probe_loop(), name="cluster-peer-probe"
            )

    # -- forwarding -----------------------------------------------------------
    async def forward(
        self,
        node: str,
        method: str,
        path_qs: str,
        headers,
        body: "bytes | None",
        kind: str,
    ):
        """Proxy one request to `node`; returns (status, headers, body)
        or None when the peer is unknown/unreachable (the caller serves
        locally / errors). Outcome feeds the peer's health."""
        url = self.peer_url(node)
        if url is None:
            return None
        fwd_headers = {
            k: v for k, v in headers.items()
            if k.lower() not in _HOP_HEADERS
        }
        fwd_headers[FORWARD_HEADER] = "1"
        t0 = time.perf_counter()
        try:
            status, resp_headers, out = await self.traced_request(
                node, method, url.rstrip("/") + path_qs,
                headers=fwd_headers, body=body, kind=kind,
            )
            FORWARDS.labels(kind).inc()
            if kind in ("write", "read"):
                WIRE_BYTES.labels(kind, "tx").inc(len(body or b""))
                WIRE_BYTES.labels(kind, "rx").inc(len(out or b""))
            if status >= 500:
                self.mark_unhealthy(node)
            return status, resp_headers, out
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — peer down mid-request
            self.mark_unhealthy(node)
            logger.warning(
                "forward %s %s to %s failed after %.3fs: %s",
                method, path_qs, node, time.perf_counter() - t0, e,
            )
            return None

    def note_failover(self) -> None:
        FAILOVERS.inc()

    async def close(self) -> None:
        self._closing = True
        if self._probe_task is not None:
            self._probe_task.cancel()
            try:
                await self._probe_task
            except asyncio.CancelledError:
                pass
            self._probe_task = None
        if self._session is not None:
            await self._session.close()
            self._session = None
