"""Stateless read replica: a read-only engine view tailing the writer's
manifests over the shared object store.

Mechanics (package docstring has the architecture):

- The engine opens with `read_only=True` end to end (engine/engine.py →
  storage/storage.py → storage/manifest): no fence, no compaction, no
  orphan GC, no sidecar dumps — the replica NEVER writes the bucket.
- A watch loop probes each region root for change: one conditional GET
  on every table's manifest snapshot (`ObjectStore.get_if_changed`,
  ETag/If-None-Match — an unchanged probe costs no transfer on stores
  with real ETags) plus LISTs of the delta/tombstone/rollup dirs. The
  composed digest IS the change token; an unchanged token refreshes the
  staleness clock for free.
- On change, the replica opens a FRESH read-only view (the full manifest
  fold + index replay the normal open runs) and atomically swaps it in —
  in-flight queries keep the old view via their own references, and
  read-only engines hold no background state, so the old view closes
  safely after the swap. Regioned deployments swap per REGION
  (RegionedEngine.refresh_region), so one busy region never pays for a
  quiet one; a REGIONS-descriptor change (split) reopens the whole tree.
- Every swap routes through the serving invalidation funnel
  (`serving_invalidate`) with the mutation's time range — the union of
  time ranges of SSTs/tombstones that changed between the views — so
  replica-side result caches and rule dirty-sets stay invalidation-
  correct exactly like a local write commit would have left them.

Staleness contract: the token is (manifest epoch, lag ms). The epoch is
`Manifest.epoch()` floored monotonic (GC can retire the max id; the
surfaced token never moves backwards); the lag is the time since the
last probe that CONFIRMED the view matches the store. Queries on a
replica carry it in the EXPLAIN `cluster` verdict and the
`X-Horaedb-Staleness-Ms` response header; `/api/v1/cluster/status`
compares epochs writer-vs-replica — equality is catch-up.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time

from horaedb_tpu.cluster import REFRESHES, REPLICA_EPOCH, REPLICA_LAG, WATCH_ERRORS
from horaedb_tpu.common import tracing
from horaedb_tpu.common.error import ReplicaReadOnlyError
from horaedb_tpu.objstore import NotFound
from horaedb_tpu.storage.types import TimeRange

logger = logging.getLogger(__name__)

ENGINE_TABLES = ("metrics", "series", "index", "tags", "data", "exemplars")
# result-cache / rule-dirty-set bearing tables (the funnel's audience)
SAMPLE_TABLES = ("data", "exemplars")


def _table_diff_range(old_table, new_table):
    """(changed?, union TimeRange of what changed, tombstones_changed?)
    between two manifest views of one table root."""
    old_ssts = {s.id: s.meta.time_range for s in old_table.manifest.all_ssts()}
    new_ssts = {s.id: s.meta.time_range for s in new_table.manifest.all_ssts()}
    old_tombs = {t.id: t.time_range for t in old_table.manifest.all_tombstones()}
    new_tombs = {t.id: t.time_range for t in new_table.manifest.all_tombstones()}
    changed_ids = set(old_ssts) ^ set(new_ssts)
    changed_tombs = set(old_tombs) ^ set(new_tombs)
    if not changed_ids and not changed_tombs:
        return False, None, False
    lo, hi = None, None
    for rid in changed_ids:
        rng = old_ssts.get(rid) or new_ssts[rid]
        lo = rng.start if lo is None else min(lo, rng.start)
        hi = rng.end if hi is None else max(hi, rng.end)
    for tid in changed_tombs:
        rng = old_tombs.get(tid) or new_tombs[tid]
        lo = rng.start if lo is None else min(lo, rng.start)
        hi = rng.end if hi is None else max(hi, rng.end)
    rng = TimeRange(int(lo), int(hi)) if lo is not None else None
    return True, rng, bool(changed_tombs)


def invalidate_swapped_views(old_engine, new_engine) -> int:
    """Satellite contract (ISSUE 15): a replica's snapshot swap is its
    flush/delete commit — route it through the serving invalidation
    funnel with the mutation's time range so the result cache purges and
    the rule evaluator's dirty sets see the event, exactly like a local
    write would have. Returns funnel events fired."""
    from horaedb_tpu.serving.cache import RESULT_CACHE

    fired = 0
    old_subs = old_engine.sub_engines()
    for prefix, new_sub in new_engine.sub_engines().items():
        old_sub = old_subs.get(prefix)
        if old_sub is None:
            continue  # fresh region (split): nothing cached under it yet
        for name in SAMPLE_TABLES:
            old_t = getattr(old_sub, f"{name}_table")
            new_t = getattr(new_sub, f"{name}_table")
            changed, rng, tombs = _table_diff_range(old_t, new_t)
            if not changed:
                continue
            reason = "delete" if tombs else "flush"
            RESULT_CACHE.serving_invalidate(new_t._root, reason, rng)
            fired += 1
    return fired


class ReplicaEngine:
    """Read-only engine facade with the watch/swap loop. Delegates the
    entire query/discovery surface to the current view (atomic reference
    swap), so the HTTP tier uses it exactly like an engine."""

    def __init__(self) -> None:
        raise RuntimeError("use ReplicaEngine.open")

    @classmethod
    async def open(
        cls,
        root: str,
        store,
        num_regions: int = 1,
        granularity: str = "series",
        watch_interval_s: float = 2.0,
        watch_backoff_cap_s: float = 30.0,
        engine_kwargs: "dict | None" = None,
        open_retries: int = 0,
        open_retry_delay_s: float = 0.5,
    ) -> "ReplicaEngine":
        """Open the read-only view. `open_retries` > 0 waits for the
        writer to have created the store layout (REGIONS descriptor /
        first manifests) instead of failing a racing boot."""
        self = object.__new__(cls)
        self._root = root
        self._store = store
        self._num_regions = num_regions
        self._granularity = granularity
        self._engine_kwargs = dict(engine_kwargs or {})
        self._engine_kwargs["read_only"] = True
        self._interval_s = watch_interval_s
        self._backoff_cap_s = watch_backoff_cap_s
        self._etags: dict[str, str | None] = {}
        self._tokens: dict[str, str] = {}
        self._desc_token: "str | None" = None
        self._epoch_floor = 0
        self._consecutive_errors = 0
        self._swaps = 0
        self._watch_task: "asyncio.Task | None" = None
        self._closing = False
        self._refresh_lock = asyncio.Lock()
        self._engine = None
        last: "BaseException | None" = None
        for attempt in range(max(1, open_retries + 1)):
            try:
                eng = await self._open_view()
            except NotFound as e:
                last = e
                if attempt < open_retries:
                    await asyncio.sleep(open_retry_delay_s)
                continue
            if (not self._regioned and attempt < open_retries
                    and not await self._store.list(self._root)):
                # single-engine roots have no boot marker (the regioned
                # path waits on the REGIONS descriptor): ZERO objects
                # under the root inside the retry window means the
                # writer hasn't booted — wait instead of confidently
                # serving nothing. A booted-but-idle writer has already
                # left layout (index sidecar, fence, manifests) and its
                # truthful answer IS empty, so it opens immediately; the
                # watch loop swaps in the first flush.
                await eng.close()
                await asyncio.sleep(open_retry_delay_s)
                continue
            self._engine = eng
            break
        if self._engine is None:
            raise ReplicaReadOnlyError(
                f"replica open: no store layout under {root!r} yet "
                "(is the writer up?)", cause=last,
            )
        # prime the watch tokens so the first loop probe compares against
        # the view just opened, not against nothing
        for eroot in self._engine_roots():
            self._tokens[eroot] = await self._root_token(eroot)
        if self._regioned:
            self._desc_token = await self._descriptor_token()
        self._last_sync = time.monotonic()
        self._export()
        return self

    # -- view management ------------------------------------------------------
    @property
    def _regioned(self) -> bool:
        return self._num_regions > 1

    async def _open_view(self):
        if self._regioned:
            from horaedb_tpu.engine.region import RegionedEngine

            return await RegionedEngine.open(
                self._root, self._store, self._num_regions,
                granularity=self._granularity, **self._engine_kwargs,
            )
        from horaedb_tpu.engine.engine import MetricEngine

        return await MetricEngine.open(
            self._root, self._store, **self._engine_kwargs,
        )

    def _engine_roots(self) -> "list[str]":
        if self._regioned:
            return [f"{self._root}/region-{i}" for i in sorted(self._engine.engines)]
        return [self._root]

    @property
    def engine(self):
        """The current read-only view (atomic reference; swapped whole)."""
        return self._engine

    @property
    def read_only(self) -> bool:
        return True

    def __getattr__(self, name: str):
        # the full engine surface (query/labels/series/metadata/...)
        # delegates to the CURRENT view; mutations raise from the view's
        # own read-only guards. __getattr__ only fires for names this
        # facade doesn't define. Private names never delegate — during
        # open, a missing private attr delegating through a missing
        # `_engine` would recurse.
        if name.startswith("_"):
            raise AttributeError(name)
        eng = self.__dict__.get("_engine")
        if eng is None:
            raise AttributeError(name)
        return getattr(eng, name)

    # -- staleness token ------------------------------------------------------
    def manifest_epoch(self) -> int:
        """Floored-monotonic manifest epoch (the staleness token's first
        half): GC retiring the max record id must not move the surfaced
        token backwards."""
        self._epoch_floor = max(self._epoch_floor,
                                self._engine.manifest_epoch())
        return self._epoch_floor

    def staleness_ms(self) -> float:
        """Milliseconds since the view was last CONFIRMED current (an
        unchanged probe or a completed swap)."""
        return max(0.0, (time.monotonic() - self._last_sync) * 1000.0)

    def staleness(self) -> dict:
        return {
            "manifest_epoch": self.manifest_epoch(),
            "staleness_ms": round(self.staleness_ms(), 1),
        }

    def _export(self) -> None:
        REPLICA_EPOCH.set(self.manifest_epoch())
        REPLICA_LAG.set(round(self.staleness_ms() / 1000.0, 3))

    def watch_stats(self) -> dict:
        """The watch loop's health in one dict (/debug/cluster's replica
        row): lag token plus the loop's error/backoff posture — an
        operator reads "is this replica keeping up, and if not, is it
        the store or the writer" without grepping logs."""
        return {
            **self.staleness(),
            "watch_interval_s": self._interval_s,
            "backoff_s": round(self.backoff_s(), 3),
            "consecutive_errors": self._consecutive_errors,
            "swaps": self._swaps,
        }

    # -- the watch loop -------------------------------------------------------
    async def _root_token(self, eroot: str) -> str:
        """Change token for one region root: conditional-GET ETag of each
        table's manifest snapshot + the delta/tombstone/rollup listings.
        Any commit anywhere in the region changes it (a flush writes a
        delta; a fold rewrites the snapshot AND empties the delta dir; a
        delete adds a tombstone record; compaction reshapes all three)."""
        h = hashlib.blake2b(digest_size=16)
        for table in ENGINE_TABLES:
            troot = f"{eroot}/{table}"
            snap = f"{troot}/manifest/snapshot"
            try:
                _data, etag = await self._store.get_if_changed(
                    snap, self._etags.get(snap)
                )
                self._etags[snap] = etag
            except NotFound:
                self._etags[snap] = None
            h.update(str(self._etags[snap]).encode())
            for sub in ("delta", "tombstone", "rollup"):
                metas = await self._store.list(f"{troot}/manifest/{sub}")
                h.update(b"|")
                h.update(",".join(m.path for m in metas).encode())
            h.update(b"#")
        return h.hexdigest()

    async def _descriptor_token(self) -> "str | None":
        path = f"{self._root}/REGIONS"
        try:
            _data, etag = await self._store.get_if_changed(
                path, self._etags.get(path)
            )
            self._etags[path] = etag
            return etag
        except NotFound:
            return None

    async def watch_once(self) -> str:
        """One probe-and-maybe-swap pass. Returns "unchanged", "refreshed",
        or raises on store failure (the loop counts + backs off)."""
        async with self._refresh_lock:
            refreshed = False
            if self._regioned:
                desc = await self._descriptor_token()
                if desc != self._desc_token:
                    # meta-plane change (split): the region SET moved —
                    # reopen the whole tree
                    await self._swap_full()
                    self._desc_token = desc
                    refreshed = True
                else:
                    for eroot in self._engine_roots():
                        tok = await self._root_token(eroot)
                        if tok != self._tokens.get(eroot):
                            region_id = int(eroot.rsplit("-", 1)[-1])
                            await self._swap_region(region_id)
                            self._tokens[eroot] = tok
                            refreshed = True
            else:
                eroot = self._root
                tok = await self._root_token(eroot)
                if tok != self._tokens.get(eroot):
                    await self._swap_full()
                    self._tokens[eroot] = tok
                    refreshed = True
            self._last_sync = time.monotonic()
            self._consecutive_errors = 0
            self._export()
            if refreshed:
                REFRESHES.labels("ok").inc()
                return "refreshed"
            REFRESHES.labels("unchanged").inc()
            return "unchanged"

    async def _swap_full(self) -> None:
        old = self._engine
        with tracing.span("replica_swap_full", root=self._root):
            fresh = await self._open_view()
            fired = invalidate_swapped_views(old, fresh)
        self._engine = fresh
        self._swaps += 1
        # re-prime per-root tokens (the region set may have changed);
        # anything committed between token and swap shows as one harmless
        # extra refresh on the next probe
        for eroot in self._engine_roots():
            self._tokens[eroot] = await self._root_token(eroot)
        await old.close()
        logger.info(
            "replica %s: full snapshot swap (epoch %d, %d invalidations)",
            self._root, self.manifest_epoch(), fired,
        )

    async def _swap_region(self, region_id: int) -> None:
        old_sub = self._engine.engines[region_id]
        # refresh_region swaps inside the RegionedEngine; diff the views
        # through a one-region facade pair for the funnel events
        class _One:
            def __init__(self, sub, rid):
                self._sub, self._rid = sub, rid

            def sub_engines(self):
                return {f"region-{self._rid}/": self._sub}

        with tracing.span("replica_swap_region", region=region_id):
            await self._engine.refresh_region(region_id)
            invalidate_swapped_views(
                _One(old_sub, region_id),
                _One(self._engine.engines[region_id], region_id),
            )
        self._swaps += 1
        logger.info(
            "replica %s: region %d snapshot swap (epoch %d)",
            self._root, region_id, self.manifest_epoch(),
        )

    def backoff_s(self) -> float:
        """Current watch-loop delay: the base interval, doubled per
        consecutive probe failure, capped — a faulted store costs
        bounded probe traffic, and one success resets the ladder."""
        if self._consecutive_errors == 0:
            return self._interval_s
        return min(
            self._backoff_cap_s,
            self._interval_s * (2 ** self._consecutive_errors),
        )

    def note_watch_error(self) -> None:
        # jaxlint: disable=J004 loop-confined; fires after watch_once raised OUT of the lock
        self._consecutive_errors += 1
        WATCH_ERRORS.inc()

    async def watch_loop(self) -> None:
        """The background tail loop (server/main.py owns the task)."""
        while not self._closing:
            try:
                await self.watch_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — faulted store: backoff
                self.note_watch_error()
                REFRESHES.labels("error").inc()
                self._export()
                logger.warning(
                    "replica watch probe failed (%d consecutive): %s",
                    self._consecutive_errors, e,
                )
            # re-check before the (up to backoff-cap) sleep: close()'s
            # cancel can be swallowed by the asyncio.wait_for race in the
            # resilient store's attempt loop (bpo-37658 on 3.10) when it
            # lands exactly as an inner op completes — without the flag,
            # a lost cancel turns close() into a full-backoff stall
            if self._closing:
                return
            await asyncio.sleep(self.backoff_s())

    def start_watch(self) -> None:
        if self._watch_task is None:
            self._watch_task = asyncio.create_task(
                self.watch_loop(), name="cluster-replica-watch"
            )

    async def close(self) -> None:
        self._closing = True
        if self._watch_task is not None:
            self._watch_task.cancel()
            try:
                await self._watch_task
            except asyncio.CancelledError:
                pass
            self._watch_task = None
        await self._engine.close()
