"""Fence-protected region-assignment map over the shared object store.

The RFC's meta plane maps each region to exactly one writer node
(docs/rfcs/20240827-metric-engine.md:28-76). Without a meta service, the
store itself arbitrates — the same monotonic-version conditional-put
pattern as epoch fencing (storage/fence.py):

- The map is a JSON record `{version, regions: {region_id: node},
  updated_by, updated_unix_ms}` persisted at
  `{cluster_root}/assignment/{version:020d}`.
- To mutate, read the current max version, apply the change, and
  `put_if_absent` version+1. Exactly one contender can create a given
  version (S3 `If-None-Match: *`); losers re-read and retry — a stale
  proposer can never silently clobber a concurrent claim.
- Highest version wins, forever. Records are never deleted: the dir
  stays tiny (one object per ownership change) and doubles as an
  ownership audit log, exactly like the fence dir.

The map is ROUTING state, not the safety mechanism: data safety is the
region's epoch fence. `takeover` therefore writes the new assignment
version FIRST (so routers converge on the new owner) and then acquires a
fresh epoch fence on each taken region root — the moment the fence
lands, the lapsed writer's next manifest mutation raises FencedError
regardless of what any router believes. A crash between the two steps
leaves routing pointing at a node that never claimed the fences; the old
writer keeps working until a retried takeover completes — inconsistent
routing, never split-brain.

jaxlint J017 pins assignment-record mutation to this module: a second
writer of `cluster/assignment` objects would fork the meta plane.
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass, field

from horaedb_tpu.common.error import HoraeError, ensure
from horaedb_tpu.objstore import ObjectStore, PreconditionFailed

logger = logging.getLogger(__name__)

ASSIGNMENT_DIR = "assignment"


def assignment_dir(cluster_root: str) -> str:
    return f"{cluster_root.rstrip('/')}/{ASSIGNMENT_DIR}"


def assignment_path(cluster_root: str, version: int) -> str:
    return f"{assignment_dir(cluster_root)}/{version:020d}"


def _version_of(path: str) -> int:
    try:
        return int(path.rsplit("/", 1)[-1])
    except ValueError:
        return -1


@dataclass(frozen=True)
class Assignment:
    """One decoded assignment-map version: region id -> owning node."""

    version: int = 0
    regions: "dict[int, str]" = field(default_factory=dict)
    updated_by: str = ""
    updated_unix_ms: int = 0

    def owner_of(self, region_id: int) -> "str | None":
        return self.regions.get(int(region_id))

    def regions_of(self, node: str) -> "list[int]":
        return sorted(r for r, n in self.regions.items() if n == node)

    def to_json(self) -> bytes:
        return json.dumps({
            "version": self.version,
            "regions": {str(r): n for r, n in sorted(self.regions.items())},
            "updated_by": self.updated_by,
            "updated_unix_ms": self.updated_unix_ms,
        }).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "Assignment":
        try:
            d = json.loads(data.decode())
            return cls(
                version=int(d["version"]),
                regions={int(r): str(n)
                         for r, n in dict(d.get("regions") or {}).items()},
                updated_by=str(d.get("updated_by", "")),
                updated_unix_ms=int(d.get("updated_unix_ms", 0)),
            )
        except HoraeError:
            raise
        except Exception as e:  # noqa: BLE001 — corrupt record, typed error
            raise HoraeError(f"corrupt assignment record: {e}") from e


async def load_assignment(store: ObjectStore, cluster_root: str) -> Assignment:
    """The current (highest-version) assignment; empty when none exists.
    A corrupt NEWEST record fails loudly — silently falling back to an
    older version would reroute writes to a deposed owner."""
    metas = [
        m for m in await store.list(assignment_dir(cluster_root))
        if _version_of(m.path) >= 0
    ]
    if not metas:
        return Assignment()
    newest = max(metas, key=lambda m: _version_of(m.path))
    return Assignment.from_json(await store.get(newest.path))


async def propose_assignment(
    store: ObjectStore,
    cluster_root: str,
    node_id: str,
    mutate,
    max_attempts: int = 16,
) -> Assignment:
    """CAS loop: read the current map, apply `mutate(regions_dict) ->
    regions_dict`, put_if_absent the next version. Returns the committed
    Assignment. `mutate` returning the UNCHANGED dict short-circuits
    without a write (idempotent boot claims). Losing the conditional put
    re-reads and re-applies — the fenced mutation API J017 pins."""
    for _ in range(max_attempts):
        cur = await load_assignment(store, cluster_root)
        new_regions = mutate(dict(cur.regions))
        ensure(isinstance(new_regions, dict),
               "assignment mutate must return the regions dict")
        new_regions = {int(r): str(n) for r, n in new_regions.items()}
        if new_regions == cur.regions:
            return cur
        nxt = Assignment(
            version=cur.version + 1,
            regions=new_regions,
            updated_by=node_id,
            updated_unix_ms=int(time.time() * 1000),
        )
        try:
            await store.put_if_absent(
                assignment_path(cluster_root, nxt.version), nxt.to_json()
            )
        except PreconditionFailed:
            continue  # another proposer won this version; re-read
        logger.info(
            "assignment v%d committed by %s: %s",
            nxt.version, node_id, nxt.regions,
        )
        return nxt
    raise HoraeError(
        f"could not commit assignment on {cluster_root} after "
        f"{max_attempts} attempts (heavy meta-plane contention)"
    )


def bootstrap_regions(
    region_ids: "list[int]", writer_nodes: "list[str]"
) -> "dict[int, str]":
    """Deterministic default split: rendezvous-hash each region id over
    the writer set, so every writer boots to the same proposal without
    coordination (the CAS commit then makes one of them the author)."""
    from horaedb_tpu.cluster import rendezvous_pick

    ensure(bool(writer_nodes), "cluster needs at least one writer node")
    return {
        int(r): rendezvous_pick(str(int(r)).encode(), list(writer_nodes))
        for r in region_ids
    }


async def claim_regions(
    store: ObjectStore,
    cluster_root: str,
    node_id: str,
    region_ids: "list[int]",
    writer_nodes: "list[str] | None" = None,
) -> Assignment:
    """Boot-time claim: ensure every region in `region_ids` has an owner,
    claiming unowned ones per the rendezvous bootstrap (or to `node_id`
    when it is the only writer). Never steals an owned region — that is
    `takeover`'s explicit job."""
    writers = list(writer_nodes or [node_id])
    if node_id not in writers:
        writers.append(node_id)
    defaults = bootstrap_regions(region_ids, writers)

    def mutate(regions: dict) -> dict:
        for r in region_ids:
            regions.setdefault(int(r), defaults[int(r)])
        return regions

    return await propose_assignment(store, cluster_root, node_id, mutate)


async def takeover_region(
    store: ObjectStore,
    root: str,
    cluster_root: str,
    node_id: str,
    region_id: int,
    region_root: str,
    fence_validate_interval_s: float = 5.0,
):
    """Take ownership of `region_id` from its (presumed lapsed) writer:
    commit the assignment rewrite, then acquire a fresh epoch fence on
    `region_root` — the acquisition mints a HIGHER epoch, so the deposed
    writer's next fenced mutation raises FencedError no matter what it
    believes about the assignment map. Returns (Assignment, EpochFence).

    `root` is unused beyond logging symmetry with the engine roots; the
    fence root is the region's engine root (one fence covers all six
    tables of the region, engine/engine.py)."""
    from horaedb_tpu.cluster import TAKEOVERS
    from horaedb_tpu.storage.fence import EpochFence

    def mutate(regions: dict) -> dict:
        regions[int(region_id)] = node_id
        return regions

    asg = await propose_assignment(store, cluster_root, node_id, mutate)
    fence = await EpochFence.acquire(
        store, region_root.strip("/"), node_id,
        validate_interval_s=fence_validate_interval_s,
    )
    TAKEOVERS.inc()
    logger.info(
        "takeover: node=%s region=%d root=%s assignment_v=%d epoch=%d",
        node_id, region_id, region_root, asg.version, fence.epoch,
    )
    return asg, fence
