"""Cluster layer: stateless read replicas over shared object storage.

The HoraeDB v2 design's scale-out story (RFC :28-76) is shared object
storage as the data plane plus range-partitioned regions routed by an
assignment map — the meta-service architecture, minus the meta service.
Every prerequisite shipped piecemeal in this tree: the epoch fence gives
single-writer-per-region (storage/fence.py), the result-cache key
(sealed-SST set + tombstone epoch) is a correct bounded-staleness token
(serving/cache.py), and ResilientStore makes the shared store survivable.
This package composes them into horizontal scale-out — the Taurus
near-data-processing argument (arXiv:2506.20010): compute should be
stateless replicas over one durable log, applied to an LSM-over-S3
metric engine.

Three modules:

- **replica.py** — a stateless read-replica mode (`role = "replica"`):
  the engine opens READ-ONLY against the shared store and tails each
  region's manifest with a cheap conditional-GET watch loop
  (`ObjectStore.get_if_changed`, ETag/If-None-Match — the fence-probe
  machinery's sibling), atomically swapping in new sealed-SST/tombstone/
  rollup snapshots. Queries serve with bounded staleness; the staleness
  token (manifest epoch + lag ms) rides the EXPLAIN `cluster` verdict,
  the `X-Horaedb-Staleness-Ms` response header, and
  `horaedb_cluster_replica_lag_seconds`.
- **assignment.py** — a fence-protected region-assignment map persisted
  in the object store (`{root}/cluster/assignment/{version}` records,
  put_if_absent-arbitrated exactly like epoch claims) so multiple
  writer processes split regions; takeover = a new assignment version +
  a higher epoch fence on the region root, which deposes the lapsed
  writer mid-flight (jaxlint J017 pins mutation to this module's API).
- **router.py** — a consistent-hash (rendezvous) query router embedded
  in the HTTP tier: writes forward to the owning writer, reads fan
  across healthy replicas with hedged failover to the local engine on
  replica error, health-checked via `/api/v1/cluster/status`.

Topology contract: N processes, one bucket. Exactly one writer owns each
region's epoch fence at a time; any number of replicas serve reads with
bounded staleness; a standby writer takes over a lapsed fence without
coordination beyond the store itself.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from horaedb_tpu.common.hash import seahash
from horaedb_tpu.common.time_ext import ReadableDuration
from horaedb_tpu.server.metrics import GLOBAL_METRICS

# -- metric families (pre-registered zero states so /metrics shows them
# -- from boot, the PR2 convention) ------------------------------------------

REPLICA_LAG = GLOBAL_METRICS.gauge(
    "horaedb_cluster_replica_lag_seconds",
    help="Seconds since this replica last confirmed its view matches the "
         "shared store (a successful watch probe with no change, or a "
         "completed snapshot swap). The bounded-staleness number the "
         "X-Horaedb-Staleness-Ms response header surfaces per query.",
)
REPLICA_EPOCH = GLOBAL_METRICS.gauge(
    "horaedb_cluster_manifest_epoch",
    help="This process's manifest epoch (max live record id across "
         "tables/regions, floored monotonic). Writer-vs-replica equality "
         "IS the catch-up check.",
)
REFRESHES = GLOBAL_METRICS.counter(
    "horaedb_cluster_refreshes_total",
    help="Replica snapshot swaps, by outcome: ok (fresh view swapped "
         "in), error (open failed; backoff + retry), unchanged (watch "
         "probe found nothing new).",
    labelnames=("result",),
)
WATCH_ERRORS = GLOBAL_METRICS.counter(
    "horaedb_cluster_watch_errors_total",
    help="Watch-loop probe failures (faulted store); each grows the "
         "loop's exponential backoff until the next success resets it.",
)
FORWARDS = GLOBAL_METRICS.counter(
    "horaedb_cluster_forwards_total",
    help="Requests the cluster router forwarded to a peer, by kind: "
         "write (replica/non-owner -> owning writer), read (writer -> "
         "replica offload).",
    labelnames=("kind",),
)
FAILOVERS = GLOBAL_METRICS.counter(
    "horaedb_cluster_failovers_total",
    help="Hedged read failovers: a routed replica answered with an "
         "error (or was unreachable) and the query was served by the "
         "local engine instead.",
)
TAKEOVERS = GLOBAL_METRICS.counter(
    "horaedb_cluster_takeovers_total",
    help="Region ownership takeovers this process performed (assignment "
         "record rewrite + fresh epoch fence deposing the lapsed writer).",
)
PEER_HEALTHY = GLOBAL_METRICS.gauge(
    "horaedb_cluster_peer_healthy",
    help="Peer health as the router sees it (1 healthy / 0 not), from "
         "/api/v1/cluster/status probes and request outcomes.",
    labelnames=("node",),
)
PROBE_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_cluster_probe_seconds",
    help="Peer status-probe latency through the traced client funnel, "
         "by peer and outcome: ok (2xx), error (non-2xx answer), "
         "unreachable (connect/timeout failure).",
    labelnames=("peer", "outcome"),
)
FLEET_PARTIALS = GLOBAL_METRICS.counter(
    "horaedb_cluster_fleet_partials_total",
    help="Federated EXPLAIN merges that degraded: a remote fragment was "
         "missing (dead peer, non-explain answer, hedged failover) and "
         "the fleet verdict counted it in `partial` instead of hanging.",
)
WIRE_BYTES = GLOBAL_METRICS.counter(
    "horaedb_cluster_wire_bytes_total",
    help="Bytes the cluster tier moved between nodes, by kind (write/"
         "read forwarding payloads, partial_grid scatter-gather "
         "fragments) and direction as this node saw them (tx = request "
         "body sent, rx = response body received). The near-data claim "
         "in numbers: partial_grid rx stays at bucket scale while the "
         "rows it summarizes never cross the wire.",
    labelnames=("kind", "direction"),
)

for _r in ("ok", "error", "unchanged"):
    REFRESHES.labels(_r)
for _k in ("write", "read", "partial_grid"):
    FORWARDS.labels(_k)
for _k in ("write", "read", "partial_grid"):
    for _d in ("tx", "rx"):
        WIRE_BYTES.labels(_k, _d)


# -- federated EXPLAIN -------------------------------------------------------

def fleet_fragment(node: str, explain: dict | None) -> dict | None:
    """Extract one node's contribution to the fleet verdict from its full
    EXPLAIN payload: the identity + staleness token + the sub-verdicts an
    operator compares across nodes. Returns None when the payload isn't
    an EXPLAIN dict (the caller counts it as a partial)."""
    if not isinstance(explain, dict):
        return None
    cluster = explain.get("cluster")
    cluster = cluster if isinstance(cluster, dict) else {}
    frag = {
        "node": cluster.get("node", node),
        "role": cluster.get("role", "unknown"),
        "staleness_ms": float(cluster.get("staleness_ms", 0.0) or 0.0),
        "manifest_epoch": cluster.get("manifest_epoch"),
        "cluster": cluster,
    }
    for key in ("serving", "admission", "encoding", "memory"):
        if isinstance(explain.get(key), dict):
            frag[key] = explain[key]
    # scatter-gather provenance: which region shards this node computed
    # and how many fragment bytes it shipped back
    for key in ("regions", "wire_bytes"):
        if key in cluster:
            frag[key] = cluster[key]
    return frag


def fleet_verdict(origin: str, fragments: "list[dict]",
                  partial: int = 0,
                  wire_bytes: "int | None" = None) -> dict:
    """Merge per-node EXPLAIN fragments into the pinned-schema `fleet`
    verdict — the merge surface both the whole-forward read path and the
    distributed scatter-gather reuse. Schema (stable; cluster_smoke +
    the chaos lane assert it):

        origin        node id that ran the merge
        nodes         per-node fragments (fleet_fragment), origin first
        staleness_ms  MAX across fragments — the result is only as fresh
                      as its stalest contributor
        partial       fragments lost to dead/degraded peers (counted,
                      never waited for)
        wire_bytes    response/fragment bytes that crossed the wire for
                      THIS query (present when the path measured them) —
                      the per-query face of
                      horaedb_cluster_wire_bytes_total
    """
    if partial:
        FLEET_PARTIALS.inc(partial)
    out = {
        "origin": origin,
        "nodes": fragments,
        "staleness_ms": max(
            (f.get("staleness_ms", 0.0) for f in fragments), default=0.0
        ),
        "partial": int(partial),
    }
    if wire_bytes is not None:
        out["wire_bytes"] = int(wire_bytes)
    return out


def rendezvous_order(key: bytes, nodes: "list[str]") -> "list[str]":
    """Highest-random-weight (rendezvous) ranking of `nodes` for `key`:
    every router instance computes the same order with no shared state,
    and removing a node only moves the keys it owned (the minimal-
    disruption property consistent hashing exists for). Used for
    read fan-out (key = a query identity) and the default region ->
    writer assignment (key = the region id)."""
    return sorted(
        nodes,
        key=lambda n: seahash(key + b"\x00" + n.encode()),
        reverse=True,
    )


def rendezvous_pick(key: bytes, nodes: "list[str]") -> "str | None":
    order = rendezvous_order(key, nodes)
    return order[0] if order else None


@dataclass
class ClusterPeer:
    """One peer process in the cluster ([[metric_engine.cluster.peers]])."""

    node: str = ""
    url: str = ""
    role: str = "writer"  # "writer" | "replica"

    @classmethod
    def from_dict(cls, d: dict) -> "ClusterPeer":
        from horaedb_tpu.common.error import ensure

        unknown = set(d) - {"node", "url", "role"}
        ensure(not unknown,
               f"unknown cluster peer keys: {sorted(unknown)}")
        p = cls(node=str(d.get("node", "")), url=str(d.get("url", "")),
                role=str(d.get("role", "writer")).lower())
        ensure(bool(p.node), "cluster peer needs a node id")
        ensure(p.role in ("writer", "replica"),
               f"cluster peer role must be writer|replica, got {p.role!r}")
        return p


@dataclass
class DistributedConfig:
    """`[metric_engine.cluster.distributed]` — the scatter-gather read
    path (docs/operations.md "Distributed query execution"). Applies
    only on a regioned writer with healthy computing peers; everything
    else (standalone, single region, no peers, forwarded requests)
    executes exactly as before."""

    # split eligible grid queries across computing nodes instead of
    # forwarding them whole (the whole-forward offload stays the
    # fallback whenever a query is not split-eligible)
    enabled: bool = True
    # a query must fan over at least this many regions to be worth
    # splitting (below it, per-fragment overhead beats the parallelism)
    min_regions: int = 2
    # cap on computing nodes per query, self included (0 = no cap)
    max_fanout: int = 0
    # per-fragment budget: a peer slower than this is treated as dead
    # (its shards re-run locally and count in the fleet `partial`)
    fragment_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(10)
    )

    @classmethod
    def from_dict(cls, d: "dict | None") -> "DistributedConfig":
        from horaedb_tpu.common.error import ensure

        if d is None:
            return cls()
        known = set(cls.__dataclass_fields__)
        unknown = set(d) - known
        ensure(not unknown,
               f"unknown config keys for DistributedConfig: {sorted(unknown)}")
        kwargs = dict(d)
        if "fragment_timeout" in kwargs:
            kwargs["fragment_timeout"] = ReadableDuration.parse(
                kwargs["fragment_timeout"]
            )
        cfg = cls(**kwargs)
        ensure(cfg.min_regions >= 1,
               f"distributed.min_regions must be >= 1, got {cfg.min_regions}")
        ensure(cfg.max_fanout >= 0,
               f"distributed.max_fanout must be >= 0, got {cfg.max_fanout}")
        return cfg


@dataclass
class ClusterConfig:
    """`[metric_engine.cluster]` knobs (docs/operations.md "Scale-out").

    `enabled = false` (the default) keeps the single-process behavior
    byte-identical. With it on, `role` picks the process's job:

    - "writer": owns region epoch fences per the assignment map, accepts
      writes, serves reads (optionally offloading them to replicas).
    - "replica": opens the engine read-only, tails manifests with the
      conditional-GET watch loop, serves reads with bounded staleness,
      forwards writes to the owning writer.
    """

    enabled: bool = False
    role: str = "writer"
    # watch-loop probe spacing on replicas (each probe is one conditional
    # GET + a few LISTs per table; unchanged probes cost no transfer)
    watch_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(2)
    )
    # watch-loop backoff cap under a faulted store
    watch_backoff_cap: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(30)
    )
    # advisory bound: /api/v1/cluster/status reports stale=true past it
    max_staleness: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(30)
    )
    # peer status-probe spacing (the router's health view)
    probe_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(5)
    )
    # writers offload reads to healthy replicas (rendezvous-routed) when
    # any are known; off = every node serves its own reads
    route_reads: bool = True
    # this process's advertised URL (what peers' routers forward to)
    self_url: str = ""
    # peer processes sharing the bucket
    peers: "list[ClusterPeer]" = field(default_factory=list)
    # scatter-gather split-read knobs ([metric_engine.cluster.distributed])
    distributed: DistributedConfig = field(default_factory=DistributedConfig)

    @classmethod
    def from_dict(cls, d: dict | None) -> "ClusterConfig":
        from horaedb_tpu.common.error import ensure

        if d is None:
            return cls()
        known = set(cls.__dataclass_fields__)
        unknown = set(d) - known
        ensure(not unknown,
               f"unknown config keys for ClusterConfig: {sorted(unknown)}")
        kwargs = dict(d)
        for k in ("watch_interval", "watch_backoff_cap", "max_staleness",
                  "probe_interval"):
            if k in kwargs:
                kwargs[k] = ReadableDuration.parse(kwargs[k])
        if "peers" in kwargs:
            kwargs["peers"] = [
                p if isinstance(p, ClusterPeer) else ClusterPeer.from_dict(p)
                for p in kwargs["peers"]
            ]
        if "distributed" in kwargs and not isinstance(
            kwargs["distributed"], DistributedConfig
        ):
            kwargs["distributed"] = DistributedConfig.from_dict(
                kwargs["distributed"]
            )
        cfg = cls(**kwargs)
        ensure(cfg.role in ("writer", "replica"),
               f"cluster.role must be writer|replica, got {cfg.role!r}")
        return cfg

    def writer_nodes(self) -> "list[str]":
        return [p.node for p in self.peers if p.role == "writer"]

    def peer_by_node(self, node: str) -> "ClusterPeer | None":
        for p in self.peers:
            if p.node == node:
                return p
        return None
