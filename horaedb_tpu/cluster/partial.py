"""Partial-grid wire schema + the canonical distributed merge.

The scatter-gather read path (cluster/router.py plans it, server/main.py
drives it) ships PER-REGION partial aggregates between nodes: each
computing node runs its region shards through the normal engine scan
path and answers with (sum, count, min, max, mean) grids per
(series, bucket) plus provenance — bucket-scale bytes, never rows (the
Taurus near-data-processing shape, arXiv:2506.20010).

Everything fragment-shaped lives HERE (jaxlint J023): the binary
encode/decode pair and the ONE merge fold. Bit-exactness of the
distributed result rests on two invariants this module owns:

- **Wire fidelity.** Grid arrays cross the wire as raw little-endian
  buffers with their dtype preserved — a JSON float round-trip would
  lose NaN payloads and -0.0 signs and break the u64-view equality the
  property tests assert. The single-partial shortcut in `merge_grids`
  returns the decoded part AS-IS, so the wire must carry every grid key
  the engine produced (mean included) at full fidelity.
- **Fixed fold order.** `merge_partials` sorts fragments into the
  coordinator's canonical region order (RegionedEngine iterates
  `list(self.engines)` — the range router's ids, sorted by range start)
  and folds LEFT exactly like the single-node merge: float addition is
  not associative, so ((a+c)+b) != ((a+b)+c) in the last ulp. Same
  region order + same elementwise ops = bit-identical grids.

`merge_grids` is the single implementation of the fold;
engine/region.py's `_merge_grids` delegates here.
"""

from __future__ import annotations

import json
import struct

import numpy as np

from horaedb_tpu.common import memtrace

MAGIC = b"HDPG1\n"
WIRE_CONTENT_TYPE = "application/x-horaedb-partial-grids"
# grid keys in canonical wire order (extra keys append after, sorted)
_KNOWN_KEYS = ("sum", "count", "min", "max", "mean")


def _key_order(grids: dict) -> "list[str]":
    known = [k for k in _KNOWN_KEYS if k in grids]
    extra = sorted(set(grids) - set(_KNOWN_KEYS))
    return known + extra


def encode_partials(
    node: str,
    parts: "list[tuple[int, list, dict]]",
    provenance: "dict | None" = None,
) -> bytes:
    """Serialize per-region partial grids to wire bytes.

    `parts` is [(region_id, tsids, grids)] straight from
    `query_partial_grids`. Layout: MAGIC, u32 header length, JSON header
    (node + provenance + per-region array directory), then the raw
    array payload — tsids as little-endian u64, each grid as its own
    dtype's little-endian bytes. The header carries offsets into the
    payload so decode is zero-copy-shaped (one frombuffer per array).
    """
    blobs: list[bytes] = []
    offset = 0

    def _append(buf: bytes) -> int:
        nonlocal offset
        blobs.append(buf)
        # each tobytes() serialization is a real copy onto the wire
        memtrace.track_bytes(len(buf), "wire_codec", "copy")
        start = offset
        offset += len(buf)
        return start

    regions = []
    for region_id, tsids, grids in parts:
        t = memtrace.tracked_contiguous(
            np.asarray(list(tsids), dtype=np.uint64), "wire_codec"
        )
        if t.dtype.byteorder == ">":  # pragma: no cover — BE hosts
            t = t.byteswap().view(t.dtype.newbyteorder("<"))
        entry = {
            "region_id": int(region_id),
            "n_series": int(t.shape[0]),
            "tsids": {"offset": _append(t.tobytes()), "nbytes": t.nbytes},
            "grids": {},
        }
        n_buckets = None
        for key in _key_order(grids):
            g = memtrace.tracked_contiguous(
                np.asarray(grids[key]), "wire_codec"
            )
            if g.dtype.byteorder == ">":  # pragma: no cover — BE hosts
                g = g.byteswap().view(g.dtype.newbyteorder("<"))
            n_buckets = int(g.shape[1]) if g.ndim == 2 else 0
            entry["grids"][key] = {
                "offset": _append(g.tobytes()),
                "nbytes": g.nbytes,
                "dtype": g.dtype.str,
            }
        entry["n_buckets"] = n_buckets
        regions.append(entry)

    header = {
        "node": str(node),
        "provenance": dict(provenance or {}),
        "regions": regions,
    }
    hdr = json.dumps(header, separators=(",", ":")).encode()
    return b"".join([MAGIC, struct.pack("<I", len(hdr)), hdr, *blobs])


def decode_partials(buf: bytes) -> "tuple[dict, list[tuple[int, list, dict]]]":
    """Inverse of `encode_partials`: (header dict, parts). Grid arrays
    come back with their exact wire dtype and bytes (u64-view equality
    holds across a round trip); tsids come back as python ints, matching
    the engine-local (tsids, grids) shape the merge fold consumes."""
    if buf[: len(MAGIC)] != MAGIC:
        raise ValueError("not a partial-grid payload (bad magic)")
    pos = len(MAGIC)
    (hdr_len,) = struct.unpack_from("<I", buf, pos)
    pos += 4
    header = json.loads(buf[pos: pos + hdr_len])
    payload = memoryview(buf)[pos + hdr_len:]
    parts = []
    for entry in header.get("regions", ()):
        toff = entry["tsids"]["offset"]
        tsids = np.frombuffer(
            payload[toff: toff + entry["tsids"]["nbytes"]], dtype="<u8"
        ).tolist()
        memtrace.track_bytes(entry["tsids"]["nbytes"], "wire_codec", "view")
        n = entry["n_series"]
        grids = {}
        for key, spec in entry["grids"].items():
            g = np.frombuffer(
                payload[spec["offset"]: spec["offset"] + spec["nbytes"]],
                dtype=np.dtype(spec["dtype"]),
            )
            # frombuffer aliases the wire payload — decode is view-shaped
            memtrace.track_bytes(spec["nbytes"], "wire_codec", "view")
            nb = entry.get("n_buckets") or 0
            grids[key] = g.reshape(n, nb) if n * nb == g.size else g
        parts.append((int(entry["region_id"]), tsids, grids))
    return header, parts


def merge_grids(results: list, device_mesh=None):
    """THE distributed/regioned grid fold: union the series axis, add
    sums/counts, min/max elementwise, recompute mean — the same
    associative fold the per-segment pushdown uses (data.py::one_segment),
    applied left-to-right in the caller-supplied order. A single partial
    returns AS-IS (dtype and mean untouched — the engine's own output is
    the canonical answer for one region).

    `device_mesh` routes the elementwise fold through
    parallel/merge.py's cross-chip grid fold when the grids are f64 —
    the per-cell left fold is order-identical, so the device path is
    bitwise-equal to the host path (tests/test_cluster_distributed.py
    asserts it)."""
    if len(results) == 1:
        # by-reference shortcut: the lone region's own grids ARE the
        # answer — file a reuse, not a copy, for the hand-back
        _tsids, only = results[0]
        memtrace.track_bytes(
            sum(int(np.asarray(g).nbytes) for g in only.values()),
            "wire_codec", "reuse",
        )
        return results[0]
    all_tsids = sorted({t for tsids, _ in results for t in tsids})
    pos = {t: i for i, t in enumerate(all_tsids)}
    n_buckets = next(iter(results[0][1].values())).shape[1]
    shape = (len(all_tsids), n_buckets)
    use_device = device_mesh is not None and all(
        np.asarray(part[k]).dtype == np.float64
        for _, part in results for k in ("sum", "count", "min", "max")
    )
    if use_device:
        # bitwise precondition: a platform whose runtime flushes f64
        # subnormals (XLA:CPU sets FTZ/DAZ on its threads) would launder
        # denormal cells the host fold keeps — probe once, fall back
        from horaedb_tpu.parallel.merge import device_fold_safe

        use_device = device_fold_safe(device_mesh)
    if use_device:
        # align each partial into a stacked [k, S, B] lane (identity
        # rows where a partial lacks the series), then fold on-device
        stacked = {
            "sum": np.zeros((len(results),) + shape),
            "count": np.zeros((len(results),) + shape),
            "min": np.full((len(results),) + shape, np.inf),
            "max": np.full((len(results),) + shape, -np.inf),
        }
        for j, (tsids, part) in enumerate(results):
            idx = np.asarray([pos[t] for t in tsids], dtype=np.int64)
            for k in ("sum", "count", "min", "max"):
                stacked[k][j, idx] = np.asarray(part[k])
        from horaedb_tpu.parallel.merge import sharded_grid_fold

        grids = sharded_grid_fold(device_mesh, stacked)
    else:
        grids = {
            "sum": np.zeros(shape),
            "count": np.zeros(shape),
            "min": np.full(shape, np.inf),
            "max": np.full(shape, -np.inf),
        }
        for tsids, part in results:
            idx = np.asarray([pos[t] for t in tsids], dtype=np.int64)
            np.add.at(grids["sum"], idx, np.asarray(part["sum"]))
            np.add.at(grids["count"], idx, np.asarray(part["count"]))
            np.minimum.at(grids["min"], idx, np.asarray(part["min"]))
            np.maximum.at(grids["max"], idx, np.asarray(part["max"]))
    with np.errstate(invalid="ignore", divide="ignore"):
        grids["mean"] = grids["sum"] / grids["count"]
    return all_tsids, grids


def merge_partials(
    parts: "list[tuple[int, list, dict]]",
    order: "list[int] | None" = None,
    device_mesh=None,
):
    """Coordinator entry: fold fragments gathered from any number of
    nodes in the CANONICAL region order. `order` is the coordinator's
    region-id iteration order (`list(engine.engines)`); fragments for
    unknown regions sort after, by id — deterministic regardless of
    which node answered which shard or in what order fragments arrived.
    Returns (tsids, grids) or None when no region produced rows."""
    if not parts:
        return None
    if order is not None:
        rank = {int(r): i for i, r in enumerate(order)}
        parts = sorted(
            parts, key=lambda p: (rank.get(int(p[0]), len(rank)), int(p[0]))
        )
    else:
        parts = sorted(parts, key=lambda p: int(p[0]))
    return merge_grids([(tsids, grids) for _, tsids, grids in parts],
                       device_mesh=device_mesh)
