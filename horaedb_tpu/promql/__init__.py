"""PromQL subset: tokenizer, AST, and parser.

The reference ships no query language (its server is a demo HTTP surface,
src/server/src/main.rs:59-80); its RFC names VictoriaMetrics as the model
(docs/rfcs/20240827-metric-engine.md:80-84), whose whole point is serving
PromQL over exactly this storage shape. This module closes that loop: a
compact, honest subset of PromQL evaluated against the metric engine, with
the `*_over_time` family and aggregations riding the device downsample
pushdown (the TPU path) and counter functions riding the raw scan.

Supported grammar (see promql/eval.py for semantics and divergences):

    expr      := and_expr ("or" and_expr)*
    and_expr  := cmp (("and"|"unless") cmp)*
    cmp       := arith ((">"|">="|"<"|"<="|"=="|"!=") arith)*
    arith     := term (("+"|"-") term)*
    term      := unary (("*"|"/") unary)*
    unary     := "-"? primary
    primary   := NUMBER
               | FUNC "(" expr ")"
               | AGG ("by"|"without") "(" labels ")" "(" expr ")"
               | AGG "(" expr ")" [("by"|"without") "(" labels ")"]
               | ("topk"|"bottomk") "(" INT "," expr ")"
               | "(" expr ")"
               | selector
    selector  := NAME ["{" matcher ("," matcher)* "}"] ["[" DURATION "]"]
                 ["offset" DURATION]
    matcher   := NAME ("=" | "!=" | "=~" | "!~") STRING

FUNC:   rate increase delta avg_over_time sum_over_time min_over_time
        max_over_time count_over_time last_over_time
MATHFN: abs ceil floor round sqrt ln log2 log10 exp   — MATHFN "(" expr ")"
        clamp_min clamp_max "(" expr "," ["-"] NUMBER ")"
        histogram_quantile "(" NUMBER "," expr ")"  — expr yields `le` buckets
        label_replace "(" expr "," STRING x4 ")"  — dst, replacement, src, regex
AGG:    sum avg min max count
A NAME from any function set followed by anything but "(" parses as a
metric selector (a metric named `rate` stays queryable).
DURATION: integer + unit in {ms, s, m, h, d, w}

Binary arithmetic: scalar-vector elementwise, or vector-vector with
EXACT label-set matching (ignoring __name__; one-to-one only — group_left
/group_right many-to-one matching is out of the subset and rejected
loudly). Comparisons are Prometheus filter semantics (failing steps drop;
the `bool` modifier is out of the subset), and the set operators
and/or/unless match per step on the __name__-stripped label set — the
shapes SLO burn-rate rules need (`err/total` ratios, `short > x and
long > x`). `and`/`or`/`unless` are reserved words in operator position
only; a metric so named stays queryable standalone.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from horaedb_tpu.common.error import HoraeError


class PromQLError(HoraeError):
    """Parse or evaluation error (surfaces as Prometheus bad_data)."""


FUNCS = frozenset({
    "rate", "increase", "delta", "avg_over_time", "sum_over_time",
    "min_over_time", "max_over_time", "count_over_time", "last_over_time",
})
AGGS = frozenset({"sum", "avg", "min", "max", "count"})
TOPK_AGGS = frozenset({"topk", "bottomk"})
# elementwise math over a vector (or scalar); clamp_* take (expr, scalar)
MATH_FUNCS = frozenset({"abs", "ceil", "floor", "round", "sqrt", "ln", "log2",
                        "log10", "exp"})
CLAMP_FUNCS = frozenset({"clamp_min", "clamp_max"})

_DURATION_UNITS = {
    "ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
    "d": 86_400_000, "w": 7 * 86_400_000,
}

# matcher op -> QueryRequest matcher op (engine/engine.py:78-80); "=" maps
# to the cheaper equality filter lane instead
_MATCH_OPS = {"!=": "ne", "=~": "re", "!~": "nre"}


# -- AST --------------------------------------------------------------------


@dataclass(frozen=True)
class Selector:
    name: str
    # (key, op, value) with op in {"=", "!=", "=~", "!~"}
    matchers: tuple = ()
    range_ms: int | None = None  # [5m] -> 300000; None = instant vector
    offset_ms: int = 0           # `offset 5m` shifts the data window back


@dataclass(frozen=True)
class Func:
    fn: str
    arg: Selector  # subset: over-time/counter functions take a selector


@dataclass(frozen=True)
class Agg:
    op: str
    expr: object
    by: tuple | None = None       # by(...) projection
    without: tuple | None = None  # without(...) exclusion


@dataclass(frozen=True)
class TopK:
    op: str      # topk | bottomk
    k: int
    expr: object


@dataclass(frozen=True)
class MathFn:
    fn: str           # abs/ceil/floor/round/sqrt/ln/log2/log10/exp/clamp_*
    expr: object
    arg: float | None = None  # clamp bound


@dataclass(frozen=True)
class HistogramQuantile:
    q: float
    expr: object  # must evaluate to a vector of `le`-labelled buckets


@dataclass(frozen=True)
class LabelReplace:
    expr: object
    dst: str
    replacement: str  # RE2-style $1 / ${name} group references
    src: str
    regex: str


@dataclass(frozen=True)
class Scalar:
    value: float


@dataclass(frozen=True)
class BinOp:
    op: str  # + - * /
    left: object
    right: object


@dataclass(frozen=True)
class Cmp:
    """Filter comparison (Prometheus semantics: steps failing the
    predicate drop out; the value kept is the LEFT operand's)."""

    op: str  # > >= < <= == !=
    left: object
    right: object


@dataclass(frozen=True)
class SetOp:
    """Vector set operator matching per step on the __name__-stripped
    label set: and (intersect), or (union, left wins), unless (minus)."""

    op: str  # and | or | unless
    left: object
    right: object


CMP_OPS = frozenset({">", ">=", "<", "<=", "==", "!="})
SET_OPS = frozenset({"and", "or", "unless"})


# -- tokenizer --------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<WS>\s+)
  | (?P<NUMBER>\d+\.\d*|\.\d+|\d+)
  | (?P<NAME>[a-zA-Z_:][a-zA-Z0-9_:]*)
  | (?P<STRING>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<OP>=~|!~|!=|==|>=|<=|=|>|<|\+|-|\*|/|\(|\)|\{|\}|\[|\]|,)
    """,
    re.VERBOSE,
)


@dataclass
class _Tok:
    kind: str
    text: str
    pos: int


def _tokenize(src: str) -> list[_Tok]:
    out, i = [], 0
    while i < len(src):
        m = _TOKEN_RE.match(src, i)
        if m is None:
            raise PromQLError(f"unexpected character {src[i]!r} at {i}")
        i = m.end()
        kind = m.lastgroup
        if kind != "WS":
            out.append(_Tok(kind, m.group(), m.start()))
    out.append(_Tok("EOF", "", len(src)))
    return out


def _unquote(s: str) -> str:
    """Resolve PromQL string escapes. Hand-rolled: `unicode_escape` would
    round-trip through latin-1 and mangle non-ASCII label values."""
    body = s[1:-1]
    if "\\" not in body:
        return body
    out, i = [], 0
    simple = {"n": "\n", "t": "\t", "r": "\r", "\\": "\\", '"': '"', "'": "'"}
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            n = body[i + 1]
            if n in simple:
                out.append(simple[n])
                i += 2
                continue
            if n == "u" and i + 6 <= len(body):
                try:
                    out.append(chr(int(body[i + 2 : i + 6], 16)))
                    i += 6
                    continue
                except ValueError:
                    pass
            if n == "x" and i + 4 <= len(body):
                try:
                    out.append(chr(int(body[i + 2 : i + 4], 16)))
                    i += 4
                    continue
                except ValueError:
                    pass
            out.append(n)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


# -- parser -----------------------------------------------------------------


@dataclass
class _Parser:
    toks: list[_Tok]
    i: int = field(default=0)

    def peek(self) -> _Tok:
        return self.toks[self.i]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str) -> _Tok:
        t = self.next()
        if t.text != text:
            raise PromQLError(f"expected {text!r} at {t.pos}, got {t.text!r}")
        return t

    # expr := and_expr ("or" and_expr)*  — Prometheus precedence: `or`
    # binds loosest, then and/unless, then comparisons, then +-, then */
    def expr(self):
        node = self.and_expr()
        while self.peek().kind == "NAME" and self.peek().text == "or":
            self.next()
            node = SetOp("or", node, self.and_expr())
        return node

    def and_expr(self):
        node = self.cmp()
        while (self.peek().kind == "NAME"
               and self.peek().text in ("and", "unless")):
            op = self.next().text
            node = SetOp(op, node, self.cmp())
        return node

    def cmp(self):
        node = self.arith()
        while self.peek().kind == "OP" and self.peek().text in CMP_OPS:
            op = self.next().text
            node = Cmp(op, node, self.arith())
        return node

    def arith(self):
        node = self.term()
        while self.peek().text in ("+", "-"):
            op = self.next().text
            node = BinOp(op, node, self.term())
        return node

    def term(self):
        node = self.unary()
        while self.peek().text in ("*", "/"):
            op = self.next().text
            node = BinOp(op, node, self.unary())
        return node

    def unary(self):
        if self.peek().text == "-":
            self.next()
            return BinOp("-", Scalar(0.0), self.primary())
        return self.primary()

    def _called(self) -> bool:
        """True when the NAME at the cursor is followed by '(' — the
        function-vs-metric disambiguation Prometheus itself uses (a metric
        literally named `rate` or `abs` stays queryable)."""
        return self.toks[self.i + 1].text == "("

    def primary(self):
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            return Scalar(float(t.text))
        if t.text == "(":
            self.next()
            node = self.expr()
            self.expect(")")
            return node
        if t.kind == "NAME":
            name = t.text
            if name in FUNCS and self._called():
                self.next()
                self.expect("(")
                arg = self.expr()
                self.expect(")")
                if not isinstance(arg, Selector):
                    raise PromQLError(f"{name}() takes a range-vector selector")
                if arg.range_ms is None:
                    raise PromQLError(
                        f"{name}() needs a range selector, e.g. m[5m]"
                    )
                return Func(name, arg)
            if name in AGGS and (
                self._called() or self.toks[self.i + 1].text in ("by", "without")
            ):
                return self._aggregate(name)
            if name in TOPK_AGGS and self._called():
                self.next()
                self.expect("(")
                k_tok = self.next()
                if k_tok.kind != "NUMBER" or float(k_tok.text) != int(float(k_tok.text)):
                    raise PromQLError(f"{name}() needs an integer k at {k_tok.pos}")
                self.expect(",")
                inner = self.expr()
                self.expect(")")
                return TopK(name, int(float(k_tok.text)), inner)
            if name == "histogram_quantile" and self._called():
                self.next()
                self.expect("(")
                neg = self.peek().text == "-"
                if neg:
                    self.next()
                q_tok = self.next()
                if q_tok.kind != "NUMBER":
                    raise PromQLError(
                        f"histogram_quantile needs a numeric q at {q_tok.pos}"
                    )
                self.expect(",")
                inner = self.expr()
                self.expect(")")
                return HistogramQuantile(
                    float(q_tok.text) * (-1.0 if neg else 1.0), inner
                )
            if name == "label_replace" and self._called():
                self.next()
                self.expect("(")
                inner = self.expr()
                strs = []
                for _ in range(4):
                    self.expect(",")
                    t2 = self.next()
                    if t2.kind != "STRING":
                        raise PromQLError(
                            f"label_replace needs string args at {t2.pos}"
                        )
                    strs.append(_unquote(t2.text))
                self.expect(")")
                return LabelReplace(inner, strs[0], strs[1], strs[2], strs[3])
            if name in MATH_FUNCS and self._called():
                self.next()
                self.expect("(")
                inner = self.expr()
                self.expect(")")
                return MathFn(name, inner)
            if name in CLAMP_FUNCS and self._called():
                self.next()
                self.expect("(")
                inner = self.expr()
                self.expect(",")
                bound = self.next()
                neg = False
                if bound.text == "-":
                    neg = True
                    bound = self.next()
                if bound.kind != "NUMBER":
                    raise PromQLError(f"{name}() needs a numeric bound at {bound.pos}")
                self.expect(")")
                b = float(bound.text) * (-1.0 if neg else 1.0)
                return MathFn(name, inner, b)
            return self._selector()
        raise PromQLError(f"unexpected token {t.text!r} at {t.pos}")

    def _aggregate(self, op: str):
        self.next()  # the AGG name
        by = without = None
        if self.peek().text in ("by", "without"):
            mode = self.next().text
            labels = self._label_list()
            if mode == "by":
                by = labels
            else:
                without = labels
        self.expect("(")
        inner = self.expr()
        self.expect(")")
        if by is None and without is None and self.peek().text in ("by", "without"):
            mode = self.next().text
            labels = self._label_list()
            if mode == "by":
                by = labels
            else:
                without = labels
        return Agg(op, inner, by=by, without=without)

    def _label_list(self) -> tuple:
        self.expect("(")
        out = []
        while self.peek().text != ")":
            t = self.next()
            if t.kind != "NAME":
                raise PromQLError(f"expected label name at {t.pos}")
            out.append(t.text)
            if self.peek().text == ",":
                self.next()
        self.expect(")")
        return tuple(out)

    def _selector(self):
        name = self.next().text
        matchers = []
        if self.peek().text == "{":
            self.next()
            while self.peek().text != "}":
                key = self.next()
                if key.kind != "NAME":
                    raise PromQLError(f"expected label name at {key.pos}")
                op = self.next().text
                if op not in ("=", "!=", "=~", "!~"):
                    raise PromQLError(f"bad matcher op {op!r}")
                val = self.next()
                if val.kind != "STRING":
                    raise PromQLError(f"expected quoted value at {val.pos}")
                matchers.append((key.text, op, _unquote(val.text)))
                if self.peek().text == ",":
                    self.next()
            self.expect("}")
        range_ms = None
        if self.peek().text == "[":
            self.next()
            range_ms = self._duration()
            self.expect("]")
        offset_ms = 0
        if self.peek().text == "offset":
            self.next()
            offset_ms = self._duration()
        return Selector(name, tuple(matchers), range_ms, offset_ms)

    def _duration(self) -> int:
        num = self.next()
        if num.kind != "NUMBER":
            raise PromQLError(f"expected duration at {num.pos}")
        unit = self.next()
        if unit.text not in _DURATION_UNITS:
            raise PromQLError(f"bad duration unit {unit.text!r}")
        return int(float(num.text) * _DURATION_UNITS[unit.text])


def parse(src: str):
    """Parse one PromQL expression; raises PromQLError on any syntax the
    subset does not cover."""
    p = _Parser(_tokenize(src))
    node = p.expr()
    if p.peek().kind != "EOF":
        t = p.peek()
        raise PromQLError(f"trailing input at {t.pos}: {t.text!r}")
    return node


def parse_duration_ms(s: str) -> int:
    """'5m' / '30s' / '250ms' -> milliseconds (for the `step` params)."""
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h|d|w)", s)
    if m is None:
        # Prometheus also accepts bare seconds
        try:
            return int(float(s) * 1000)
        except ValueError:
            raise PromQLError(f"bad duration {s!r}") from None
    return int(float(m.group(1)) * _DURATION_UNITS[m.group(2)])
