"""PromQL subset evaluation over the metric engine.

Execution strategy (the point of doing this in a TPU framework):

- `sum_over_time` / `count_over_time` / `avg_over_time` / `min_over_time`
  / `max_over_time` with window == step ride the engine's aggregate
  PUSHDOWN (engine/data.py::query_downsample): every per-(series, bucket)
  reduction runs inside the device scan — raw rows never reach the host.
- Counter functions (`rate`, `increase`, `delta`), `last_over_time`,
  instant selectors, and windows != step need per-window first/last
  semantics the grid does not carry; they evaluate from the raw scan with
  vectorized per-series window reductions on host.
- Aggregations (`sum by (...)`) group the per-series step vectors; scalar
  arithmetic is elementwise.

Documented divergences from Prometheus (semantics kept simple and stated
rather than silently approximated):

1. Windows are right-aligned HALF-OPEN buckets [t-step, t) evaluated at
   each step timestamp, not Prometheus's (t-window, t] — boundary samples
   land one bucket later.
2. `rate`/`increase` use (last - first + counter-reset corrections) over
   the window WITHOUT Prometheus's edge extrapolation — values are exact
   over observed samples, slightly lower than Prometheus near window
   edges.
3. Instant vector lookback is 5 minutes (Prometheus default), applied at
   each step of a range query.
4. Vector-vector binary arithmetic (label matching) is not in the subset.
5. histogram_quantile: a step whose +Inf bucket is absent yields NO value
   (as in Prometheus), but an absent FINITE bucket is treated as empty at
   the previous cumulative count instead of being dropped from the vector
   — the winning bucket matches Prometheus, while the interpolation lower
   bound may be the absent bucket's le rather than the next-lower present
   one.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from horaedb_tpu.common import tracing
from horaedb_tpu.engine.engine import QueryRequest
from horaedb_tpu.storage import scanstats
from horaedb_tpu.promql import (
    Agg,
    BinOp,
    Cmp,
    Func,
    HistogramQuantile,
    LabelReplace,
    MathFn,
    PromQLError,
    Scalar,
    Selector,
    SetOp,
    TopK,
    _MATCH_OPS,
)

_MATH = {
    "abs": np.abs, "ceil": np.ceil, "floor": np.floor,
    # Prometheus round() resolves .5 ties UP (floor(v+0.5)); np.round's
    # banker's rounding would diverge on every half-integer
    "round": lambda v: np.floor(v + 0.5),
    "sqrt": np.sqrt, "ln": np.log, "log2": np.log2,
    "log10": np.log10, "exp": np.exp,
}

LOOKBACK_MS = 300_000  # Prometheus default instant-vector staleness window

# grid stat backing each aligned *_over_time function
_GRID_STAT = {
    "sum_over_time": "sum",
    "count_over_time": "count",
    "avg_over_time": "mean",
    "min_over_time": "min",
    "max_over_time": "max",
}


@dataclass
class SeriesVector:
    """One output series: its labels and one value per step (NaN = absent)."""

    labels: dict[str, str]
    values: np.ndarray


def _to_query(sel: Selector, start_ms: int, end_ms: int,
              bucket_ms: int | None = None) -> QueryRequest:
    filters, matchers = [], []
    for key, op, val in sel.matchers:
        if op == "=":
            filters.append((key.encode(), val.encode()))
        else:
            matchers.append((key.encode(), _MATCH_OPS[op], val.encode()))
    return QueryRequest(
        metric=sel.name.encode(), start_ms=start_ms, end_ms=end_ms,
        filters=filters, matchers=matchers, bucket_ms=bucket_ms,
    )


class RangeEvaluator:
    """Evaluate one parsed expression over [start, end] at `step` spacing.

    Steps are `start + k*step` for k in 0..floor((end-start)/step)
    (Prometheus range-query grid)."""

    def __init__(self, engine, start_ms: int, end_ms: int, step_ms: int,
                 max_series: int = 10_000):
        if step_ms <= 0:
            raise PromQLError("step must be > 0")
        if end_ms < start_ms:
            raise PromQLError("end must be >= start")
        n_steps = (end_ms - start_ms) // step_ms + 1
        if n_steps > 11_000:
            raise PromQLError(
                f"{n_steps} steps exceeds the resolution limit (11000); "
                "increase step"
            )
        self._engine = engine
        self.start = start_ms
        self.step = step_ms
        self.steps = start_ms + step_ms * np.arange(n_steps, dtype=np.int64)
        self._max_series = max_series

    # -- public -------------------------------------------------------------

    async def eval(self, node) -> "list[SeriesVector] | float":
        if isinstance(node, Scalar):
            return node.value
        if isinstance(node, BinOp):
            return await self._binop(node)
        if isinstance(node, Cmp):
            return await self._cmp(node)
        if isinstance(node, SetOp):
            return await self._setop(node)
        if isinstance(node, Selector):
            if node.range_ms is not None:
                raise PromQLError(
                    "a range selector needs a function (rate, *_over_time)"
                )
            return await self._instant(node)
        if isinstance(node, Func):
            return await self._func(node)
        if isinstance(node, Agg):
            return await self._agg(node)
        if isinstance(node, TopK):
            return await self._topk(node)
        if isinstance(node, MathFn):
            return await self._math(node)
        if isinstance(node, HistogramQuantile):
            return await self._histogram_quantile(node)
        if isinstance(node, LabelReplace):
            return await self._label_replace(node)
        raise PromQLError(f"unsupported node {type(node).__name__}")

    async def _label_replace(self, node: LabelReplace):
        """Prometheus label_replace(v, dst, replacement, src, regex): when
        regex FULL-matches src's value, dst is set to replacement with
        RE2-style $N/${name} group references expanded; an empty result
        drops dst; non-matching series pass through unchanged. The engine's
        catastrophic-backtracking guard applies (the regex is user input
        evaluated on the event loop)."""
        import re as _re

        from horaedb_tpu.engine.index import _reject_catastrophic

        inner = await self.eval(node.expr)
        if isinstance(inner, float):
            raise PromQLError("label_replace needs a vector operand")
        if not _re.fullmatch(r"[a-zA-Z_][a-zA-Z0-9_]*", node.dst):
            raise PromQLError(f"invalid destination label {node.dst!r}")
        try:
            _reject_catastrophic(node.regex)
        except Exception as e:  # noqa: BLE001 — HoraeError -> bad_data
            raise PromQLError(str(e)) from None
        try:
            pat = _re.compile(node.regex)
        except _re.error as e:
            raise PromQLError(f"bad regex {node.regex!r}: {e}") from None
        # RE2 replacement syntax -> Python expand template:
        # $$ -> $, ${name} -> \g<name>, $1 -> \g<1>
        def _tr(m):
            g = m.group(1)
            if g == "$":
                return "$"
            if g.startswith("{"):
                return rf"\g<{g[1:-1]}>"
            return rf"\g<{g}>"

        template = _re.sub(r"\$(\$|\{\w+\}|\d+)", _tr, node.replacement)
        out = []
        for sv in inner:
            m = pat.fullmatch(sv.labels.get(node.src, ""))
            if m is None:
                out.append(sv)
                continue
            try:
                val = m.expand(template)
            except (_re.error, IndexError) as e:
                raise PromQLError(
                    f"bad replacement {node.replacement!r}: {e}"
                ) from None
            labels = dict(sv.labels)
            if val == "":
                labels.pop(node.dst, None)
            else:
                labels[node.dst] = val
            out.append(SeriesVector(labels, sv.values))
        return out

    async def _histogram_quantile(self, node: HistogramQuantile):
        """Prometheus histogram_quantile over classic `le` buckets: group
        the inner vector by labels-minus-le, enforce monotone cumulative
        counts, and linearly interpolate within the winning bucket
        (promql/quantile.go semantics; the +Inf bucket carries the total).
        Vectorized over steps per group."""
        inner = await self.eval(node.expr)
        if isinstance(inner, float):
            raise PromQLError("histogram_quantile needs a vector of buckets")
        q = node.q
        groups: dict[tuple, list[tuple[float, np.ndarray]]] = {}
        glabels: dict[tuple, dict] = {}
        for sv in inner:
            le_s = sv.labels.get("le")
            if le_s is None:
                continue  # Prometheus ignores bucket-less series
            try:
                le = float("inf") if le_s in ("+Inf", "Inf", "inf") else float(le_s)
            except ValueError:
                continue
            rest = {k: v for k, v in sv.labels.items()
                    if k not in ("le", "__name__")}
            key = tuple(sorted(rest.items()))
            groups.setdefault(key, []).append((le, sv.values))
            glabels[key] = rest
        out = []
        for key, buckets in sorted(groups.items()):
            buckets.sort(key=lambda b: b[0])
            les = np.array([b[0] for b in buckets])
            if not np.isinf(les[-1]) or len(buckets) < 2:
                continue  # no +Inf bucket -> undefined (Prometheus: NaN/skip)
            raw = np.stack([b[1] for b in buckets])  # [buckets, steps]
            # a step where the +Inf series is absent has NO total — emitting
            # one from the finite buckets would fabricate a quantile
            inf_absent = np.isnan(raw[-1])
            # absent FINITE buckets impute to the previous bucket's
            # cumulative count via the max-accumulate repair: they can then
            # never win the bucket search, though the interpolation lower
            # bound remains the absent bucket's le (documented divergence —
            # Prometheus drops the bucket from the instant vector entirely)
            cum = np.where(np.isnan(raw), 0.0, raw)
            cum = np.maximum.accumulate(cum, axis=0)  # also repairs jitter
            total = cum[-1]
            n_steps = cum.shape[1]
            vals = np.full(n_steps, np.nan)
            ok = (total > 0) & ~inf_absent
            if q < 0:
                vals[ok] = -np.inf
            elif q > 1:
                vals[ok] = np.inf
            else:
                rank = q * total  # target cumulative count per step
                # first bucket with cum >= rank (argmax of a bool stack)
                ge = cum >= rank[None, :]
                b_idx = np.argmax(ge, axis=0)
                lo_bound = np.where(b_idx > 0, les[np.maximum(b_idx - 1, 0)], 0.0)
                hi_bound = les[b_idx]
                cum_lo = np.where(
                    b_idx > 0,
                    cum[np.maximum(b_idx - 1, 0), np.arange(n_steps)],
                    0.0,
                )
                cum_hi = cum[b_idx, np.arange(n_steps)]
                # +Inf winning bucket: Prometheus returns its lower bound
                inf_win = np.isinf(hi_bound)
                with np.errstate(all="ignore"):
                    frac = np.where(
                        cum_hi > cum_lo, (rank - cum_lo) / (cum_hi - cum_lo), 1.0
                    )
                    interp = lo_bound + (hi_bound - lo_bound) * frac
                res = np.where(inf_win, lo_bound, interp)
                # quantile.go: a winning FIRST bucket with upperBound <= 0
                # returns the upper bound itself (interpolating from the
                # hardcoded 0 lower bound would exceed the data's range)
                if les[0] <= 0:
                    res = np.where(b_idx == 0, les[0], res)
                vals[ok] = res[ok]
            if not np.isnan(vals).all():
                out.append(SeriesVector(glabels[key], vals))
        return out

    async def _math(self, node: MathFn):
        inner = await self.eval(node.expr)

        def apply(v):
            with np.errstate(all="ignore"):
                if node.fn == "clamp_min":
                    return np.maximum(v, node.arg)
                if node.fn == "clamp_max":
                    return np.minimum(v, node.arg)
                return _MATH[node.fn](v)

        if isinstance(inner, float):
            return float(apply(np.float64(inner)))
        # function application drops __name__ (Prometheus semantics)
        return [
            SeriesVector(
                {k: v for k, v in sv.labels.items() if k != "__name__"},
                apply(sv.values),
            )
            for sv in inner
        ]

    # -- series plumbing ----------------------------------------------------

    # raw-path materialization cap: the native JSON API caps at 1M rows;
    # PromQL's raw functions get more headroom (rate over long windows) but
    # never unbounded — a panel query must not OOM the server
    MAX_RAW_ROWS = 5_000_000

    def _labels_of(self, sel: Selector, tsids, keep_name: bool):
        """tsid -> result labels, decoded only for the tsids actually in
        the result (a selective query must not decode a 100k-series
        metric). Public engine surface — works on RegionedEngine too."""
        by_tsid = self._engine.series_labels_map(sel.name.encode(), list(tsids))
        out = {}
        for tsid, labs in by_tsid.items():
            d = {k.decode(errors="replace"): v.decode(errors="replace")
                 for k, v in labs.items()}
            if keep_name:
                d["__name__"] = sel.name
            out[tsid] = d
        return out

    async def _raw_series(self, sel: Selector, pre_ms: int):
        """Raw samples per tsid over [start - pre, end], each sorted by ts:
        {tsid: (ts_array, value_array)}. `offset` shifts the DATA window
        back and the returned timestamps forward by the same amount, so
        every downstream window computation stays offset-oblivious."""
        o = sel.offset_ms
        req = _to_query(sel, self.start - pre_ms - o,
                        int(self.steps[-1]) + 1 - o)
        req.limit = self.MAX_RAW_ROWS + 1
        scanstats.note("promql_raw_selects")
        table = await self._engine.query(req)
        if table is None:
            return {}
        if table.num_rows > self.MAX_RAW_ROWS:
            raise PromQLError(
                f"query materializes more than {self.MAX_RAW_ROWS} raw "
                "samples; narrow the range/selector, or use an *_over_time "
                "function with window == step (served by pushdown)"
            )
        tsid = table.column("tsid").to_numpy(zero_copy_only=False).astype(np.uint64)
        ts = table.column("ts").to_numpy(zero_copy_only=False).astype(np.int64) + o
        val = table.column("value").to_numpy(zero_copy_only=False)
        order = np.lexsort((ts, tsid))
        tsid, ts, val = tsid[order], ts[order], val[order]
        out = {}
        bounds = np.flatnonzero(tsid[1:] != tsid[:-1]) + 1
        starts = np.concatenate([[0], bounds, [len(tsid)]])
        for i in range(len(starts) - 1):
            lo, hi = starts[i], starts[i + 1]
            if lo < hi:
                out[int(tsid[lo])] = (ts[lo:hi], val[lo:hi])
        if len(out) > self._max_series:
            raise PromQLError(
                f"query selects {len(out)} series (limit {self._max_series})"
            )
        return out

    # -- selector / function evaluation --------------------------------------

    async def _instant(self, sel: Selector) -> list[SeriesVector]:
        """Instant vector at each step: last sample within the lookback."""
        series = await self._raw_series(sel, LOOKBACK_MS)
        labels = self._labels_of(sel, series.keys(), keep_name=True)
        out = []
        for tsid, (ts, val) in series.items():
            idx = np.searchsorted(ts, self.steps, side="right") - 1
            vals = np.full(len(self.steps), np.nan)
            ok = idx >= 0
            cand = np.where(ok, idx, 0)
            fresh = ok & (self.steps - ts[cand] <= LOOKBACK_MS)
            vals[fresh] = val[cand[fresh]]
            if np.isnan(vals).all():
                continue
            out.append(SeriesVector(labels.get(tsid, {}), vals))
        return out

    async def _func(self, node: Func) -> list[SeriesVector]:
        sel = node.arg
        window = sel.range_ms
        if node.fn in _GRID_STAT and window == self.step:
            return await self._grid_over_time(node.fn, sel)
        series = await self._raw_series(sel, window)
        labels = self._labels_of(sel, series.keys(), keep_name=False)
        out = []
        for tsid, (ts, val) in series.items():
            vals = self._window_reduce(node.fn, ts, val, window)
            if np.isnan(vals).all():
                continue
            out.append(SeriesVector(labels.get(tsid, {}), vals))
        return out

    async def _grid_over_time(self, fn: str, sel: Selector) -> list[SeriesVector]:
        """window == step: ONE device-pushdown downsample serves every step
        — the TPU fast path (raw rows never reach the host).

        Buckets anchor one window BEFORE the first step, so bucket k covers
        [steps[k] - step, steps[k]) and step 0 gets a real value from
        pre-range samples — identical alignment to the raw-path
        `_window_reduce` (a step nudge across the ==window boundary must
        not add or drop points)."""
        o = sel.offset_ms
        t0 = self.start - self.step - o
        req = _to_query(sel, t0, int(self.steps[-1]) - o, bucket_ms=self.step)
        scanstats.note("promql_pushdowns")
        res = await self._engine.query(req)
        # span attribution: which aggregation kernel the calibrated
        # registry dispatcher served this pushdown with (visible on
        # /debug/traces next to the scan stage timings)
        from horaedb_tpu.ops import agg_registry

        tracing.add_attr(agg_impl=agg_registry.last_choice())
        if res is None:
            return []
        tsids, grids = res
        labels = self._labels_of(sel, [int(t) for t in tsids], keep_name=False)
        stat = _GRID_STAT[fn]
        grid = np.asarray(grids[stat], dtype=np.float64)
        count = np.asarray(grids["count"])
        out = []
        for i, tsid in enumerate(tsids):
            vals = np.full(len(self.steps), np.nan)
            n = min(grid.shape[1], len(self.steps))
            v = grid[i, :n].copy()
            v[count[i, :n] == 0] = np.nan
            vals[:n] = v
            if np.isnan(vals).all():
                continue
            out.append(SeriesVector(labels.get(int(tsid), {}), vals))
        return out

    def _window_reduce(self, fn: str, ts, val, window: int) -> np.ndarray:
        """Per-step reduction over [t-window, t) windows of one series."""
        lo = np.searchsorted(ts, self.steps - window, side="left")
        hi = np.searchsorted(ts, self.steps, side="left")
        n = len(self.steps)
        vals = np.full(n, np.nan)
        if fn in ("sum_over_time", "count_over_time", "avg_over_time"):
            csum = np.concatenate([[0.0], np.cumsum(val)])
            cnt = (hi - lo).astype(np.float64)
            s = csum[hi] - csum[lo]
            nz = cnt > 0
            if fn == "sum_over_time":
                vals[nz] = s[nz]
            elif fn == "count_over_time":
                vals[nz] = cnt[nz]
            else:
                vals[nz] = s[nz] / cnt[nz]
            return vals
        if fn == "last_over_time":
            nz = hi > lo
            vals[nz] = val[hi[nz] - 1]
            return vals
        if fn in ("min_over_time", "max_over_time"):
            # one vectorized reduceat over interleaved (lo, hi) bounds:
            # even slots hold each window's reduction (odd slots are the
            # inter-window gaps — discarded). A sentinel pad makes hi ==
            # len(val) a legal index; empty windows are masked by `nz`.
            red = np.minimum if fn == "min_over_time" else np.maximum
            pad = np.append(val, np.inf if fn == "min_over_time" else -np.inf)
            idx = np.empty(2 * n, dtype=np.int64)
            idx[0::2] = lo
            idx[1::2] = np.maximum(hi, lo)
            nz = hi > lo
            out = red.reduceat(pad, idx)[0::2]
            vals[nz] = out[nz]
            return vals
        if fn in ("rate", "increase", "delta"):
            # counter semantics: increase = last - first + resets. A reset
            # restarts the counter at ~0, so each one contributes the full
            # PRE-RESET value (Prometheus's correction), not the drop
            # amount. delta skips the correction (gauge). No edge
            # extrapolation (module docstring).
            drops = np.where(val[1:] < val[:-1], val[:-1], 0.0)
            cdrop = np.concatenate([[0.0], np.cumsum(drops)])
            nz = hi - lo >= 2
            first = val[np.where(nz, lo, 0)]
            last = val[np.where(nz, hi - 1, 0)]
            resets = cdrop[np.where(nz, hi - 1, 0)] - cdrop[np.where(nz, lo, 0)]
            if fn == "delta":
                vals[nz] = (last - first)[nz]
            else:
                inc = (last - first + resets)[nz]
                vals[nz] = inc if fn == "increase" else inc / (window / 1000.0)
            return vals
        raise PromQLError(f"unsupported function {fn}")

    async def _topk(self, node: TopK) -> list[SeriesVector]:
        """topk/bottomk with Prometheus RANGE semantics: the winning set is
        chosen independently at every step, so a series appears only at the
        steps where it ranks (masked NaN elsewhere)."""
        inner = await self.eval(node.expr)
        if isinstance(inner, float):
            raise PromQLError(f"{node.op}() needs a vector operand")
        if not inner or node.k <= 0:
            return []
        stack = np.stack([sv.values for sv in inner])  # [series, steps]
        fill = -np.inf if node.op == "topk" else np.inf
        arr = np.where(np.isnan(stack), fill, stack)
        # secondary validity key: the NaN fill ties with a REAL -Inf (topk)
        # / +Inf (bottomk) value, and a plain stable sort could rank the
        # absent series into the k-set (its mask would then silently drop a
        # real member). Valid entries must win every tie.
        isnan = np.isnan(stack)
        tie = isnan.astype(np.int8) if node.op == "bottomk" else (~isnan).astype(np.int8)
        order = np.lexsort((tie, arr), axis=0)
        k = min(node.k, stack.shape[0])
        keep_idx = order[-k:, :] if node.op == "topk" else order[:k, :]
        keep = np.zeros(stack.shape, dtype=bool)
        keep[keep_idx, np.arange(stack.shape[1])[None, :]] = True
        keep &= ~np.isnan(stack)
        out = []
        for i, sv in enumerate(inner):
            vals = np.where(keep[i], sv.values, np.nan)
            if not np.isnan(vals).all():
                out.append(SeriesVector(sv.labels, vals))
        return out

    # -- aggregation / arithmetic --------------------------------------------

    async def _agg(self, node: Agg) -> list[SeriesVector]:
        inner = await self.eval(node.expr)
        if isinstance(inner, float):
            raise PromQLError(f"{node.op}() needs a vector operand")
        groups: dict[tuple, list[SeriesVector]] = {}
        for sv in inner:
            if node.by is not None:
                key_labels = {k: sv.labels.get(k, "") for k in node.by}
            elif node.without is not None:
                key_labels = {
                    k: v for k, v in sv.labels.items()
                    if k not in node.without and k != "__name__"
                }
            else:
                key_labels = {}
            key = tuple(sorted(key_labels.items()))
            groups.setdefault(key, []).append(sv)
        out = []
        for key, members in sorted(groups.items()):
            stack = np.stack([m.values for m in members])
            with np.errstate(all="ignore"):
                if node.op == "sum":
                    vals = np.nansum(stack, axis=0)
                elif node.op == "avg":
                    vals = np.nanmean(stack, axis=0)
                elif node.op == "min":
                    vals = np.nanmin(stack, axis=0)
                elif node.op == "max":
                    vals = np.nanmax(stack, axis=0)
                else:  # count
                    vals = np.sum(~np.isnan(stack), axis=0).astype(np.float64)
            # all-NaN step stays NaN (nansum yields 0.0 there — mask it)
            allnan = np.isnan(stack).all(axis=0)
            if node.op in ("sum", "count"):
                vals = np.where(allnan, np.nan, vals)
            out.append(SeriesVector(dict(key), vals))
        return out

    async def _binop(self, node: BinOp):
        left = await self.eval(node.left)
        right = await self.eval(node.right)
        if isinstance(left, float) and isinstance(right, float):
            return float(_apply(node.op, np.float64(left), np.float64(right)))
        if isinstance(left, float):
            return [
                SeriesVector(sv.labels, _apply(node.op, left, sv.values))
                for sv in right
            ]
        if isinstance(right, float):
            return [
                SeriesVector(sv.labels, _apply(node.op, sv.values, right))
                for sv in left
            ]
        # vector-vector: exact one-to-one label-set matching (__name__
        # ignored, dropped from the result — Prometheus arithmetic strips
        # the metric name). Unmatched series drop; a duplicate label set
        # on either side would be many-to-one matching, which is outside
        # the subset and rejected loudly.
        rmap = _keyed(right, "right operand")
        out = []
        for key, lsv in _keyed(left, "left operand").items():
            rsv = rmap.get(key)
            if rsv is None:
                continue
            out.append(SeriesVector(
                dict(key), _apply(node.op, lsv.values, rsv.values)
            ))
        return out

    async def _cmp(self, node: "Cmp"):
        """Filter comparison: steps where the predicate fails become NaN
        (absent); the surviving value is the LEFT operand's, labels kept
        verbatim (Prometheus keeps __name__ through filter comparisons).
        Series with no surviving step drop entirely."""
        left = await self.eval(node.left)
        right = await self.eval(node.right)
        if isinstance(left, float) and isinstance(right, float):
            raise PromQLError(
                "scalar-scalar comparison needs the bool modifier, which "
                "is outside the subset; compare a vector against a scalar"
            )
        if isinstance(left, float):
            # scalar OP vector keeps the VECTOR's entries (Prometheus:
            # the vector side survives filtering); mirror the predicate
            out = []
            for sv in right:
                keep = _cmp_mask(node.op, np.full_like(sv.values, left),
                                 sv.values)
                vals = np.where(keep, sv.values, np.nan)
                if not np.isnan(vals).all():
                    out.append(SeriesVector(sv.labels, vals))
            return out
        if isinstance(right, float):
            pairs = [(sv, np.full_like(sv.values, right)) for sv in left]
        else:
            rmap = _keyed(right, "right operand")
            pairs = [
                (lsv, rmap[key].values)
                for key, lsv in _keyed(left, "left operand").items()
                if key in rmap
            ]
        out = []
        for lsv, rvals in pairs:
            keep = _cmp_mask(node.op, lsv.values, rvals)
            vals = np.where(keep, lsv.values, np.nan)
            if not np.isnan(vals).all():
                out.append(SeriesVector(lsv.labels, vals))
        return out

    async def _setop(self, node: "SetOp"):
        """and/or/unless per step on the __name__-stripped label set:
        `and` keeps left steps where the right series has a value,
        `unless` keeps left steps where it does NOT, `or` is the union
        with left winning matched steps. Left labels survive verbatim."""
        left = await self.eval(node.left)
        right = await self.eval(node.right)
        if isinstance(left, float) or isinstance(right, float):
            raise PromQLError(
                f"`{node.op}` needs vector operands on both sides"
            )
        rmap = _keyed(right, "right operand")
        lmap = _keyed(left, "left operand")
        out = []
        for key, lsv in lmap.items():
            rsv = rmap.get(key)
            if node.op == "and":
                if rsv is None:
                    continue
                vals = np.where(np.isnan(rsv.values), np.nan, lsv.values)
                if np.isnan(vals).all():
                    continue
            elif node.op == "unless":
                vals = (lsv.values if rsv is None
                        else np.where(np.isnan(rsv.values), lsv.values,
                                      np.nan))
                if np.isnan(vals).all():
                    continue
            else:  # or: left value wins; right fills left's gaps
                vals = (lsv.values if rsv is None
                        else np.where(np.isnan(lsv.values), rsv.values,
                                      lsv.values))
            out.append(SeriesVector(lsv.labels, vals))
        if node.op == "or":
            out.extend(rsv for key, rsv in rmap.items() if key not in lmap)
        return out


def _apply(op: str, a, b):
    with np.errstate(all="ignore"):
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        return a / b


def _keyed(vec, side: str) -> dict:
    """{__name__-stripped sorted label items: SeriesVector}. A duplicate
    key is many-to-one territory — rejected, not silently merged."""
    out = {}
    for sv in vec:
        key = tuple(sorted(
            (k, v) for k, v in sv.labels.items() if k != "__name__"
        ))
        if key in out:
            raise PromQLError(
                f"vector matching: duplicate label set {dict(key)} on the "
                f"{side} (many-to-one matching is outside the subset; "
                "aggregate one side first)"
            )
        out[key] = sv
    return out


def _cmp_mask(op: str, a, b):
    """Comparison predicate; NaN on either side compares False (the step
    is absent, so it cannot survive a filter)."""
    with np.errstate(all="ignore"):
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == "==":
            return a == b
        return ~np.isnan(a) & ~np.isnan(b) & (a != b)


def walk_expr(node):
    """Yield every node of a parsed PromQL expression tree (generic
    dataclass descent). THE walker: max_selector_window_ms,
    selector_metrics, the rule engine's relevance filter, and the
    server's provenance view all ride this one traversal, so a new node
    type (or a Selector field change) is handled in exactly one place."""
    from dataclasses import fields as dc_fields, is_dataclass

    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if is_dataclass(n) and not isinstance(n, type):
            for f in dc_fields(n):
                v = getattr(n, f.name)
                if isinstance(v, (list, tuple)):
                    stack.extend(v)
                else:
                    stack.append(v)


def selector_metrics(node) -> tuple:
    """Sorted metric names the expression reads (every selector)."""
    return tuple(sorted({
        n.name for n in walk_expr(node) if isinstance(n, Selector)
    }))


def max_selector_window_ms(node) -> int:
    """Largest data lookback any part of `node` reads at one step: the
    max selector range (rate windows) floored at the instant-vector
    LOOKBACK. The rule evaluator uses this to smear a dirty data range
    onto the output steps it can influence — a sample at time x can only
    change steps in (x, x + window]."""
    worst = LOOKBACK_MS
    for n in walk_expr(node):
        if isinstance(n, Selector):
            # `offset` shifts the DATA window back: a sample at x feeds
            # steps in (x + offset, x + offset + window] — the lookback
            # is window PLUS offset, not max of the two
            window = (int(n.range_ms) if n.range_ms is not None
                      else LOOKBACK_MS)
            worst = max(worst, window + int(n.offset_ms or 0))
    return worst


async def evaluate_range(
    engine, expr, start_ms: int, end_ms: int, step_ms: int,
    max_series: int = 10_000,
) -> "tuple[np.ndarray, list[SeriesVector] | float]":
    """The reusable eval entry for standing queries (rule bodies): parse
    (if given a string) and evaluate over the [start, end] step grid,
    returning (steps, series). Exactly the engine the HTTP handlers run —
    a recording rule's incremental output is bit-exact vs a cold
    /api/v1/query_range of the same body by construction, because both
    ARE this function."""
    from horaedb_tpu.promql import parse

    node = parse(expr) if isinstance(expr, str) else expr
    ev = RangeEvaluator(engine, start_ms, end_ms, step_ms,
                        max_series=max_series)
    return ev.steps, await ev.eval(node)


def to_prometheus_matrix(
    series: "list[SeriesVector] | float", steps: np.ndarray
) -> dict:
    """Prometheus /api/v1/query_range response `data` payload."""
    secs = steps / 1000.0
    if isinstance(series, float):
        return {
            "resultType": "matrix",
            "result": [{
                "metric": {},
                "values": [[float(s), _fmt(series)] for s in secs],
            }],
        }
    result = []
    for sv in series:
        pts = [
            [float(secs[i]), _fmt(sv.values[i])]
            for i in range(len(steps))
            if not np.isnan(sv.values[i])
        ]
        if pts:
            result.append({"metric": sv.labels, "values": pts})
    return {"resultType": "matrix", "result": result}


def to_prometheus_vector(
    series: "list[SeriesVector] | float", at_ms: int
) -> dict:
    """Prometheus instant-query `data` payload (last step only)."""
    sec = at_ms / 1000.0
    if isinstance(series, float):
        return {
            "resultType": "scalar",
            "result": [sec, _fmt(series)],
        }
    result = []
    for sv in series:
        v = sv.values[-1]
        if not np.isnan(v):
            result.append({"metric": sv.labels, "value": [sec, _fmt(v)]})
    return {"resultType": "vector", "result": result}


def _fmt(v) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)
