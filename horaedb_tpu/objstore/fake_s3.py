"""In-process fake S3 endpoint for tests, soak runs, and dev.

The reference tests its store layer against tmpdir LocalFileSystem
(storage.rs:394-396) because the `object_store` crate is assumed correct;
this repo's S3 client is first-party, so it gets a real HTTP counterparty:
an aiohttp server speaking the subset of the S3 API the client uses —
GET/PUT/HEAD/DELETE on objects and ListObjectsV2 with continuation tokens.

Fault injection for retry tests: `fail_next(n, status)` makes the next n
object requests fail with the given status. Every request's Authorization
header is recorded so tests can assert SigV4 signing happened (full
signature VERIFICATION also supported via `verify_signatures`, using the
same public algorithm from the client module — a differential check, both
sides computing independently from the raw request).
"""

from __future__ import annotations

import urllib.parse
from xml.sax.saxutils import escape

from aiohttp import web

from horaedb_tpu.objstore.s3 import sign_v4

_LIST_PAGE = 1000


class FakeS3:
    """One bucket namespace held in a dict; start()/stop() manage the site."""

    def __init__(self, bucket: str = "test-bucket",
                 verify_signatures: tuple[str, str, str] | None = None,
                 list_page: int = _LIST_PAGE,
                 ignore_conditional_puts: bool = False) -> None:
        self.bucket = bucket
        self.objects: dict[str, bytes] = {}
        self.auth_headers: list[str] = []
        self.requests: list[tuple[str, str]] = []
        self.list_page = list_page
        # emulate pre-2024 S3 clones that answer 200 to a conditional PUT
        # on an existing key (the capability the fence probe must reject)
        self.ignore_conditional_puts = ignore_conditional_puts
        self._fail_budget = 0
        self._fail_status = 500
        # (key_id, key_secret, region) -> reject bad signatures with 403
        self._verify = verify_signatures
        self._runner: web.AppRunner | None = None
        self.port: int | None = None

    # -- fault injection -----------------------------------------------------

    def fail_next(self, n: int, status: int = 500) -> None:
        self._fail_budget = n
        self._fail_status = status

    def _etag(self, key: str) -> str:
        """S3-shaped quoted ETag over the object content (md5 like real
        single-part uploads — it only has to be stable and
        content-addressed for the conditional-GET contract)."""
        import hashlib

        return f'"{hashlib.md5(self.objects[key]).hexdigest()}"'

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> str:
        app = web.Application()
        app.router.add_route("GET", "/{bucket}", self._list)
        app.router.add_route("*", "/{bucket}/{key:.*}", self._object)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- request handling ----------------------------------------------------

    def _gate(self, request: web.Request) -> web.Response | None:
        self.requests.append((request.method, request.path_qs))
        auth = request.headers.get("Authorization", "")
        self.auth_headers.append(auth)
        if self._fail_budget > 0:
            self._fail_budget -= 1
            return web.Response(status=self._fail_status, text="injected fault")
        if self._verify is not None:
            resp = self._check_signature(request, auth)
            if resp is not None:
                return resp
        return None

    def _check_signature(self, request: web.Request, auth: str) -> web.Response | None:
        key_id, key_secret, region = self._verify
        try:
            signed = dict(
                part.split("=", 1)
                for part in auth.removeprefix("AWS4-HMAC-SHA256 ").split(", ")
            )
            signed_names = signed["SignedHeaders"].split(";")
        except (ValueError, KeyError):
            return web.Response(status=403, text="malformed Authorization")
        headers = {n: request.headers.get(n, "") for n in signed_names}
        expect = sign_v4(
            request.method,
            urllib.parse.quote(request.path, safe="/-_.~"),
            [(k, v) for k, v in request.query.items()],
            headers,
            request.headers.get("x-amz-content-sha256", ""),
            key_id, key_secret, region,
            request.headers.get("x-amz-date", ""),
        )
        if expect != auth:
            return web.Response(status=403, text="SignatureDoesNotMatch")
        return None

    async def _object(self, request: web.Request) -> web.Response:
        gated = self._gate(request)
        if gated is not None:
            return gated
        if request.match_info["bucket"] != self.bucket:
            return web.Response(status=404, text="NoSuchBucket")
        key = request.match_info["key"]
        if request.method == "PUT":
            if (
                request.headers.get("If-None-Match") == "*"
                and key in self.objects
                and not self.ignore_conditional_puts
            ):
                return web.Response(status=412, text="PreconditionFailed")
            self.objects[key] = await request.read()
            return web.Response(status=200,
                                headers={"ETag": self._etag(key)})
        if key not in self.objects:
            return web.Response(status=404, text="NoSuchKey")
        if request.method == "GET":
            # conditional GET (the cluster watch primitive): a matching
            # If-None-Match answers 304 with no body, like real S3
            etag = self._etag(key)
            if request.headers.get("If-None-Match") == etag:
                return web.Response(status=304, headers={"ETag": etag})
            return web.Response(body=self.objects[key],
                                headers={"ETag": etag})
        if request.method == "HEAD":
            return web.Response(
                headers={"Content-Length": str(len(self.objects[key]))}
            )
        if request.method == "DELETE":
            del self.objects[key]
            return web.Response(status=204)
        return web.Response(status=405)

    async def _list(self, request: web.Request) -> web.Response:
        gated = self._gate(request)
        if gated is not None:
            return gated
        if request.match_info["bucket"] != self.bucket:
            return web.Response(status=404, text="NoSuchBucket")
        if request.query.get("list-type") != "2":
            return web.Response(status=400, text="only ListObjectsV2")
        prefix = request.query.get("prefix", "")
        token = request.query.get("continuation-token", "")
        keys = sorted(k for k in self.objects if k.startswith(prefix))
        if token:
            keys = [k for k in keys if k > token]
        page, rest = keys[: self.list_page], keys[self.list_page:]
        items = "".join(
            f"<Contents><Key>{escape(k)}</Key>"
            f"<Size>{len(self.objects[k])}</Size></Contents>"
            for k in page
        )
        trunc = "true" if rest else "false"
        nxt = (
            f"<NextContinuationToken>{escape(page[-1])}</NextContinuationToken>"
            if rest else ""
        )
        xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            '<ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<IsTruncated>{trunc}</IsTruncated>{nxt}{items}"
            "</ListBucketResult>"
        )
        return web.Response(text=xml, content_type="application/xml")
