"""ChaosStore: seeded fault injection over any ObjectStore.

The reference tests its store layer against a well-behaved tmpdir
filesystem; real S3 misbehaves in specific, enumerable ways. This module
makes those behaviors injectable so the chaos lane (tests/test_chaos.py,
tools/chaos_smoke.py) can drive the WHOLE engine — write, flush,
compact, scan, crash, reopen — against them and assert exact results
plus zero acknowledged-row loss:

- **Injected errors**: per-op-type probability of raising a transient
  (`InjectedFault`, classified retryable) error before or after the
  inner op runs ("after" models a lost ack: the op took effect but the
  caller saw a failure — retries must be idempotent).
- **Added latency**: per-op delay, for deadline/timeout exercise.
- **Torn writes**: a `put` lands a PREFIX of the payload in the inner
  store, then raises — the non-atomic backend a crashed multipart leaves
  behind. (Readers must never trust an object the manifest doesn't
  reference; recovery must GC it.)
- **Delayed visibility**: a `put` commits (GET/HEAD see it — matching
  S3's strong read-after-write), but LIST omits it for
  `visibility_lag_ops` store ops (or until `settle()`) — the
  eventual-listing behavior manifest merges, fence validation, and
  orphan GC must tolerate. Conditional puts are exempt: S3's
  conditional writes are strongly consistent, and the fence stakes
  correctness on exactly that.
- **Crash points**: `crash_next(op, path_substr)` raises `InjectedCrash`
  (a BaseException — deliberately NOT retryable/catchable by the
  resilience layer) at the matching call, modelling the process dying
  mid-sequence. The harness abandons the engine object without close()
  and reopens over the surviving store state.

Determinism: every probabilistic decision comes from one
`random.Random(seed)`; a `FaultPlan` is a value object, so a failing
soak seed reproduces exactly.

Explicit one-shot injections (`fail_next`) exist alongside the
probabilistic plan for tests that need a fault at an exact call.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field

from horaedb_tpu.common.error import RetryableError
from horaedb_tpu.objstore import ObjectMeta, ObjectStore


class InjectedFault(RetryableError):
    """A chaos-plan transient fault (retryable by design)."""


class InjectedCrash(BaseException):
    """The simulated process death. BaseException on purpose: nothing in
    the engine (including the resilience layer's `except Exception`
    ladders) may swallow it — it must unwind to the chaos harness, which
    then abandons the engine and reopens, exactly like a real crash."""


@dataclass
class OpFaults:
    """Per-op-type probabilities/levels. All default to 'well-behaved'."""

    error_rate: float = 0.0          # P(raise InjectedFault before the op)
    lost_ack_rate: float = 0.0       # P(op runs, then raise anyway)
    latency_s: float = 0.0           # added await before the op
    # put only, DATA-plane paths only ("/data/" objects): land a prefix,
    # then raise. Control-plane writes (manifest delta/snapshot, fence
    # epochs) are atomic in every real backend — S3's single PUT is
    # atomic and LocalStore renames — so tearing them would model a
    # store no deployment runs on; crashed multipart DATA uploads are
    # the real-world source of partial objects.
    torn_write_rate: float = 0.0


@dataclass
class FaultPlan:
    """A seeded chaos schedule. `ops` maps op name (put/get/list/delete/
    head/put_if_absent/put_stream) to its OpFaults; missing ops are
    clean. `visibility_lag_ops` > 0 hides every put from LIST for that
    many subsequent store ops (0 = immediately listed)."""

    seed: int = 0
    ops: dict[str, OpFaults] = field(default_factory=dict)
    visibility_lag_ops: int = 0

    def for_op(self, op: str) -> OpFaults:
        return self.ops.get(op) or _CLEAN


_CLEAN = OpFaults()


class ChaosStore(ObjectStore):
    """ObjectStore decorator applying a FaultPlan (see module docstring)."""

    def __init__(self, inner: ObjectStore, plan: FaultPlan | None = None):
        self._inner = inner
        self.plan = plan or FaultPlan()
        self._rng = random.Random(self.plan.seed)
        # eventual-listing lag: path -> op_no at which LIST starts seeing it
        self._unlisted: dict[str, int] = {}
        self._op_no = 0
        # explicit one-shot injections: op -> remaining forced failures
        self._fail_next: dict[str, int] = {}
        # armed crash points: (op, path_substr)
        self._crashes: list[tuple[str, str]] = []
        self.injected_errors = 0
        self.injected_crashes = 0

    # -- explicit controls ---------------------------------------------------

    def fail_next(self, op: str, n: int = 1) -> None:
        """Force the next `n` calls of `op` to raise InjectedFault."""
        self._fail_next[op] = self._fail_next.get(op, 0) + n

    def crash_next(self, op: str, path_substr: str = "") -> None:
        """Arm a crash point: the next `op` call whose path contains
        `path_substr` raises InjectedCrash INSTEAD of running."""
        self._crashes.append((op, path_substr))

    def settle(self) -> None:
        """Make every lagging object LIST-visible now."""
        self._unlisted.clear()

    # -- fault machinery -----------------------------------------------------

    def _check_crash(self, op: str, path: str) -> None:
        for i, (c_op, substr) in enumerate(self._crashes):
            if c_op == op and substr in path:
                del self._crashes[i]
                self.injected_crashes += 1
                raise InjectedCrash(f"injected crash at {op} {path}")

    async def _pre(self, op: str, path: str) -> OpFaults:
        """Shared prologue: tick the op clock (expiring listing lag),
        check crash points and forced failures, apply latency, roll the
        error dice."""
        self._op_no += 1
        self._settle_due()
        self._check_crash(op, path)
        faults = self.plan.for_op(op)
        if self._fail_next.get(op, 0) > 0:
            self._fail_next[op] -= 1
            self.injected_errors += 1
            raise InjectedFault(f"forced fault: {op} {path}")
        if faults.latency_s > 0:
            await asyncio.sleep(faults.latency_s)
        if faults.error_rate > 0 and self._rng.random() < faults.error_rate:
            self.injected_errors += 1
            raise InjectedFault(f"injected fault: {op} {path}")
        return faults

    def _post(self, op: str, path: str, faults: OpFaults) -> None:
        """Lost-ack injection: the op ran; the caller still sees a fault."""
        if faults.lost_ack_rate > 0 and self._rng.random() < faults.lost_ack_rate:
            self.injected_errors += 1
            raise InjectedFault(f"injected lost ack: {op} {path}")

    def _settle_due(self) -> None:
        if not self._unlisted:
            return
        for p in [p for p, at in self._unlisted.items() if self._op_no >= at]:
            del self._unlisted[p]

    def _mark_unlisted(self, path: str) -> None:
        if self.plan.visibility_lag_ops > 0:
            self._unlisted[path] = self._op_no + self.plan.visibility_lag_ops

    # -- the verbs -----------------------------------------------------------

    async def put(self, path: str, data: bytes) -> None:
        faults = await self._pre("put", path)
        if (
            faults.torn_write_rate > 0 and "/data/" in path
            and self._rng.random() < faults.torn_write_rate
        ):
            # a torn PUT: a strict prefix lands, the ack never comes
            cut = self._rng.randrange(0, max(1, len(data)))
            await self._inner.put(path, bytes(data[:cut]))
            self._mark_unlisted(path)
            self.injected_errors += 1
            raise InjectedFault(f"injected torn write: put {path} ({cut}B)")
        await self._inner.put(path, bytes(data))
        self._mark_unlisted(path)
        self._post("put", path, faults)

    async def put_if_absent(self, path: str, data: bytes) -> None:
        faults = await self._pre("put_if_absent", path)
        # conditional puts skip listing lag: they ARE the arbiter the
        # fence stakes correctness on, and S3's conditional writes are
        # strongly consistent even where listings lag
        await self._inner.put_if_absent(path, bytes(data))
        self._post("put_if_absent", path, faults)

    async def get(self, path: str) -> bytes:
        faults = await self._pre("get", path)
        # read-after-write is STRONG (matching modern S3): lag hits LIST only
        data = await self._inner.get(path)
        self._post("get", path, faults)
        return data

    async def get_if_changed(self, path: str, etag):
        # the conditional GET is a get for fault purposes: same error
        # rates/latency/crash points (the replica watch loop under test)
        faults = await self._pre("get", path)
        out = await self._inner.get_if_changed(path, etag)
        self._post("get", path, faults)
        return out

    async def list(self, prefix: str) -> list[ObjectMeta]:
        faults = await self._pre("list", prefix)
        out = await self._inner.list(prefix)
        if self._unlisted:
            out = [m for m in out if m.path not in self._unlisted]
        self._post("list", prefix, faults)
        return out

    async def delete(self, path: str) -> None:
        faults = await self._pre("delete", path)
        self._unlisted.pop(path, None)
        await self._inner.delete(path)
        self._post("delete", path, faults)

    async def head(self, path: str) -> ObjectMeta:
        faults = await self._pre("head", path)
        meta = await self._inner.head(path)
        self._post("head", path, faults)
        return meta

    async def put_stream(self, path: str, chunks) -> int:
        """Streamed put: crash points fire mid-stream (after the first
        chunk is consumed) so a crashed multipart leaves consumed-but-
        unlanded bytes, the worst case for replay logic."""
        faults = await self._pre("put_stream", path)
        parts: list[bytes] = []
        async for c in chunks:
            parts.append(c)
            self._check_crash("put_stream_mid", path)
        data = await asyncio.to_thread(b"".join, parts)
        await self._inner.put(path, data)
        self._mark_unlisted(path)
        self._post("put_stream", path, faults)
        return sum(len(p) for p in parts)

    # -- pass-throughs -------------------------------------------------------

    async def verify_conditional_puts(self, prefix: str) -> None:
        await self._inner.verify_conditional_puts(prefix)

    def local_path(self, path: str) -> str | None:
        return self._inner.local_path(path)

    async def close(self) -> None:
        closer = getattr(self._inner, "close", None)
        if closer is not None:
            await closer()
