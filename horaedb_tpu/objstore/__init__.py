"""Object-store abstraction — the durability layer and inter-component "network".

The reference's shared medium is the `object_store` crate's put/get/list/delete/
head API over S3-like storage, with LocalFileSystem as the dev backend
(SURVEY §5.8; reference: src/columnar_storage/src/types.rs:135, used at
storage.rs:193,216 and manifest/mod.rs:139-143,301-315). We keep the same
five-verb contract. All methods are async; LocalStore offloads blocking file IO
to threads so manifest/compaction loops never block the event loop.
"""

from __future__ import annotations

import asyncio
import hashlib
import itertools
import os
import shutil
import threading
from abc import ABC, abstractmethod
from dataclasses import dataclass

# unique per-attempt suffix stream for LocalStore.put_if_absent sidecars
_ifabsent_seq = itertools.count()

from horaedb_tpu.common.error import HoraeError


@dataclass(frozen=True)
class ObjectMeta:
    """Result of `head` — the subset of metadata the engine uses."""

    path: str
    size: int


class NotFound(HoraeError):
    """Raised by get/head/delete on a missing object (manifest recovery
    distinguishes missing-snapshot from corrupt-snapshot, manifest/mod.rs:336-354)."""


class PreconditionFailed(HoraeError):
    """Raised by put_if_absent when the object already exists — the loser's
    signal in the region-ownership epoch race (storage/fence.py)."""


class ObjectStore(ABC):
    """put/get/list/delete/head over a flat namespace of `/`-separated keys."""

    @abstractmethod
    async def put(self, path: str, data: bytes) -> None: ...

    async def put_if_absent(self, path: str, data: bytes) -> None:
        """Atomic create-if-absent: succeeds exactly once per key across all
        concurrent callers; raises PreconditionFailed if the key exists.
        The primitive behind epoch fencing (S3: `If-None-Match: *`
        conditional PUT; local FS: O_EXCL-style link; memory: dict under
        lock). Stores that cannot provide it must override and raise."""
        raise HoraeError(
            f"{type(self).__name__} does not support conditional puts"
        )

    async def verify_conditional_puts(self, prefix: str) -> None:
        """Prove put_if_absent is actually ENFORCED before anything (epoch
        fencing) stakes correctness on it. Part of the store contract so
        callers invoke it unconditionally — a silently-skipped probe is a
        latent split-brain. Default: no-op, because local/memory stores
        enforce natively in-process (O_EXCL link / dict under lock);
        stores whose enforcement is a REMOTE claim (S3-likes: the far
        endpoint's If-None-Match handling) override with a real probe
        that raises HoraeError on a non-enforcing endpoint."""
        return None

    @abstractmethod
    async def get(self, path: str) -> bytes: ...

    async def get_if_changed(
        self, path: str, etag: "str | None"
    ) -> "tuple[bytes | None, str]":
        """Conditional GET — the cluster watch primitive (HTTP 304 /
        If-None-Match analog). Returns `(data, new_etag)` when the object
        differs from `etag`, `(None, etag)` when unchanged; raises
        NotFound on a missing object like `get`. `etag=None` always
        fetches. The default is an unconditional GET plus a content
        digest compare — correct for every backend; stores with real
        ETags (S3-likes) override so an unchanged probe costs one 304,
        not a transfer. Read replicas tail manifests with this
        (horaedb_tpu/cluster/replica.py)."""
        data = await self.get(path)
        new = "d:" + hashlib.blake2b(data, digest_size=16).hexdigest()
        if etag is not None and new == etag:
            return None, etag
        return data, new

    @abstractmethod
    async def list(self, prefix: str) -> list[ObjectMeta]: ...

    @abstractmethod
    async def delete(self, path: str) -> None: ...

    @abstractmethod
    async def head(self, path: str) -> ObjectMeta: ...

    # Local filesystem path for readers that need one (parquet mmap); stores
    # without local paths return None and callers fall back to `get` bytes.
    def local_path(self, path: str) -> str | None:
        return None

    async def put_stream(self, path: str, chunks) -> int:
        """Streaming put from an async iterator of bytes chunks. The default
        accumulates then puts (fine for in-memory fakes); stores with real
        backends override to bound memory at chunk granularity."""
        parts = []
        async for c in chunks:
            parts.append(c)
        # the join materializes the whole object — CPU-bound for large
        # SSTs, so it runs off the event loop (J018)
        data = await asyncio.to_thread(b"".join, parts)
        await self.put(path, data)
        return len(data)


class MemStore(ObjectStore):
    """In-memory store for tests (the reference uses tmpdir+LocalFileSystem as
    its fake backend, storage.rs:394-396; we provide both)."""

    def __init__(self) -> None:
        self._objects: dict[str, bytes] = {}
        self._lock = asyncio.Lock()

    async def put(self, path: str, data: bytes) -> None:
        async with self._lock:
            self._objects[path] = bytes(data)

    async def put_if_absent(self, path: str, data: bytes) -> None:
        async with self._lock:
            if path in self._objects:
                raise PreconditionFailed(f"object exists: {path}")
            self._objects[path] = bytes(data)

    async def get(self, path: str) -> bytes:
        try:
            return self._objects[path]
        except KeyError:
            raise NotFound(f"object not found: {path}") from None

    async def list(self, prefix: str) -> list[ObjectMeta]:
        norm = prefix.rstrip("/") + "/" if prefix else ""
        out = [
            ObjectMeta(path=k, size=len(v))
            for k, v in self._objects.items()
            if k.startswith(norm)
        ]
        out.sort(key=lambda m: m.path)
        return out

    async def delete(self, path: str) -> None:
        async with self._lock:
            if self._objects.pop(path, None) is None:
                raise NotFound(f"object not found: {path}")

    async def head(self, path: str) -> ObjectMeta:
        try:
            return ObjectMeta(path=path, size=len(self._objects[path]))
        except KeyError:
            raise NotFound(f"object not found: {path}") from None


class LocalStore(ObjectStore):
    """Object store over a local directory (reference: object_store's
    LocalFileSystem, built in src/server/src/main.rs:122-124)."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _fs_path(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path.lstrip("/")))
        if p != self.root and not p.startswith(self.root + os.sep):
            raise HoraeError(f"path escapes store root: {path}")
        return p

    async def put(self, path: str, data: bytes) -> None:
        def _put() -> None:
            fs = self._fs_path(path)
            os.makedirs(os.path.dirname(fs), exist_ok=True)
            # Atomic replace: write sidecar then rename, so a crashed put never
            # leaves a truncated snapshot (manifest commit point semantics,
            # manifest/mod.rs:301-307).
            tmp = fs + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, fs)

        await asyncio.to_thread(_put)

    async def put_if_absent(self, path: str, data: bytes) -> None:
        def _put() -> None:
            fs = self._fs_path(path)
            os.makedirs(os.path.dirname(fs), exist_ok=True)
            # full-content atomic create: write a sidecar, then hard-link it
            # to the final name — link(2) fails with EEXIST atomically, and
            # the object can never be observed partially written. The sidecar
            # name must be unique per ATTEMPT (pid alone collides across the
            # thread pool's concurrent callers racing one key)
            tmp = fs + f".{os.getpid()}.{threading.get_ident()}.{next(_ifabsent_seq)}.ifabsent"
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            try:
                os.link(tmp, fs)
            except FileExistsError:
                raise PreconditionFailed(f"object exists: {path}") from None
            finally:
                try:
                    os.remove(tmp)
                except OSError:
                    pass

        await asyncio.to_thread(_put)

    async def put_stream(self, path: str, chunks) -> int:
        """Streaming put from an async iterator of bytes chunks (the
        multipart-upload analog: the reference streams SST encodes straight
        to the store via AsyncArrowWriter, storage.rs:192-224). Atomic: the
        object appears only after the final rename; an aborted stream leaves
        nothing at `path`. Returns total bytes written."""
        fs = self._fs_path(path)
        os.makedirs(os.path.dirname(fs), exist_ok=True)
        tmp = fs + ".tmp"
        total = 0
        f = await asyncio.to_thread(open, tmp, "wb")
        try:
            async for chunk in chunks:
                await asyncio.to_thread(f.write, chunk)
                total += len(chunk)
            await asyncio.to_thread(f.flush)
            await asyncio.to_thread(os.fsync, f.fileno())
            await asyncio.to_thread(f.close)
            await asyncio.to_thread(os.replace, tmp, fs)
        except BaseException:
            try:
                f.close()
            finally:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            raise
        return total

    async def get(self, path: str) -> bytes:
        def _get() -> bytes:
            fs = self._fs_path(path)
            try:
                with open(fs, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                raise NotFound(f"object not found: {path}") from None

        return await asyncio.to_thread(_get)

    async def get_if_changed(
        self, path: str, etag: "str | None"
    ) -> "tuple[bytes | None, str]":
        """Stat-token conditional GET: (inode, mtime_ns, size) names the
        object version — every put lands via os.replace, so a changed
        object is a NEW inode. An unchanged probe costs one stat, no
        read (the watch-loop economy the base digest default can't give
        a filesystem store)."""
        def _probe():
            fs = self._fs_path(path)
            try:
                st = os.stat(fs)
            except FileNotFoundError:
                raise NotFound(f"object not found: {path}") from None
            tok = f"s:{st.st_ino}:{st.st_mtime_ns}:{st.st_size}"
            if etag is not None and tok == etag:
                return None, tok
            try:
                with open(fs, "rb") as f:
                    return f.read(), tok
            except FileNotFoundError:
                raise NotFound(f"object not found: {path}") from None

        return await asyncio.to_thread(_probe)

    async def list(self, prefix: str) -> list[ObjectMeta]:
        def _list() -> list[ObjectMeta]:
            base = self._fs_path(prefix) if prefix else self.root
            out: list[ObjectMeta] = []
            if not os.path.isdir(base):
                return out
            for dirpath, _dirnames, filenames in os.walk(base):
                for name in filenames:
                    if name.endswith((".tmp", ".ifabsent")):
                        continue
                    fs = os.path.join(dirpath, name)
                    rel = os.path.relpath(fs, self.root).replace(os.sep, "/")
                    out.append(ObjectMeta(path=rel, size=os.path.getsize(fs)))
            out.sort(key=lambda m: m.path)
            return out

        return await asyncio.to_thread(_list)

    async def delete(self, path: str) -> None:
        def _delete() -> None:
            try:
                os.remove(self._fs_path(path))
            except FileNotFoundError:
                raise NotFound(f"object not found: {path}") from None

        await asyncio.to_thread(_delete)

    async def head(self, path: str) -> ObjectMeta:
        def _head() -> ObjectMeta:
            try:
                return ObjectMeta(path=path, size=os.path.getsize(self._fs_path(path)))
            except FileNotFoundError:
                raise NotFound(f"object not found: {path}") from None

        return await asyncio.to_thread(_head)

    def local_path(self, path: str) -> str | None:
        return self._fs_path(path)

    def destroy(self) -> None:
        shutil.rmtree(self.root, ignore_errors=True)
