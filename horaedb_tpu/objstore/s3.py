"""S3-compatible object store backend.

The reference's "cloud native" data plane IS the object store: its server
config defines the full S3 knob tree (region/keys/endpoint/bucket/prefix/
max_retries/http/timeout — src/server/src/config.rs:104-170) in front of the
`object_store` crate. This is the TPU framework's equivalent: the same five
verbs (put/get/list/delete/head) over any S3-compatible HTTP endpoint
(AWS, minio, GCS-interop, the in-repo fake), signed with AWS Signature v4,
with bounded retries and the reference's two-tier timeout split (metadata ops
vs data IO).

Design notes:
- Path-style addressing (`{endpoint}/{bucket}/{key}`) because the endpoint is
  always explicit in the config — virtual-hosted style needs DNS wildcards
  that self-hosted S3s rarely have.
- `delete` HEADs first so a missing object raises NotFound: S3's DELETE is
  idempotent (204 for absent keys) but the engine contract distinguishes
  missing-from-present (manifest recovery, manifest/mod.rs:336-354).
- Retries: idempotent verbs retry on 5xx/429 and transport errors with
  exponential backoff (50 ms * 2^n, capped 2 s), `max_retries` total attempts.
  PUT is retried too — S3 PUT is atomic-replace, so a duplicate is harmless.
- ListObjectsV2 with continuation tokens; keys are returned RELATIVE to the
  configured prefix so the engine sees the same namespace as LocalStore.
"""

from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
import logging
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field

from horaedb_tpu.common.error import (
    HoraeError,
    PersistentError,
    RetryableError,
)
from horaedb_tpu.common.time_ext import ReadableDuration
from horaedb_tpu.objstore import (
    NotFound,
    ObjectMeta,
    ObjectStore,
    PreconditionFailed,
)

logger = logging.getLogger(__name__)

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


@dataclass
class HttpOptions:
    """Connection-pool knobs (reference config.rs:135-151, same defaults)."""

    pool_max_idle_per_host: int = 1024
    timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(15)
    )
    keep_alive_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(10)
    )
    keep_alive_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(2)
    )


@dataclass
class TimeoutOptions:
    """Two-tier timeouts (reference config.rs:153-170): `timeout` bounds
    single-object metadata ops (head/delete/list page), `io_timeout` bounds
    data-moving ops (get/put). On top of those TOTAL bounds, two explicit
    transport-layer timeouts (config-surfaced under
    `[metric_engine.storage.object_store.timeout]`):

    - `connect_timeout` bounds TCP connect + TLS handshake per attempt —
      a black-holed endpoint (SYN dropped by a firewall) fails in
      seconds instead of pinning a flush worker for the full total;
    - `read_timeout` bounds the gap between received chunks (sock_read)
      — a server that accepts the request then stalls mid-body trips
      this long before a large transfer's generous total would."""

    timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(10)
    )
    io_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(10)
    )
    connect_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(5)
    )
    read_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(10)
    )


@dataclass
class S3LikeConfig:
    """Mirror of the reference's S3LikeStorageConfig (config.rs:104-130)."""

    region: str = ""
    key_id: str = ""
    key_secret: str = ""
    endpoint: str = ""
    bucket: str = ""
    prefix: str = ""
    max_retries: int = 3
    http: HttpOptions = field(default_factory=HttpOptions)
    timeout: TimeoutOptions = field(default_factory=TimeoutOptions)


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_v4(
    method: str,
    canonical_uri: str,
    query: list[tuple[str, str]],
    headers: dict[str, str],
    payload_hash: str,
    key_id: str,
    key_secret: str,
    region: str,
    amz_date: str,
) -> str:
    """AWS Signature Version 4 for service "s3" — returns the Authorization
    header value. Public algorithm (AWS docs "Signature Calculations for the
    Authorization Header"); `headers` must already include host and
    x-amz-date, and every header given is signed."""
    date = amz_date[:8]
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(query)
    )
    lower = {k.lower().strip(): " ".join(v.split()) for k, v in headers.items()}
    signed_names = ";".join(sorted(lower))
    canonical_headers = "".join(f"{k}:{lower[k]}\n" for k in sorted(lower))
    canonical_request = "\n".join([
        method, canonical_uri, canonical_query, canonical_headers,
        signed_names, payload_hash,
    ])
    scope = f"{date}/{region}/s3/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])
    k = _hmac(("AWS4" + key_secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, "s3")
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    return (
        f"AWS4-HMAC-SHA256 Credential={key_id}/{scope}, "
        f"SignedHeaders={signed_names}, Signature={signature}"
    )


class S3Error(PersistentError):
    """Deterministic S3 rejection (4xx other than 404/412): the same
    request fails the same way every time — classified `persistent` so
    upper layers (ResilientStore, the flush executor) surface it instead
    of burning retry budget."""


class S3RetriesExhausted(S3Error, RetryableError):
    """The client's own bounded retries ran out against 5xx/429/transport
    faults. Still an S3Error for compatibility, but classified
    `retryable` (RetryableError wins in classify()): the fault was
    transient — a LATER attempt may succeed, which is exactly what the
    resilience layer's longer ladder and breaker exist to decide."""


class S3LikeStore(ObjectStore):
    """ObjectStore over an S3-compatible endpoint (see module docstring)."""

    def __init__(self, config: S3LikeConfig) -> None:
        if not config.endpoint or not config.bucket:
            raise HoraeError("S3Like store requires endpoint and bucket")
        self.config = config
        self._endpoint = config.endpoint.rstrip("/")
        self._host = urllib.parse.urlparse(self._endpoint).netloc
        self._prefix = config.prefix.strip("/")
        self._session = None  # created lazily inside the running loop
        self._cond_put_verified = False  # set by verify_conditional_puts

    # -- key <-> object mapping ---------------------------------------------

    def _key(self, path: str) -> str:
        p = path.lstrip("/")
        full = f"{self._prefix}/{p}" if self._prefix else p
        if ".." in full.split("/"):
            raise HoraeError(f"path escapes store prefix: {path}")
        return full

    def _uri(self, key: str) -> str:
        # sign and request the SAME encoding; '/' stays literal
        return "/" + urllib.parse.quote(f"{self.config.bucket}/{key}", safe="/-_.~")

    # -- transport ----------------------------------------------------------

    async def _ensure_session(self):
        if self._session is None:
            import aiohttp

            ka = self.config.http.keep_alive_timeout.seconds
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(
                    limit_per_host=self.config.http.pool_max_idle_per_host,
                    keepalive_timeout=ka,
                ),
                timeout=aiohttp.ClientTimeout(
                    connect=self.config.http.timeout.seconds
                ),
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    def _headers(
        self, method: str, uri: str, query: list[tuple[str, str]], payload: bytes | None
    ) -> dict[str, str]:
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        payload_hash = (
            hashlib.sha256(payload).hexdigest() if payload else _EMPTY_SHA256
        )
        headers = {
            "host": self._host,
            "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash,
        }
        headers["Authorization"] = sign_v4(
            method, uri, query, headers, payload_hash,
            self.config.key_id, self.config.key_secret,
            self.config.region, amz_date,
        )
        return headers

    async def _request(
        self,
        method: str,
        key: str,
        *,
        query: list[tuple[str, str]] | None = None,
        payload: bytes | None = None,
        io: bool = False,
        uri: str | None = None,
        extra_headers: dict[str, str] | None = None,
        allow_statuses: tuple[int, ...] = (),
        header_names: tuple[str, ...] = (),
    ):
        """One signed request with bounded retries. Returns (status, body,
        content_length) — plus a dict of the response headers named in
        `header_names` (keyed EXACTLY as passed; aiohttp's lookup is
        case-insensitive, the returned dict's is not) appended as a 4th
        element when any are requested. 404 surfaces as NotFound; other
        4xx raise S3Error immediately; 5xx/429 and transport errors
        retry.

        `extra_headers` ride unsigned (legal in SigV4 — only SignedHeaders
        participate in the signature); conditional headers like
        `If-None-Match` go here. Statuses in `allow_statuses` return to the
        caller instead of raising (e.g. 412 PreconditionFailed)."""
        import aiohttp

        import yarl

        session = await self._ensure_session()
        query = query or []
        uri = uri if uri is not None else self._uri(key)
        # the WIRE query string must be byte-identical to the canonical query
        # that was signed — build it once and pass pre-encoded so yarl
        # cannot re-quote it differently
        qs = "&".join(
            f"{urllib.parse.quote(k, safe='-_.~')}="
            f"{urllib.parse.quote(v, safe='-_.~')}"
            for k, v in sorted(query)
        )
        url = yarl.URL(self._endpoint + uri + (f"?{qs}" if qs else ""),
                       encoded=True)
        tmo = (self.config.timeout.io_timeout if io else self.config.timeout.timeout)
        # explicit connect/read timeouts alongside the total: a black-holed
        # endpoint (dropped SYNs) or a mid-body stall fails within its own
        # bound instead of riding the full total on every attempt
        req_timeout = aiohttp.ClientTimeout(
            total=tmo.seconds,
            connect=self.config.timeout.connect_timeout.seconds,
            sock_read=self.config.timeout.read_timeout.seconds,
        )
        attempts = max(1, self.config.max_retries)
        last: str = ""
        for attempt in range(attempts):
            headers = self._headers(method, uri, query, payload)
            if extra_headers:
                headers = {**headers, **extra_headers}
            try:
                async with session.request(
                    method,
                    url,
                    data=payload,
                    headers=headers,
                    timeout=req_timeout,
                ) as resp:
                    body = await resp.read()
                    got = (
                        {n: resp.headers.get(n, "") for n in header_names}
                        if header_names else None
                    )
                    if resp.status in allow_statuses:
                        return ((resp.status, body, 0, got)
                                if got is not None else (resp.status, body, 0))
                    if resp.status == 404:
                        raise NotFound(f"object not found: {key}")
                    if resp.status in (429,) or resp.status >= 500:
                        last = f"HTTP {resp.status}: {body[:200]!r}"
                    elif resp.status >= 400:
                        raise S3Error(
                            f"{method} {key}: HTTP {resp.status}: {body[:500]!r}"
                        )
                    else:
                        clen = int(resp.headers.get("Content-Length", len(body)))
                        return ((resp.status, body, clen, got)
                                if got is not None
                                else (resp.status, body, clen))
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                last = f"{type(e).__name__}: {e}"
            if attempt + 1 < attempts:
                await asyncio.sleep(min(0.05 * (2 ** attempt), 2.0))
        raise S3RetriesExhausted(
            f"{method} {key}: retries exhausted ({attempts}): {last}"
        )

    # -- the five verbs -----------------------------------------------------

    async def put(self, path: str, data: bytes) -> None:
        await self._request("PUT", self._key(path), payload=bytes(data), io=True)

    async def put_if_absent(self, path: str, data: bytes) -> None:
        # S3 conditional write (supported by AWS since 2024-08 and by the
        # compatible stores this client targets): If-None-Match: * makes the
        # PUT fail with 412 when the key exists. 409 also maps (some stores
        # answer ConditionalRequestConflict for concurrent conditional PUTs
        # racing on one key — for the caller both mean "lost the race").
        # A store that silently IGNORES the condition (older MinIO/clones
        # answer 200 for existing keys) breaks every caller that relies on
        # exactly-one-winner semantics; verify_conditional_puts() probes
        # for that before fencing trusts this verb (ADVICE r5).
        status, _, _ = await self._request(
            "PUT", self._key(path), payload=bytes(data), io=True,
            extra_headers={"If-None-Match": "*"}, allow_statuses=(409, 412),
        )
        if status in (409, 412):
            raise PreconditionFailed(f"object exists: {path}")

    async def verify_conditional_puts(self, prefix: str) -> None:
        """Capability probe: prove the endpoint actually enforces
        `If-None-Match: *` before anything (epoch fencing) stakes
        correctness on it. Two conditional PUTs of one sentinel key —
        the second (or, when another process probed first, the first)
        MUST come back PreconditionFailed; a store that answers 200 for
        an existing key silently degrades fencing to no protection, so
        that is a loud boot-time failure, not a latent split-brain.
        Runs once per store instance; the sentinel stays behind as a
        capability-audit marker (and fast-paths later probes)."""
        if self._cond_put_verified:
            return
        key = f"{prefix.rstrip('/')}/.cond-put-probe"
        try:
            await self.put_if_absent(key, b"conditional-put capability probe")
        except PreconditionFailed:
            # an earlier probe's sentinel rejected us: condition enforced
            self._cond_put_verified = True
            return
        try:
            await self.put_if_absent(key, b"conditional-put capability probe")
        except PreconditionFailed:
            self._cond_put_verified = True
            return
        raise HoraeError(
            f"object store at {self._endpoint!r} ignores conditional PUTs "
            f"(If-None-Match: * on existing key {key!r} returned success); "
            "epoch fencing cannot provide single-writer protection on this "
            "store — upgrade the store or disable fencing (node_id)"
        )

    async def get(self, path: str) -> bytes:
        _, body, _ = await self._request("GET", self._key(path), io=True)
        return body

    async def get_if_changed(
        self, path: str, etag: "str | None"
    ) -> "tuple[bytes | None, str]":
        """Real conditional GET: `If-None-Match: <etag>` answers 304 with
        no body when the object is unchanged — the watch-loop probe costs
        a round-trip, never a transfer. The same fence-probe machinery
        pattern as put_if_absent: the condition rides unsigned extra
        headers through the signed request path. Stores that ignore the
        condition (200 + full body on a match) degrade gracefully: the
        returned ETag compare below restores the unchanged verdict, only
        the transfer economy is lost."""
        extra = {"If-None-Match": etag} if etag else None
        status, body, _clen, hdrs = await self._request(
            "GET", self._key(path), io=True, extra_headers=extra,
            allow_statuses=(304,), header_names=("ETag",),
        )
        new = hdrs.get("ETag", "") or ""
        if status == 304:
            return None, etag or new
        if not new:
            # no ETag from this endpoint: fall back to a content digest
            # so the caller's change detection stays sound
            new = "d:" + hashlib.blake2b(body, digest_size=16).hexdigest()
        if etag is not None and new == etag:
            return None, etag
        return body, new

    async def head(self, path: str) -> ObjectMeta:
        _, _, clen = await self._request("HEAD", self._key(path))
        return ObjectMeta(path=path, size=clen)

    async def delete(self, path: str) -> None:
        # HEAD first: the engine contract raises NotFound for absent keys,
        # S3's DELETE alone cannot tell (idempotent 204)
        await self._request("HEAD", self._key(path))
        await self._request("DELETE", self._key(path))

    async def list(self, prefix: str) -> list[ObjectMeta]:
        want = self._key(prefix.rstrip("/") + "/" if prefix else "")
        base_uri = "/" + urllib.parse.quote(self.config.bucket, safe="-_.~")
        strip = len(self._prefix) + 1 if self._prefix else 0
        out: list[ObjectMeta] = []
        token: str | None = None
        while True:
            query = [("list-type", "2"), ("prefix", want)]
            if token:
                query.append(("continuation-token", token))
            _, body, _ = await self._request(
                "GET", f"list:{want}", query=query, uri=base_uri
            )
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            for item in root.iter(f"{ns}Contents"):
                k = item.find(f"{ns}Key").text or ""
                size = int(item.find(f"{ns}Size").text or 0)
                out.append(ObjectMeta(path=k[strip:], size=size))
            trunc = root.find(f"{ns}IsTruncated")
            if trunc is not None and (trunc.text or "").lower() == "true":
                tok = root.find(f"{ns}NextContinuationToken")
                token = tok.text if tok is not None else None
                if not token:
                    break
            else:
                break
        out.sort(key=lambda m: m.path)
        return out
