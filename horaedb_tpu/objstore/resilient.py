"""ResilientStore: the hardened boundary around every object-store call.

In the HoraeDB v2 design the shared object store IS the distributed data
plane (PAPER §0) — which makes every naked `store.get()`/`put()` a
single point of failure for a flush, a compaction, or a query scan. This
module wraps any ObjectStore with the fault-tolerance contract the rest
of the tree builds on:

- **Classified retries.** Every attempt's failure runs through the error
  taxonomy (common/error.py): `retryable` faults retry with capped
  exponential backoff and FULL jitter (sleep ~ U(0, min(cap, base*2^n)),
  the AWS-recommended variant — synchronized retry storms from many
  clients decorrelate); `persistent` and `fatal` faults surface
  immediately. Semantic results (NotFound, PreconditionFailed) are part
  of the store contract, not failures — they pass through untouched and
  count as successes.
- **Per-attempt deadlines.** Each attempt runs under
  `asyncio.wait_for(op, op_deadline)`: a black-holed endpoint costs a
  bounded timeout, not a hung flush worker. Ops issued on behalf of a
  request additionally respect the request's end-to-end deadline
  (common/deadline.py): each attempt is capped at the remaining budget
  and the ladder stops — `DeadlineExceeded`, the HTTP 504 — once the
  budget cannot cover another attempt, so retries/backoff never outlive
  the query that asked. Background work (no deadline installed) keeps
  the configured ladder unchanged.
- **A circuit breaker per store.** `failure_threshold` consecutive
  gave-ups open the breaker; while open every call fails fast with
  `UnavailableError` (carrying a Retry-After hint) instead of burning a
  full retry ladder against a dead backend. After `open_s` the breaker
  half-opens and admits one probe; success closes it, failure re-opens.
- **Observability.** `horaedb_objstore_attempts_total{op,result}`,
  `horaedb_objstore_retries_total{op}`, `horaedb_objstore_gave_up_total
  {op}`, and `horaedb_objstore_breaker_state{store}` render on /metrics,
  and every retry backoff is a span (`objstore_retry`) on the active
  trace, so a retry storm is visible in /debug/traces with the op, the
  attempt number, and the error that caused it.

`put_stream` is deliberately NOT retried per-attempt: its chunk iterator
is consumed by the first attempt, and buffering it would defeat the
streaming memory bound. It still gets the breaker, the classification,
and the metrics; replay of failed streams belongs to the layer that owns
the bytes (the flush executor's park/replay machinery).

Deployment shape: the server wraps its store once at boot
(server/main.py), so engine flush, manifest, fence, compaction, and scan
reads all inherit the policy without knowing it exists. jaxlint J009
enforces the boundary: concrete stores are constructed inside objstore/
or handed straight to a ResilientStore.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from horaedb_tpu.common import deadline as deadline_ctx
from horaedb_tpu.common import tracing
from horaedb_tpu.common.error import (
    DeadlineExceeded,
    HoraeError,
    UnavailableError,
    classify,
)
from horaedb_tpu.common.time_ext import ReadableDuration
from horaedb_tpu.objstore import ObjectMeta, ObjectStore
from horaedb_tpu.server.metrics import GLOBAL_METRICS

OBJSTORE_ATTEMPTS = GLOBAL_METRICS.counter(
    "horaedb_objstore_attempts_total",
    help="Object-store attempts through the resilience layer, by verb and "
         "outcome (ok | retryable | persistent | fatal | breaker_open).",
    labelnames=("op", "result"),
)
OBJSTORE_RETRIES = GLOBAL_METRICS.counter(
    "horaedb_objstore_retries_total",
    help="Backoff retries issued after a retryable object-store failure.",
    labelnames=("op",),
)
OBJSTORE_GAVE_UP = GLOBAL_METRICS.counter(
    "horaedb_objstore_gave_up_total",
    help="Object-store ops that exhausted their retry budget (the failure "
         "surfaced to the caller as UnavailableError).",
    labelnames=("op",),
)
OBJSTORE_BREAKER_STATE = GLOBAL_METRICS.gauge(
    "horaedb_objstore_breaker_state",
    help="Circuit breaker state per store: 0 closed, 1 half-open, 2 open.",
    labelnames=("store",),
)

OPS = ("put", "put_if_absent", "put_stream", "get", "list", "delete", "head")


@dataclass
class RetryPolicy:
    """Retry/backoff/deadline knobs ([metric_engine.storage.object_store.
    resilience] in the server config)."""

    max_attempts: int = 4
    backoff_base: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.millis(50)
    )
    backoff_cap: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(2)
    )
    # per-ATTEMPT deadline: a black-holed endpoint costs this much, not a
    # hung worker (the S3 client's own timeouts usually fire first; this
    # is the backstop for stores without native timeouts)
    op_deadline: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(30)
    )


@dataclass
class BreakerPolicy:
    """Circuit-breaker knobs (same config table as RetryPolicy)."""

    # consecutive gave-up ops (full retry ladders, not single attempts)
    # that open the breaker; 0 disables the breaker entirely
    failure_threshold: int = 5
    # how long the breaker stays open before half-opening one probe
    open_for: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(10)
    )


class CircuitBreaker:
    """Per-store breaker: closed -> (threshold gave-ups) -> open ->
    (open_for elapsed) -> half-open probe -> closed | open.

    Event-loop-confined like the rest of the store plumbing — no locks.
    `clock` is injectable so tests drive state transitions without
    sleeping."""

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"

    def __init__(self, policy: BreakerPolicy, name: str = "objstore",
                 clock=time.monotonic):
        self._policy = policy
        self._clock = clock
        self._name = name
        self._failures = 0
        self._opened_at: float | None = None
        self._probing = False
        self._gauge = OBJSTORE_BREAKER_STATE.labels(name)
        self._gauge.set(0)

    @property
    def state(self) -> str:
        if self._opened_at is None:
            return self.CLOSED
        if self._clock() - self._opened_at >= self._policy.open_for.seconds:
            return self.HALF_OPEN
        return self.OPEN

    def _set_gauge(self) -> None:
        self._gauge.set(
            {self.CLOSED: 0, self.HALF_OPEN: 1, self.OPEN: 2}[self.state]
        )

    def retry_after_s(self) -> float:
        if self._opened_at is None:
            return 0.0
        return max(
            0.0,
            self._policy.open_for.seconds - (self._clock() - self._opened_at),
        )

    def admit(self) -> bool:
        """May an op proceed? OPEN rejects; HALF_OPEN admits one probe at
        a time (concurrent callers fail fast while the probe is out)."""
        st = self.state
        if st == self.CLOSED:
            return True
        if st == self.HALF_OPEN and not self._probing:
            self._probing = True
            self._set_gauge()
            return True
        self._set_gauge()
        return False

    def on_success(self) -> None:
        self._failures = 0
        self._opened_at = None
        self._probing = False
        self._set_gauge()

    def on_gave_up(self) -> None:
        """One op exhausted its whole retry ladder (or a half-open probe
        failed): count toward — or re-arm — the open state."""
        self._probing = False
        if self._policy.failure_threshold <= 0:
            return  # breaker disabled
        self._failures += 1
        if self._opened_at is not None or (
            self._failures >= self._policy.failure_threshold
        ):
            self._opened_at = self._clock()
        self._set_gauge()

    def on_probe_aborted(self) -> None:
        """An admitted op ended without a verdict (cancelled mid-flight):
        release the half-open probe slot WITHOUT moving state, so the
        next caller can probe — a leaked slot would lock the breaker
        open forever."""
        self._probing = False
        self._set_gauge()

    def force_open(self) -> None:
        """Trip the breaker now (admin/test hook; smoke gates use it to
        prove the 503 shedding path without a dead backend)."""
        self._failures = max(self._failures, self._policy.failure_threshold)
        self._opened_at = self._clock()
        self._set_gauge()

    def reset(self) -> None:
        self.on_success()


class ResilientStore(ObjectStore):
    """ObjectStore wrapper implementing the module-docstring contract.

    `rng` is injectable (tests pin jitter); `clock` feeds the breaker."""

    def __init__(
        self,
        inner: ObjectStore,
        retry: RetryPolicy | None = None,
        breaker: BreakerPolicy | None = None,
        name: str = "objstore",
        rng: random.Random | None = None,
        clock=time.monotonic,
    ) -> None:
        self._inner = inner
        self._retry = retry or RetryPolicy()
        self.breaker = CircuitBreaker(breaker or BreakerPolicy(), name=name,
                                      clock=clock)
        self._rng = rng or random.Random()
        self._name = name
        # pre-register every (op, result=ok) child so /metrics shows the
        # families' zero state from boot (the PR2 convention)
        for op in OPS:
            OBJSTORE_ATTEMPTS.labels(op, "ok")
            OBJSTORE_RETRIES.labels(op)
            OBJSTORE_GAVE_UP.labels(op)

    @property
    def inner(self) -> ObjectStore:
        return self._inner

    # -- the retry core ------------------------------------------------------

    def _backoff_s(self, attempt: int) -> float:
        """Capped exponential with FULL jitter: U(0, min(cap, base*2^n))."""
        cap = self._retry.backoff_cap.seconds
        base = self._retry.backoff_base.seconds
        return self._rng.uniform(0.0, min(cap, base * (2 ** attempt)))

    def _check_admit(self, op: str) -> None:
        if not self.breaker.admit():
            OBJSTORE_ATTEMPTS.labels(op, "breaker_open").inc()
            retry_after = self.breaker.retry_after_s()
            raise UnavailableError(
                f"object store unavailable (circuit breaker open, "
                f"store={self._name}, op={op}); failing fast",
                retry_after_s=retry_after,
            )

    async def _call(self, op: str, fn, *args):
        """One resilient op: admit -> bounded attempts -> classified
        surface. `fn` is the inner-store coroutine function.

        Every admitted call reaches exactly one breaker verdict —
        on_success (returned, semantic result, or a deterministic
        rejection that proves the backend is up), on_gave_up (budget
        exhausted), or on_probe_aborted (cancelled mid-flight). A leaked
        half-open probe slot would lock the breaker open forever."""
        self._check_admit(op)
        try:
            return await self._attempt_loop(op, fn, args)
        except asyncio.CancelledError:
            self.breaker.on_probe_aborted()
            raise
        except DeadlineExceeded:
            # the CALLER's budget died mid-ladder: no availability verdict
            # either way — release a half-open probe slot without moving
            # breaker state (same contract as a cancellation)
            self.breaker.on_probe_aborted()
            raise

    def _raise_budget_spent(self, op: str, attempt: int,
                            last: BaseException | None) -> None:
        """The query deadline (common/deadline.py) cannot cover another
        attempt: stop the ladder NOW, typed. An op issued on behalf of a
        request must never outlive the request — a black-holed store
        under a 1 s query deadline costs ~1 s, not the full ladder."""
        d = deadline_ctx.current()
        raise DeadlineExceeded(
            f"{op} abandoned after {attempt} attempt(s): query deadline "
            f"exceeded (store={self._name})",
            cause=last,
            budget_s=d.budget_s if d else None,
            elapsed_s=d.elapsed_s() if d else None,
            at=f"objstore_{op}",
        )

    async def _attempt_loop(self, op: str, fn, args):
        deadline = self._retry.op_deadline.seconds
        attempts = max(1, self._retry.max_attempts)
        last: BaseException | None = None
        for attempt in range(attempts):
            # per-attempt timeout = min(op_deadline, the driving query's
            # remaining budget); background work (no deadline contextvar)
            # keeps the configured op_deadline unchanged
            rem = deadline_ctx.remaining_s()
            timeout = deadline
            if rem is not None:
                if rem <= 0.0:
                    self._raise_budget_spent(op, attempt, last)
                timeout = min(deadline, rem)
            try:
                result = await asyncio.wait_for(fn(*args), timeout=timeout)
            except HoraeError as e:
                from horaedb_tpu.objstore import NotFound, PreconditionFailed

                if isinstance(e, (NotFound, PreconditionFailed)):
                    # semantic contract results, not faults
                    OBJSTORE_ATTEMPTS.labels(op, "ok").inc()
                    self.breaker.on_success()
                    raise
                last = e
            except Exception as e:  # noqa: BLE001 — classified below
                # (CancelledError is BaseException: handled by _call)
                last = e
            else:
                OBJSTORE_ATTEMPTS.labels(op, "ok").inc()
                self.breaker.on_success()
                return result
            cls = classify(last)
            OBJSTORE_ATTEMPTS.labels(op, cls).inc()
            if cls in ("fatal", "persistent"):
                # deterministic / process-level: surface now. The backend
                # RESPONDED, so availability-wise this is a success — it
                # must not poison the breaker, and above all it must
                # release a half-open probe slot (a 4xx during recovery
                # would otherwise brick the breaker open forever)
                self.breaker.on_success()
                raise last
            if attempt + 1 < attempts:
                # retrying (or even just backing off) past the caller's
                # remaining budget is work nobody will read: stop typed
                rem = deadline_ctx.remaining_s()
                if rem is not None and rem <= 0.0:
                    self._raise_budget_spent(op, attempt + 1, last)
                OBJSTORE_RETRIES.labels(op).inc()
                backoff = self._backoff_s(attempt)
                if rem is not None:
                    backoff = min(backoff, max(rem, 0.0))
                # the retry is a SPAN wrapping its backoff sleep, so a slow
                # traced request shows exactly where its latency went
                with tracing.span(
                    "objstore_retry", op=op, attempt=attempt + 1,
                    backoff_ms=round(backoff * 1000, 1),
                    error=str(last)[:200],
                ):
                    if backoff > 0:
                        await asyncio.sleep(backoff)
        OBJSTORE_GAVE_UP.labels(op).inc()
        self.breaker.on_gave_up()
        raise UnavailableError(
            f"{op} gave up after {attempts} attempts (store={self._name})",
            cause=last,
            retry_after_s=self.breaker.retry_after_s() or None,
        )

    # -- the five verbs (+ conditional put + stream) -------------------------

    async def put(self, path: str, data: bytes) -> None:
        await self._call("put", self._inner.put, path, data)

    async def put_if_absent(self, path: str, data: bytes) -> None:
        # Retrying a conditional put is safe in this tree: the inner stores
        # answer synchronously (no lost-ack window), and a retry that finds
        # its own previous attempt's object raises PreconditionFailed —
        # which for every caller (epoch fencing) means "lost the race",
        # the correct conservative answer.
        await self._call("put_if_absent", self._inner.put_if_absent, path, data)

    async def get(self, path: str) -> bytes:
        return await self._call("get", self._inner.get, path)

    async def get_if_changed(self, path: str, etag):
        """Conditional GET rides the `get` verb's retry/breaker/metrics
        (it IS a get, economized); an "unchanged" answer counts as a
        success like the other semantic results."""
        return await self._call("get", self._inner.get_if_changed, path, etag)

    async def list(self, prefix: str) -> list[ObjectMeta]:
        return await self._call("list", self._inner.list, prefix)

    async def delete(self, path: str) -> None:
        await self._call("delete", self._inner.delete, path)

    async def head(self, path: str) -> ObjectMeta:
        return await self._call("head", self._inner.head, path)

    async def put_stream(self, path: str, chunks) -> int:
        """Breaker + classification + metrics, but NO per-attempt retry:
        the chunk iterator is consumed by the first attempt (see module
        docstring). No wait_for either — a large stream legitimately
        outlives the per-attempt deadline; the inner transport owns its
        own IO timeouts."""
        self._check_admit("put_stream")
        try:
            n = await self._inner.put_stream(path, chunks)
        except asyncio.CancelledError:
            self.breaker.on_probe_aborted()  # no verdict: free the slot
            raise
        except Exception as e:  # noqa: BLE001 — classified below
            cls = classify(e)
            OBJSTORE_ATTEMPTS.labels("put_stream", cls).inc()
            if cls == "retryable":
                OBJSTORE_GAVE_UP.labels("put_stream").inc()
                self.breaker.on_gave_up()
                raise UnavailableError(
                    f"put_stream failed (store={self._name})", cause=e,
                    retry_after_s=self.breaker.retry_after_s() or None,
                )
            # deterministic/fatal: the backend responded — availability-
            # wise a success (and the half-open probe slot must free)
            self.breaker.on_success()
            raise
        OBJSTORE_ATTEMPTS.labels("put_stream", "ok").inc()
        self.breaker.on_success()
        return n

    # -- pass-throughs -------------------------------------------------------

    async def verify_conditional_puts(self, prefix: str) -> None:
        await self._inner.verify_conditional_puts(prefix)

    def local_path(self, path: str) -> str | None:
        return self._inner.local_path(path)

    async def close(self) -> None:
        closer = getattr(self._inner, "close", None)
        if closer is not None:
            await closer()
