"""SLO burn-rate templates: declarative `[[metric_engine.slo]]` blocks
expanded into PR 11 recording + alert rules.

The multi-window multi-burn-rate pattern (Google SRE workbook ch. 5): an
SLO names an `errors` counter and a `total` counter (instant selectors
over the SELF-SCRAPED `horaedb_*` series telemetry/collector.py
materializes); each configured burn pair (short window, long window,
burn factor) expands into

- one recording rule per distinct window:
      slo:<name>:error_ratio_<w> =
          sum(rate(<errors>[w])) / sum(rate(<total>[w]))
  (materialized as first-class series — dashboards plot the error ratio
  directly, and the alert reads the MATERIALIZED series, so a burn-rate
  evaluation costs two index lookups, not two raw scans);

- one alert rule per pair:
      (short_ratio > factor * budget) and (long_ratio > factor * budget)
  where budget = 1 - objective. The short window makes the alert fast to
  fire AND fast to resolve; the long window keeps a brief spike from
  paging; the factor scales threshold to how fast the error budget is
  actually burning.

The expansion produces plain rule dicts for rules.rule_from_dict — the
rules engine owns registration, durability, exactly-once transitions,
and the admission tenant; this module is pure template math. Expansion
is deterministic, so boot-time re-registration is idempotent (an
unchanged SLO keeps its rules' watermarks and alert states).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.time_ext import ReadableDuration

__all__ = ["SloSpec", "BurnWindow", "expand_slo", "expand_slos"]

# the workbook's canonical pairs: page on a fast burn, ticket on a slow one
DEFAULT_BURN = (("5m", "1h", 14.4), ("30m", "6h", 6.0))

_NAME_SAFE_RE = re.compile(r"[^a-zA-Z0-9_:]")
_METRIC_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")


def _safe(name: str) -> str:
    return _NAME_SAFE_RE.sub("_", str(name))


def _num(x: float) -> str:
    """Positional decimal for PromQL exprs (no scientific notation)."""
    s = f"{x:.12f}".rstrip("0").rstrip(".")
    return s or "0"


def _dur_str(v) -> str:
    """Normalize a duration to the string spelled in rule names/exprs
    (validates it parses; "5m" stays "5m")."""
    ReadableDuration.parse(v if isinstance(v, str) else str(v))
    return str(v)


def _instant_selector(expr: str, what: str) -> str:
    """The errors/total fields must be INSTANT selectors (the template
    appends the burn windows itself)."""
    from horaedb_tpu.promql import Selector, parse

    node = parse(str(expr))
    ensure(
        isinstance(node, Selector) and node.range_ms is None
        and node.offset_ms == 0,
        f"slo {what} must be an instant selector (the template appends "
        f"[window] itself), got {expr!r}",
    )
    return str(expr)


def _burn_entry(b, slo: str) -> tuple:
    """Normalize one burn entry — `{short, long, factor}` table or
    `[short, long, factor]` array — to (str, str, float) with a CONFIG
    error on any malformed shape (a raw TypeError at boot names no
    knob)."""
    if isinstance(b, dict):
        unknown = set(b) - {"short", "long", "factor"}
        ensure(not unknown,
               f"slo {slo}: unknown burn keys {sorted(unknown)}")
        missing = [k for k in ("short", "long", "factor") if b.get(k) is None]
        ensure(not missing,
               f"slo {slo}: burn entry missing {missing} "
               f"(str(None) would otherwise fail later as a duration "
               f"naming no knob)")
        vals = (b["short"], b["long"], b["factor"])
    else:
        ensure(isinstance(b, (list, tuple)) and len(b) == 3,
               f"slo {slo}: burn entry must be a {{short, long, factor}} "
               f"table or a 3-element array, got {b!r}")
        vals = tuple(b)
    try:
        return (str(vals[0]), str(vals[1]), float(vals[2]))
    except (TypeError, ValueError):
        ensure(False,
               f"slo {slo}: burn entry needs short/long durations and a "
               f"numeric factor, got {b!r}")


@dataclass(frozen=True)
class BurnWindow:
    short: str
    long: str
    factor: float

    def validate(self, slo: str) -> "BurnWindow":
        s = ReadableDuration.parse(self.short).as_millis()
        lo = ReadableDuration.parse(self.long).as_millis()
        ensure(s > 0 and lo > s,
               f"slo {slo}: burn window must have short < long "
               f"({self.short!r} vs {self.long!r})")
        ensure(self.factor > 0,
               f"slo {slo}: burn factor must be > 0")
        return self


@dataclass(frozen=True)
class SloSpec:
    """One `[[metric_engine.slo]]` block (validated)."""

    name: str
    objective: float            # good fraction, e.g. 0.999
    errors: str                 # instant selector: the bad-event counter
    total: str                  # instant selector: the all-event counter
    interval: str = "1m"        # recording-rule grid
    for_duration: str = "0s"    # alert for-duration (config key: `for`)
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    burn: tuple = DEFAULT_BURN

    @classmethod
    def from_dict(cls, d: dict) -> "SloSpec":
        ensure(isinstance(d, dict), "slo entry must be a table")
        known = {"name", "objective", "errors", "total", "interval",
                 "for", "labels", "annotations", "burn"}
        unknown = set(d) - known
        ensure(not unknown, f"unknown slo keys: {sorted(unknown)}")
        for req in ("name", "objective", "errors", "total"):
            ensure(req in d, f"slo needs {req!r}")
        burn = d.get("burn")
        if burn:
            pairs = tuple(_burn_entry(b, str(d["name"])) for b in burn)
        else:
            pairs = DEFAULT_BURN
        return cls(
            name=str(d["name"]),
            objective=float(d["objective"]),
            errors=str(d["errors"]),
            total=str(d["total"]),
            interval=str(d.get("interval", "1m")),
            for_duration=str(d.get("for", "0s")),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            burn=pairs,
        ).validate()

    def validate(self) -> "SloSpec":
        ensure(bool(_METRIC_RE.match(_safe(self.name))),
               f"invalid slo name {self.name!r}")
        ensure(0.0 < self.objective < 1.0,
               f"slo {self.name}: objective must be in (0, 1), got "
               f"{self.objective}")
        _instant_selector(self.errors, f"{self.name}.errors")
        _instant_selector(self.total, f"{self.name}.total")
        _dur_str(self.interval)
        _dur_str(self.for_duration)
        ensure(len(self.burn) > 0, f"slo {self.name}: needs >=1 burn pair")
        for b in self.burn:
            BurnWindow(*b).validate(self.name)
        return self

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def windows(self) -> list[str]:
        seen: list[str] = []
        for short, long_, _f in self.burn:
            for w in (short, long_):
                if w not in seen:
                    seen.append(w)
        return seen

    def ratio_metric(self, window: str) -> str:
        return f"slo:{_safe(self.name)}:error_ratio_{_safe(window)}"

    def alert_name(self, short: str, long_: str) -> str:
        return (f"SLOBurn_{_safe(self.name)}_{_safe(short)}_"
                f"{_safe(long_)}")


def expand_slo(spec: SloSpec) -> list[dict]:
    """One validated spec -> rule dicts (recording first: the alerts
    read the materialized ratio series)."""
    out: list[dict] = []
    for w in spec.windows():
        out.append({
            "kind": "recording",
            "name": spec.ratio_metric(w),
            "expr": (f"sum(rate({spec.errors}[{w}])) / "
                     f"sum(rate({spec.total}[{w}]))"),
            "interval": spec.interval,
            "labels": {"slo": _safe(spec.name)},
        })
    for short, long_, factor in spec.burn:
        # decimal-positional formatting: the PromQL tokenizer's NUMBER
        # grammar has no scientific notation, and repr(1.44e-05) would
        # emit exactly that
        threshold = _num(float(factor) * spec.budget)
        out.append({
            "kind": "alert",
            "name": spec.alert_name(short, long_),
            "expr": (f"({spec.ratio_metric(short)} > {threshold}) and "
                     f"({spec.ratio_metric(long_)} > {threshold})"),
            "for": spec.for_duration,
            "labels": {
                "slo": _safe(spec.name),
                "short_window": str(short),
                "long_window": str(long_),
                **{str(k): str(v) for k, v in spec.labels.items()},
            },
            "annotations": {
                "summary": (
                    f"SLO {spec.name} burning error budget at >"
                    f"{factor}x (objective {spec.objective:g}; error "
                    f"ratio above {threshold} over both {short} and "
                    f"{long_})"
                ),
                "runbook": "docs/operations.md#self-telemetry--slos",
                **{str(k): str(v) for k, v in spec.annotations.items()},
            },
        })
    return out


def expand_slos(raw: list) -> list[dict]:
    """Validate + expand every `[[metric_engine.slo]]` block; duplicate
    SLO names reject loudly (their rules would silently overwrite each
    other by name)."""
    seen: set[str] = set()
    out: list[dict] = []
    for entry in raw or ():
        spec = entry if isinstance(entry, SloSpec) else \
            SloSpec.from_dict(entry)
        key = _safe(spec.name)
        ensure(key not in seen, f"duplicate slo name {spec.name!r}")
        seen.add(key)
        out.extend(expand_slo(spec))
    return out
