"""Self-telemetry pipeline: the engine as its own long-term metric store.

Three legs (ISSUE 12 / ROADMAP item 4 follow-on):

- **telemetry/collector.py** — the self-scrape loop: the typed metric
  registry snapshotted straight into the normal ingest path under the
  low-weight `_system` tenant, so every `horaedb_*` family becomes
  PromQL-queryable history that survives restarts;
- **telemetry/metering.py** — the per-tenant usage funnel (jaxlint J015):
  rows ingested, samples rejected, bytes scanned, queue-wait seconds,
  sheds and deadline hits per tenant, exported as `horaedb_tenant_*`
  families and served at `GET /api/v1/usage`;
- **telemetry/slo.py** — declarative `[[metric_engine.slo]]` burn-rate
  templates expanded into PR 11 recording + alert rules over the
  self-scraped series.

Importing this package also wires the OpenMetrics exemplar source:
exemplar-enabled latency histograms (route latency, scan stages, flush
stages) stamp the active trace id onto their observations, and the
OpenMetrics exposition (`Accept: application/openmetrics-text` on
/metrics) renders them as `# {trace_id="..."}` — any metric spike links
straight to its `/debug/traces/{id}` span tree. The hook is injected
here rather than imported by server/metrics.py because that module must
stay dependency-free (storage/ and parallel/ import it).

Kill switch: `HORAEDB_TELEMETRY=off` (env) disables the self-scrape loop
regardless of config — the honesty-switch convention (HORAEDB_SERVING)
for A/B-ing the monitor's own overhead.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from horaedb_tpu.common import tracing as _tracing
from horaedb_tpu.common.time_ext import ReadableDuration
from horaedb_tpu.server import metrics as _metrics
from horaedb_tpu.telemetry.collector import SelfScrapeCollector
from horaedb_tpu.telemetry.metering import FIELDS, GLOBAL_METER, UsageMeter
from horaedb_tpu.telemetry.slo import SloSpec, expand_slo, expand_slos

__all__ = [
    "TelemetryConfig", "FederationConfig", "SelfScrapeCollector",
    "UsageMeter", "GLOBAL_METER", "FIELDS", "SloSpec", "expand_slo",
    "expand_slos", "telemetry_enabled",
]

# the exemplar wiring (module docstring): one injection, process-wide
_metrics.set_exemplar_source(_tracing.current_trace_id)


def telemetry_enabled(config_enabled: bool = True) -> bool:
    """Config AND the HORAEDB_TELEMETRY env kill switch (off/0/false/no
    disables; anything else — including unset — defers to config)."""
    env = os.environ.get("HORAEDB_TELEMETRY", "").strip().lower()
    if env in ("off", "0", "false", "no"):
        return False
    return bool(config_enabled)


@dataclass
class FederationConfig:
    """`[metric_engine.telemetry.federation]` — fleet telemetry pulls.

    With `enabled = true` on a node that runs the collector AND the
    cluster layer, each federation sweep pulls every healthy peer's
    registry snapshot (`GET /api/v1/telemetry/snapshot`, through the
    router's traced client funnel) and writes it into the local
    `_system` tenant with an `instance = "<peer node>"` label — one
    node's PromQL sees the whole fleet's `horaedb_*` history. Budgeted
    separately from the self-scrape (`max_series` below) so a noisy
    peer can never starve local self-observability."""

    enabled: bool = False
    # peer-pull spacing; independent of the self-scrape interval (a
    # forced POST /api/v1/telemetry/scrape also forces a sweep)
    scrape_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(30)
    )
    # per-request timeout for one peer snapshot pull
    timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(5)
    )
    # fleet-wide budget: distinct federated series (across ALL peers)
    # this collector may create; existing series keep flowing at the cap
    max_series: int = 16384
    # family-name prefixes to skip in PEER snapshots (on top of the
    # collector's own exclude list)
    exclude: list = field(default_factory=list)


@dataclass
class TelemetryConfig:
    """`[metric_engine.telemetry]` — the self-scrape loop's knobs."""

    enabled: bool = True
    # scrape spacing; each tick writes one sample per registry series
    scrape_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(15)
    )
    # accounting + admission identity of the loop's writes
    tenant: str = "_system"
    tenant_weight: float = 0.25
    # instance label stamped on every self-written series (the
    # Prometheus self-scrape idiom); the retention sweep deletes ONLY
    # series carrying it — give each engine feeding a shared store a
    # distinct value
    instance: str = "self"
    # feedback-safety budget: distinct self-written series the collector
    # may create (existing series keep flowing at the cap)
    max_series: int = 8192
    # family-name prefixes to skip entirely
    exclude: list = field(default_factory=list)
    # self-series horizon (tombstone sweep); None/0s keeps forever
    retention: ReadableDuration | None = None
    # fleet federation: pull peers' registry snapshots into `_system`
    federation: FederationConfig = field(default_factory=FederationConfig)

    @classmethod
    def from_dict(cls, d: dict | None) -> "TelemetryConfig":
        from horaedb_tpu.storage.config import _from_dict

        return _from_dict(cls, d)

    def retention_ms(self) -> int | None:
        if self.retention is None:
            return None
        ms = self.retention.as_millis()
        return ms if ms > 0 else None
