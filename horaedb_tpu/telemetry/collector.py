"""Self-scrape collector: the engine ingests its OWN /metrics.

Every observability surface before this PR was trapped in-process — a
/metrics scrape is a point in time, the trace ring is bounded, nothing
survives a restart — yet this is a time-series database. The collector
closes the loop (the Prometheus self-scrape pattern): on an interval it
snapshots the typed metric registry DIRECTLY (no HTTP round-trip, no
text-format parse), converts every family into samples — counters and
gauges as-is, histograms exploded to `_bucket`/`_sum`/`_count` series
with their `le` labels — and writes them through the NORMAL ingest path.
`horaedb_query_shed_total` et al. become first-class series: queryable
by PromQL range queries, cacheable by the serving tier, alertable by the
rules engine (the SLO burn-rate templates in telemetry/slo.py read
nothing else), retained and compacted like any tenant's data.

Feedback safety — a telemetry loop inside its own store must not
amplify itself:

- the snapshot is taken from the registry BEFORE the write, so a tick
  never observes its own ingest side effects (they surface next tick as
  ordinary counter movement — new VALUES on the same series);
- series cardinality is budgeted: the collector tracks every distinct
  (sample name, label set) it has ever emitted and DROPS new series past
  `max_series` (`horaedb_telemetry_dropped_series_total` counts them, a
  one-per-breach log names the first offender) — the registry's label
  sets are bounded by construction, so steady state emits the same
  series every tick and the budget is never touched;
- writes bypass the HTTP handler, so the HTTP families do not move from
  self-scraping (no scrape->counter->scrape spiral);
- the rules engine's self-invalidation guard already ensures an SLO
  rule's own write-back never re-dirties it; the scrape's events dirty
  rules exactly like external ingest (they ARE new data).

Usage is metered like any tenant: rows land under the low-weight
`_system` tenant in the J015 funnel, so the monitor's own cost shows up
in `/api/v1/usage?tenant=_system`.

Retention: self-telemetry is high-churn and rarely worth keeping beyond
the ops horizon; `retention` (config) tombstones self-written series
older than the horizon through the normal delete path on an infrequent
sweep, independent of the table-wide retention knob.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time

from horaedb_tpu.common.time_ext import now_ms as wall_now_ms
from horaedb_tpu.server.metrics import GLOBAL_METRICS
from horaedb_tpu.telemetry.metering import GLOBAL_METER

logger = logging.getLogger(__name__)

__all__ = ["SelfScrapeCollector", "SNAPSHOT_PATH"]

# the wire endpoint a federation sweep pulls from each peer (the JSON
# twin of /metrics: [[sample name, [[label, value]...], value]...])
SNAPSHOT_PATH = "/api/v1/telemetry/snapshot"

TELEMETRY_TICKS = GLOBAL_METRICS.counter(
    "horaedb_telemetry_ticks_total",
    help="Self-scrape ticks by result: ok (snapshot written through the "
         "ingest path), error (write failed; retried next interval).",
    labelnames=("result",),
)
TELEMETRY_SAMPLES = GLOBAL_METRICS.counter(
    "horaedb_telemetry_samples_total",
    help="Samples written by the self-scrape loop (one per registry "
         "sample per tick).",
)
TELEMETRY_SERIES = GLOBAL_METRICS.gauge(
    "horaedb_telemetry_series",
    help="Distinct self-scraped series emitted since boot — bounded by "
         "[metric_engine.telemetry] max_series (the feedback-safety "
         "budget).",
)
TELEMETRY_DROPPED = GLOBAL_METRICS.counter(
    "horaedb_telemetry_dropped_series_total",
    help="Series the self-scrape refused to create because the "
         "max_series budget was exhausted (values on existing series "
         "keep flowing).",
)
TELEMETRY_SCRAPE_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_telemetry_scrape_seconds",
    help="One self-scrape tick wall time (snapshot + payload build + "
         "ingest write).",
)
TELEMETRY_RETENTION_SWEEPS = GLOBAL_METRICS.counter(
    "horaedb_telemetry_retention_sweeps_total",
    help="Self-telemetry retention sweeps (tombstone deletes of "
         "self-series older than the configured horizon).",
)
TELEMETRY_PEER_SCRAPES = GLOBAL_METRICS.counter(
    "horaedb_telemetry_peer_scrapes_total",
    help="Fleet-telemetry federation pulls of peers' registry snapshots, "
         "by peer and result: ok (snapshot written under the peer's "
         "instance label), error (non-200 / malformed snapshot), "
         "unreachable (transport failure).",
    labelnames=("peer", "result"),
)
for _r in ("ok", "error"):
    TELEMETRY_TICKS.labels(_r)
del _r


class SelfScrapeCollector:
    """One collector per engine (module docstring has the contract).

    `clock` returns epoch ms and is injectable for the bit-equality
    property tests; `registry` defaults to the process registry."""

    def __init__(
        self,
        engine,
        registry=GLOBAL_METRICS,
        tenant: str = "_system",
        max_series: int = 8192,
        exclude: tuple = (),
        retention_ms: "int | None" = None,
        instance: str = "self",
        clock=wall_now_ms,
        meter=GLOBAL_METER,
        federation=None,
        router=None,
    ):
        self._engine = engine
        self._registry = registry
        self.tenant = tenant
        self.max_series = max(0, int(max_series))
        self.exclude = tuple(str(p) for p in exclude)
        self.retention_ms = (int(retention_ms)
                             if retention_ms else None)
        # the Prometheus self-scrape idiom: every written series carries
        # instance="<self>" — it marks the series as THIS collector's, so
        # the retention sweep can tombstone its own output without
        # touching same-named series another agent remote-wrote into
        # this engine (the engine-as-shared-metrics-store case)
        self.instance = str(instance)
        self._clock = clock
        self._meter = meter
        self._series: set = set()
        self._budget_logged = False
        # every __name__ ever written (the retention sweep's target list)
        self._written_names: set[str] = set()
        self._last_sweep_ms: int = 0
        self._swept_hi_ms: int = 0
        # fleet federation (telemetry.FederationConfig + the cluster
        # router's traced client funnel); None on single-node deployments
        self._federation = federation
        self._router = router
        self._fed_series: set = set()
        self._fed_budget_logged = False
        self._last_fed_ms: int = 0
        # (__name__, peer node) pairs the sweep tombstones per instance
        self._fed_written: "set[tuple[str, str]]" = set()

    # -- snapshot -> samples --------------------------------------------------
    def snapshot(self) -> tuple[int, list[tuple[str, tuple, float]]]:
        """(family count, [(__name__, label items, value)]) for every
        registry sample that survives the exclusion list — the exact
        values a PromQL query over the written series must return for
        the scrape timestamp."""
        out = []
        families = set()
        for family, _type, sample, key, value in \
                self._registry.snapshot_samples():
            if any(family.startswith(p) for p in self.exclude):
                continue
            families.add(family)
            out.append((sample, key, value))
        return len(families), out

    @staticmethod
    def _admit(samples: list, series: set,
               max_series: int) -> tuple[list, list, int]:
        """The staged-commit series budget, shared by the self-scrape
        and the federation sweep (each against its OWN series set and
        cap): samples on already-known series always pass; new series
        admit only under max_series. New keys are STAGED, not committed
        — the caller commits them only after the engine accepted the
        write, so a failed/degraded write never leaves phantom entries
        consuming the budget."""
        kept, dropped = [], 0
        staged: set = set()
        for name, key, value in samples:
            skey = (name, key)
            if skey not in series and skey not in staged:
                if max_series and len(series) + len(staged) >= max_series:
                    dropped += 1
                    continue
                staged.add(skey)
            kept.append((name, key, value))
        return kept, sorted(staged), dropped

    def _budgeted(self, samples: list) -> tuple[list, list, int]:
        kept, staged, dropped = self._admit(
            samples, self._series, self.max_series
        )
        if dropped:
            TELEMETRY_DROPPED.inc(dropped)
            if not self._budget_logged:
                self._budget_logged = True
                logger.warning(
                    "self-telemetry series budget (%d) exhausted; %d new "
                    "series dropped this tick (existing series keep "
                    "flowing; raise [metric_engine.telemetry] max_series "
                    "or extend the exclude list)",
                    self.max_series, dropped,
                )
        return kept, sorted(staged), dropped

    def _payload(self, samples: list, ts_ms: int) -> bytes:
        from horaedb_tpu.pb import remote_write_pb2

        req = remote_write_pb2.WriteRequest()
        for name, key, value in samples:
            series = req.timeseries.add()
            lab = series.labels.add()
            lab.name = b"__name__"
            lab.value = name.encode()
            if all(k != "instance" for k, _v in key):
                lab = series.labels.add()
                lab.name = b"instance"
                lab.value = self.instance.encode()
            for k, v in key:
                lab = series.labels.add()
                lab.name = str(k).encode()
                lab.value = str(v).encode()
            smp = series.samples.add()
            smp.timestamp = ts_ms
            smp.value = float(value)
        return req.SerializeToString()

    # -- federation (fleet telemetry) -----------------------------------------
    def federation_status(self) -> dict:
        """The /debug/cluster federation row."""
        fed = self._federation
        if fed is None or not fed.enabled or self._router is None:
            return {"enabled": False}
        return {
            "enabled": True,
            "series": len(self._fed_series),
            "max_series": fed.max_series,
            "scrape_interval_s": fed.scrape_interval.seconds,
        }

    def _federation_due(self, now_ms: int, force: bool = False) -> bool:
        fed = self._federation
        if fed is None or not fed.enabled or self._router is None:
            return False
        if force:
            return True
        return now_ms - self._last_fed_ms >= fed.scrape_interval.as_millis()

    def _peer_triples(self, node: str, status: int, body: bytes,
                      exclude: tuple) -> "list | None":
        """Parse one peer's snapshot answer into the (__name__, label
        items, value) triples `_payload` expects — every series relabeled
        `instance=<peer node>` (any instance the peer claimed for itself
        is OVERRIDDEN: the federation's instance axis is the scraper's
        peer table, never a remote string). None = malformed/non-200."""
        if status != 200:
            return None
        try:
            samples = (json.loads(body).get("data") or {}).get("samples")
            triples = []
            for name, key, value in samples:
                name = str(name)
                if any(name.startswith(p) for p in exclude):
                    continue
                items = tuple(sorted(
                    [(str(k), str(v)) for k, v in key
                     if str(k) != "instance"]
                    + [("instance", node)]
                ))
                triples.append((name, items, float(value)))
            return triples
        except (TypeError, ValueError, AttributeError):
            return None

    async def scrape_peers(self, ts_ms: "int | None" = None) -> dict:
        """One federation sweep: pull every healthy peer's registry
        snapshot through the router's traced client funnel and write it
        under instance="<peer>". Per-peer failures are counted and
        skipped — a dead peer degrades the fleet view, never the sweep.
        Returns {peers: {node: ok|error|unreachable}, written, dropped}."""
        from horaedb_tpu.ingest.cardinality import CardinalityLimited

        fed, router = self._federation, self._router
        summary: dict = {"peers": {}, "written": 0, "dropped": 0}
        if fed is None or not fed.enabled or router is None:
            return summary
        import aiohttp

        timeout = aiohttp.ClientTimeout(total=fed.timeout.seconds)
        ts = int(ts_ms if ts_ms is not None else self._clock())
        exclude = self.exclude + tuple(str(p) for p in fed.exclude)
        for node in sorted(router.peers):
            url = router.peer_url(node)
            if url is None or not router.is_healthy(node):
                continue
            try:
                status, _h, out = await router.traced_request(
                    node, "GET", url.rstrip("/") + SNAPSHOT_PATH,
                    kind="telemetry", timeout=timeout,
                )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — unreachable peer
                TELEMETRY_PEER_SCRAPES.labels(node, "unreachable").inc()
                summary["peers"][node] = "unreachable"
                continue
            triples = self._peer_triples(node, status, out, exclude)
            if triples is None:
                TELEMETRY_PEER_SCRAPES.labels(node, "error").inc()
                summary["peers"][node] = "error"
                continue
            kept, staged, dropped = self._admit(
                triples, self._fed_series, fed.max_series
            )
            if dropped:
                TELEMETRY_DROPPED.inc(dropped)
                if not self._fed_budget_logged:
                    self._fed_budget_logged = True
                    logger.warning(
                        "fleet-telemetry series budget (%d) exhausted; "
                        "%d new series from peer %s dropped (existing "
                        "series keep flowing; raise [metric_engine."
                        "telemetry.federation] max_series or extend its "
                        "exclude list)", fed.max_series, dropped, node,
                    )
            written = 0
            try:
                if kept:
                    try:
                        written = await self._engine.write_payload(
                            self._payload(kept, ts)
                        )
                        self._fed_series.update(staged)
                    except CardinalityLimited as e:
                        # same staged-commit contract as the self-scrape
                        written = e.accepted_samples
                        self._meter.account(
                            self.tenant,
                            samples_rejected=e.rejected_samples,
                        )
                    self._meter.account(self.tenant, rows_ingested=written)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — local write failed
                TELEMETRY_PEER_SCRAPES.labels(node, "error").inc()
                summary["peers"][node] = "error"
                logger.warning("federated scrape of peer %s failed to "
                               "land; next sweep retries", node,
                               exc_info=True)
                continue
            for name, _k, _v in kept:
                self._fed_written.add((name, node))
            TELEMETRY_PEER_SCRAPES.labels(node, "ok").inc()
            summary["peers"][node] = "ok"
            summary["written"] += written
            summary["dropped"] += dropped
        return summary

    # -- the tick -------------------------------------------------------------
    async def tick(self, force_federation: bool = False) -> dict:
        """One scrape: snapshot, budget, write, meter. Returns the tick
        summary INCLUDING the written samples (the property tests' and
        smoke gate's bit-equality oracle)."""
        from horaedb_tpu.common import tracing
        from horaedb_tpu.ingest.cardinality import CardinalityLimited

        t0 = time.perf_counter()
        ts_ms = int(self._clock())
        n_families, snap = self.snapshot()
        kept, staged, dropped = self._budgeted(snap)
        summary = {
            "ts_ms": ts_ms,
            "families": n_families,
            "samples": len(kept),
            "series": len(self._series) + len(staged),
            "dropped": dropped,
            "written": 0,
        }
        try:
            with tracing.trace("telemetry_scrape", samples=len(kept)):
                if kept:
                    try:
                        n = await self._engine.write_payload(
                            self._payload(kept, ts_ms)
                        )
                        # clean write: the staged series were really
                        # emitted — commit them against the budget
                        self._series.update(staged)
                    except CardinalityLimited as e:
                        # the ENGINE's cardinality defense also applies
                        # to the monitor itself: in-budget samples
                        # landed, but WHICH staged series the engine
                        # rejected is unknown — leave them uncommitted
                        # (re-staging a landed series is an idempotent
                        # set-add next tick; committing a rejected one
                        # would burn budget on a phantom)
                        n = e.accepted_samples
                        self._meter.account(
                            self.tenant,
                            samples_rejected=e.rejected_samples,
                        )
                    summary["written"] = n
                    self._meter.account(self.tenant, rows_ingested=n)
        except Exception:
            TELEMETRY_TICKS.labels("error").inc()
            logger.warning("self-scrape tick failed; next interval "
                           "retries", exc_info=True)
            summary["error"] = True
            return summary
        finally:
            TELEMETRY_SERIES.set(len(self._series))
            summary["series"] = len(self._series)
        try:
            # the sweep is housekeeping, isolated from the scrape
            # verdict: a failed delete must not mark a LANDED write as
            # a failed tick (the next due sweep retries — _swept_hi_ms
            # only advances on success)
            await self._maybe_sweep(ts_ms)
        except Exception:  # noqa: BLE001 — housekeeping only
            logger.warning("self-telemetry retention sweep failed; "
                           "next due sweep retries", exc_info=True)
            summary["sweep_error"] = True
        for name, _k, _v in kept:
            self._written_names.add(name)
        TELEMETRY_TICKS.labels("ok").inc()
        TELEMETRY_SAMPLES.inc(len(kept))
        TELEMETRY_SCRAPE_SECONDS.observe(time.perf_counter() - t0)
        summary["samples_list"] = kept
        if self._federation_due(ts_ms, force=force_federation):
            # federation rides the tick but is isolated from its
            # verdict, like the sweep: a dead fleet must not mark a
            # LANDED self-scrape as a failed tick
            self._last_fed_ms = ts_ms
            try:
                summary["federation"] = await self.scrape_peers(ts_ms)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — housekeeping only
                logger.warning("federation sweep failed; next due sweep "
                               "retries", exc_info=True)
                summary["federation"] = {"error": True}
        return summary

    async def _maybe_sweep(self, now_ms: int) -> None:
        """Infrequent retention sweep: tombstone self-series older than
        the horizon. Sweep spacing is horizon/8 (floored at 60 s) — the
        horizon bounds staleness, not the sweep's punctuality. Scoped
        two ways: the instance="..." filter confines deletes to THIS
        collector's series (never same-named data another agent wrote),
        and each sweep covers only the (prev horizon, horizon) delta, so
        a long-lived server never re-tombstones already-swept ranges
        (tombstones and invalidation-funnel events both cost)."""
        if self.retention_ms is None or not (
            self._written_names or self._fed_written
        ):
            return
        spacing = max(self.retention_ms // 8, 60_000)
        if now_ms - self._last_sweep_ms < spacing:
            return
        self._last_sweep_ms = now_ms
        horizon = now_ms - self.retention_ms
        if horizon <= self._swept_hi_ms:
            return
        start = self._swept_hi_ms  # 0 on a fresh process: one full pass
        for name in sorted(self._written_names):
            await self._engine.delete_series(
                name.encode(),
                filters=[(b"instance", self.instance.encode())],
                start_ms=start, end_ms=horizon,
            )
        # federated series carry the PEER's instance label; sweep each
        # under its own filter so another agent's same-named data stays
        for name, inst in sorted(self._fed_written):
            await self._engine.delete_series(
                name.encode(),
                filters=[(b"instance", inst.encode())],
                start_ms=start, end_ms=horizon,
            )
        self._swept_hi_ms = horizon
        TELEMETRY_RETENTION_SWEEPS.inc()
