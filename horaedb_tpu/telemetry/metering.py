"""Per-tenant usage metering: THE accounting funnel (jaxlint J015).

Every layer that knows a tenant (the admission scheduler, the remote-write
handler, the query handlers' scan provenance) reports usage through ONE
process-wide meter — never through ad-hoc per-tenant counters (J015 pins
this: a `horaedb_tenant_*` family or a `tenant` labelname registered
outside horaedb_tpu/telemetry/ is a lint finding). One funnel means the
Prometheus families, the `/api/v1/usage` summary, and any future billing
export can never disagree about what a tenant consumed.

Two views of the same ledger:

- **since-boot**: monotone per-tenant counters, exported as the
  `horaedb_tenant_*` families below (and therefore self-scraped into
  first-class series by telemetry/collector.py — long-term per-tenant
  usage history is a PromQL query, not a side system);
- **windowed**: a bounded ring of coarse time buckets per tenant, served
  by `GET /api/v1/usage?tenant=...&window=5m` for "what did this tenant
  do in the last N minutes" without touching the query path.

Tenant-count bounded: past `MAX_TENANTS` distinct tenants, new ones fold
into the `_overflow` bucket (cardinality defense on the accounting
surface itself — a tenant-id flood must not grow /metrics unboundedly).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from horaedb_tpu.server.metrics import GLOBAL_METRICS

__all__ = ["UsageMeter", "GLOBAL_METER", "FIELDS"]

# the ledger's schema: one counter family per field, labeled by tenant
FIELDS = (
    "rows_ingested", "samples_rejected", "bytes_scanned",
    "queue_wait_seconds", "queries", "sheds", "deadline_hits",
)

TENANT_ROWS = GLOBAL_METRICS.counter(
    "horaedb_tenant_rows_ingested_total",
    help="Samples accepted through the ingest path, by tenant "
         "(X-Horaedb-Tenant header; the self-scrape loop writes as "
         "`_system`).",
    labelnames=("tenant",),
)
TENANT_REJECTED = GLOBAL_METRICS.counter(
    "horaedb_tenant_samples_rejected_total",
    help="Samples rejected at ingest by the series-cardinality defense "
         "(partial-accepts), by tenant. Wholly-malformed payloads 400 "
         "before their sample count is knowable and are visible in "
         "horaedb_http_requests_total{status=\"400\"} instead.",
    labelnames=("tenant",),
)
TENANT_BYTES_SCANNED = GLOBAL_METRICS.counter(
    "horaedb_tenant_bytes_scanned_total",
    help="Bytes MATERIALIZED from SSTs to answer this tenant's queries "
         "(decoded in-memory size, identical whether the read came cold "
         "or from the block cache; result-cache hits scan nothing and "
         "charge nothing).",
    labelnames=("tenant",),
)
TENANT_QUEUE_WAIT = GLOBAL_METRICS.counter(
    "horaedb_tenant_queue_wait_seconds_total",
    help="Seconds this tenant's queries spent waiting in the admission "
         "queue before a slot.",
    labelnames=("tenant",),
)
TENANT_QUERIES = GLOBAL_METRICS.counter(
    "horaedb_tenant_queries_total",
    help="Queries admitted (granted a slot) by tenant.",
    labelnames=("tenant",),
)
TENANT_SHEDS = GLOBAL_METRICS.counter(
    "horaedb_tenant_sheds_total",
    help="Queries shed before or during a slot (queue_full/stall/cost/"
         "forced/client_disconnect), by tenant.",
    labelnames=("tenant",),
)
TENANT_DEADLINE = GLOBAL_METRICS.counter(
    "horaedb_tenant_deadline_exceeded_total",
    help="Queries that ran out of their end-to-end deadline, by tenant.",
    labelnames=("tenant",),
)

_FAMILY_OF = {
    "rows_ingested": TENANT_ROWS,
    "samples_rejected": TENANT_REJECTED,
    "bytes_scanned": TENANT_BYTES_SCANNED,
    "queue_wait_seconds": TENANT_QUEUE_WAIT,
    "queries": TENANT_QUERIES,
    "sheds": TENANT_SHEDS,
    "deadline_hits": TENANT_DEADLINE,
}


class UsageMeter:
    """The process-wide per-tenant ledger (module docstring).

    Thread-safe (ingest accounting can arrive from executor threads);
    `clock` is injectable for deterministic windowed-view tests and must
    return unix seconds."""

    MAX_TENANTS = 1024
    OVERFLOW = "_overflow"
    BUCKET_S = 10          # windowed-view granularity
    MAX_BUCKETS = 360      # per tenant: 1h of history at 10 s buckets

    def __init__(self, clock=time.time):
        self._lock = threading.Lock()
        self._clock = clock
        self._boot = clock()
        # tenant -> {field: float} since boot
        self._totals: dict[str, dict[str, float]] = {}
        # tenant -> OrderedDict[bucket_epoch -> {field: float}]
        self._windows: dict[str, OrderedDict] = {}

    def _tenant_slot(self, tenant: str) -> str:
        t = str(tenant) or "default"
        if t in self._totals or len(self._totals) < self.MAX_TENANTS:
            return t
        return self.OVERFLOW

    def account(self, tenant: str, **deltas: float) -> None:
        """Fold one usage event into the ledger. Unknown fields raise —
        a typo'd field would silently meter nothing."""
        bad = set(deltas) - set(FIELDS)
        if bad:
            raise ValueError(f"unknown usage fields: {sorted(bad)}")
        now = self._clock()
        bucket = int(now // self.BUCKET_S) * self.BUCKET_S
        with self._lock:
            t = self._tenant_slot(tenant)
            tot = self._totals.setdefault(t, dict.fromkeys(FIELDS, 0.0))
            ring = self._windows.setdefault(t, OrderedDict())
            win = ring.get(bucket)
            if win is None:
                win = ring[bucket] = dict.fromkeys(FIELDS, 0.0)
                while len(ring) > self.MAX_BUCKETS:
                    ring.popitem(last=False)
            for k, v in deltas.items():
                v = float(v)
                if v == 0.0:
                    continue
                tot[k] += v
                win[k] += v
                _FAMILY_OF[k].labels(t).inc(v)

    # -- the /api/v1/usage view ---------------------------------------------
    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._totals)

    def summary(self, tenant: str, window_s: float | None = None) -> dict:
        """Since-boot totals plus (optionally) the trailing-window sums.
        An unknown tenant answers zeros — absence of usage is a valid
        usage report, not a 404."""
        now = self._clock()
        with self._lock:
            tot = dict(self._totals.get(tenant) or dict.fromkeys(FIELDS, 0.0))
            out = {
                "tenant": tenant,
                "since_boot": {k: _tidy(v) for k, v in tot.items()},
                "boot_unix_s": round(self._boot, 3),
            }
            if window_s is not None:
                window_s = float(window_s)
                lo = now - window_s
                win = dict.fromkeys(FIELDS, 0.0)
                for bucket, vals in (self._windows.get(tenant) or {}).items():
                    # a bucket [b, b+BUCKET_S) counts when it overlaps
                    # [lo, now] — coarse by design (BUCKET_S resolution)
                    if bucket + self.BUCKET_S > lo:
                        for k, v in vals.items():
                            win[k] += v
                out["window"] = {
                    "seconds": window_s,
                    # honest-truncation marker: the ring retains
                    # MAX_BUCKETS x BUCKET_S of history and nothing
                    # predates boot — a window wider than either is only
                    # COVERED this far back (the caller must never read
                    # a truncated sum as the full window)
                    "coverage_seconds": round(min(
                        window_s,
                        self.MAX_BUCKETS * self.BUCKET_S,
                        max(now - self._boot, 0.0),
                    ), 3),
                    **{k: _tidy(v) for k, v in win.items()},
                }
        return out

    @classmethod
    def horizon_s(cls) -> float:
        """The windowed view's retention: requests beyond this cannot be
        answered from the ring (use the self-scraped horaedb_tenant_*
        series for longer ranges)."""
        return float(cls.MAX_BUCKETS * cls.BUCKET_S)

    def reset(self) -> None:
        """Forget the ledger (tests). The Prometheus counters are NOT
        reset — they are monotone by contract."""
        with self._lock:
            self._totals.clear()
            self._windows.clear()
            self._boot = self._clock()


def _tidy(v: float):
    return int(v) if float(v).is_integer() else round(v, 6)


GLOBAL_METER = UsageMeter()
