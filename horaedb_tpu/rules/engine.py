"""The rule evaluator: dirty-set driven incremental standing queries.

One RuleEngine per metric engine. The design has three legs:

1. **Dirty sets from the invalidation funnel.** The evaluator is the one
   funnel consumer besides the result cache itself (jaxlint J014): every
   flush/delete commit on the engine's data tables lands here as an
   event ``(time range, needs_clear, written-by)``. A tick walks each
   rule's unseen events; a rule with none (and nothing else to do) is
   SKIPPED — `horaedb_rules_dirty_skips_total` — so a quiet tick is
   O(changed rules), not O(rules). Compaction events are ignored
   entirely: a compaction rewrites bytes, never logical content (deletes
   and retention are already masked at scan time), so no rule output can
   depend on it.

2. **Incremental recording rules that are bit-exact by construction.**
   A dirty data range [a, b) can only influence output steps in
   (a, b + smear), where smear is the body's largest lookback window
   (promql.eval.max_selector_window_ms). The tick re-evaluates exactly
   those steps — through promql's RangeEvaluator, the same code a cold
   /api/v1/query_range runs — and writes them back through the NORMAL
   ingest path, where LWW merge-dedup makes re-materialization
   idempotent. Deletes additionally tombstone the affected output span
   first (a step whose value must DISAPPEAR cannot be fixed by an
   overwrite). New steps beyond the watermark are evaluated only while
   they can see data (step - smear <= the rule's observed ingest
   high-water mark): with the PromQL subset presence-based (no absent()),
   output past that bound is provably empty. The one documented gap:
   future-dated samples written BEFORE the rule's first evaluation
   materialize at the next mutation event or reopen, not spontaneously.

3. **Crash recovery from durable fingerprints.** In-memory dirty state
   dies with the process, so the tick checkpoints a per-segment
   fingerprint of each data table (live SST ids + tombstone ids) through
   the fenced rule store — but only when every rule has processed every
   event (a checkpoint must never claim cleanliness it didn't earn). At
   open, segments whose fingerprint differs from the checkpoint are
   exactly what changed unwatched; they seed the reopen dirty set
   (tombstones created while down re-seed with needs_clear). Re-deriving
   an already-written range is an idempotent rewrite, so a crash at ANY
   point between ingest, write-back, and checkpoint converges to the
   cold-evaluation answer.

Alert rules ride the same dirty sets: an inactive alert with no relevant
mutation cannot become active (presence-based conditions only lose
series as data ages out of the lookback), so it is skipped; pending and
firing alerts always evaluate (their `for` clocks and resolution are
time-driven). Transitions are exactly-once: each gets the rule's next
monotonic sequence number and is PUT through the fenced store *before*
any counter/surface reflects it — a crash before the PUT re-derives the
transition once; after it, the durable log owns the identity.

Self-invalidation guard: during the tick's write-back (including its
flush barrier), funnel events are attributed to the set of rule output
names being written. A rule is marked dirty by such an event only if it
READS one of those names — and never by its own output alone. External
ingest interleaving with the write-back window is attributed to it too
(the funnel carries no author); that dirt is re-detected at the next
external event or at reopen via the fingerprint diff, and in production
the next scrape arrives long before either matters.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from dataclasses import dataclass, field

import numpy as np

from horaedb_tpu.common import tracing
from horaedb_tpu.common.error import HoraeError, UnavailableError, ensure
from horaedb_tpu.common.time_ext import now_ms as wall_now_ms
from horaedb_tpu.rules import (
    ALERT_TRANSITIONS,
    ALERTS_ACTIVE,
    RULE_DIRTY_SKIPS,
    RULE_EVAL_LAG,
    RULE_EVAL_SECONDS,
    RULE_EVALS,
    RULE_SAMPLES_WRITTEN,
    RULE_TICKS,
    RULE_WRITE_DEGRADED,
    RULES_REGISTERED,
    AlertRule,
    RecordingRule,
)
from horaedb_tpu.rules.store import RuleStore

logger = logging.getLogger(__name__)

# chunk bound for one RangeEvaluator pass (its own cap is 11k steps)
MAX_EVAL_STEPS = 5_000
# samples per write-back protobuf chunk (bounds one ingest call)
MAX_WRITE_SAMPLES = 100_000
# transition-log tail kept in each alert rule's durable state record
TRANSITION_TAIL = 256


@dataclass
class _Event:
    """One funnel event, kept until every rule has seen (or outlived) it."""

    id: int
    rng: "tuple[int, int] | None"   # (start_ms, end_ms) or None = unknown
    clear: bool                     # a delete: affected output must clear
    written: "frozenset | None"     # rule outputs being written, None=external


@dataclass
class _RecRuntime:
    rule: RecordingRule
    parsed: object
    smear: int
    inputs: frozenset
    last_event: int = 0
    high_wm: "int | None" = None    # newest materialized output step
    data_hi: int = 0                # observed ingest high-water mark


@dataclass
class _AlertRuntime:
    rule: AlertRule
    parsed: object
    inputs: frozenset
    last_event: int = 0
    seq: int = 0                    # last durable transition sequence
    # key (sorted label tuple) -> {"state","since_ms","fired_at","labels","value"}
    states: dict = field(default_factory=dict)
    transitions: list = field(default_factory=list)  # durable log tail
    # a rule with no durable state yet must evaluate once regardless of
    # events: its condition may ALREADY be true over pre-registration
    # data the funnel never announced to it
    force_eval: bool = False
    # presence frontier: a sample at x can make the condition true at
    # any tick t <= x + smear (offset selectors shift presence FORWARD;
    # future-dated samples start it later) — the inactive-quiet skip is
    # only sound beyond this frontier
    smear: int = 0
    data_hi: int = 0


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class RuleEngine:
    """Evaluator + registry over one metric engine (module docstring)."""

    def __init__(self) -> None:
        raise RuntimeError("use RuleEngine.open")

    @classmethod
    async def open(
        cls,
        engine,
        store,
        root: str = "metrics/rules",
        fence=None,
        admission=None,
        tenant: str = "rules",
        clock=None,
    ) -> "RuleEngine":
        """`engine`: MetricEngine or RegionedEngine. `store`: the shared
        object store (rule records live under `root`). `fence`: the
        engine's epoch fence, when one is installed — rule state rides
        the same single-writer contract. `admission`: optional
        AdmissionController; evaluations then run as the low-weight
        `tenant` so rule storms shed before dashboards notice.
        `clock`: injectable now_ms() for deterministic tests."""
        from horaedb_tpu.serving.cache import RESULT_CACHE

        self = object.__new__(cls)
        self._engine = engine
        self._store = RuleStore(root, store, fence=fence)
        self._admission = admission
        self._tenant = tenant
        self._clock = clock or wall_now_ms
        self._recording: "dict[str, _RecRuntime]" = {}
        self._alerts: "dict[str, _AlertRuntime]" = {}
        self._events: "list[_Event]" = []
        self._next_event = 1
        self._writing_names: "frozenset | None" = None
        self._tick_lock = asyncio.Lock()
        self._degrade_events = 0
        self._data_roots: set = set()
        self._refresh_roots()
        self._max_data_ts_cache: "int | None" = None
        self._last_epoch: "dict | None" = None

        rules, states = await self._store.load()
        for rule in rules.values():
            self._install(rule, states.get(rule.name))
        self._export_registered()

        # reopen dirty set: diff the durable fingerprints against the
        # live manifests — what changed while no evaluator was watching
        prev = await self._store.load_epoch()
        self._seed_reopen_dirty(prev)

        # the ONE other funnel consumer besides the cache (jaxlint J014)
        self._sub_token = RESULT_CACHE.serving_subscribe(self._on_invalidate)
        return self

    async def close(self) -> None:
        from horaedb_tpu.serving.cache import RESULT_CACHE

        RESULT_CACHE.serving_unsubscribe(self._sub_token)

    # -- registry -------------------------------------------------------------
    def _install(self, rule, state: "dict | None") -> None:
        from horaedb_tpu.promql import parse
        from horaedb_tpu.promql.eval import max_selector_window_ms

        parsed = parse(rule.expr)
        inputs = frozenset(rule.input_metrics)
        if rule.kind == "recording":
            rt = _RecRuntime(
                rule=rule, parsed=parsed,
                smear=max_selector_window_ms(parsed), inputs=inputs,
            )
            if state:
                hw = state.get("high_wm")
                rt.high_wm = int(hw) if hw is not None else None
                rt.data_hi = int(state.get("data_hi", 0))
            self._recording[rule.name] = rt
            self._alerts.pop(rule.name, None)
        else:
            # never-transitioned rules (no record, or the empty record a
            # registration writes) force one evaluation: their condition
            # may already be true over data the funnel never announced
            virgin = not state or (
                not state.get("seq") and not state.get("states")
                and not state.get("transitions")
            )
            art = _AlertRuntime(
                rule=rule, parsed=parsed, inputs=inputs,
                force_eval=virgin,
                smear=max_selector_window_ms(parsed),
                # conservative frontier when none is recorded: the
                # newest data the tables hold (extra evals, never a
                # missed presence window)
                data_hi=int((state or {}).get("data_hi")
                            or self._max_data_ts()),
            )
            if state:
                art.seq = int(state.get("seq", 0))
                for s in state.get("states", []):
                    labels = dict(s.get("labels") or {})
                    art.states[_labels_key(labels)] = {
                        "state": s["state"],
                        "since_ms": int(s["since_ms"]),
                        "fired_at": (int(s["fired_at"])
                                     if s.get("fired_at") is not None
                                     else None),
                        "labels": labels,
                        "value": float(s.get("value", 0.0)),
                    }
                art.transitions = list(state.get("transitions", []))
            self._alerts[rule.name] = art
            self._recording.pop(rule.name, None)

    def _max_data_ts(self) -> int:
        """Newest sample timestamp the data tables can hold (manifest
        state only): the conservative alert presence frontier when no
        recorded one survives. Memoized — loading 10k rules must not
        walk every SST 10k times — and invalidated by every funnel
        event (mutations are what move it)."""
        if self._max_data_ts_cache is None:
            hi = 0
            for sub in self._engine.sub_engines().values():
                for s in sub.data_table.manifest.all_ssts():
                    hi = max(hi, int(s.meta.time_range.end))
            self._max_data_ts_cache = hi
        return self._max_data_ts_cache

    def _export_registered(self) -> None:
        RULES_REGISTERED.labels("recording").set(len(self._recording))
        RULES_REGISTERED.labels("alert").set(len(self._alerts))
        self._export_active()

    def _export_active(self) -> None:
        counts = {"pending": 0, "firing": 0}
        for art in self._alerts.values():
            for st in art.states.values():
                counts[st["state"]] = counts.get(st["state"], 0) + 1
        for k in ("pending", "firing"):
            ALERTS_ACTIVE.labels(k).set(counts.get(k, 0))

    async def register(self, rule) -> None:
        """Durably register (or replace — by name) one validated rule.
        Serialized with the tick: a mid-tick replacement must not let
        the old runtime's checkpoint clobber the fresh reset."""
        async with self._tick_lock:
            await self._register_locked(rule)

    async def _register_locked(self, rule) -> None:
        rule.validate()
        other = (self._alerts if rule.kind == "recording"
                 else self._recording)
        ensure(
            rule.name not in other,
            f"rule {rule.name!r} already exists with the other kind; "
            "delete it first",
        )
        if rule.kind == "recording" and getattr(rule, "group", ""):
            # rule-group contract: one shared interval per group, so the
            # whole chain rides one aligned step grid (members evaluate
            # in order within one tick — _tick_recording)
            for other in self._recording.values():
                o = other.rule
                ensure(
                    o.name == rule.name
                    or getattr(o, "group", "") != rule.group
                    or o.interval_ms == rule.interval_ms,
                    f"rule {rule.name}: group {rule.group!r} has interval "
                    f"{o.interval_ms}ms (from {o.name}); group members "
                    "share one interval",
                )
        replacing_recording = rule.name in self._recording
        await self._store.put_rule(rule)
        if replacing_recording:
            # the OLD body's materialized output is not the new body's:
            # left in place it would answer queries (and claim EXPLAIN
            # provenance) for an expression that never produced it.
            # Tombstone the output span; the new body re-materializes
            # from its fresh watermark.
            await self._engine.delete_series(rule.name.encode())
        # replacing a rule resets its runtime state deliberately: a new
        # body/interval invalidates the old watermark and alert states —
        # DURABLY, for both kinds: a stale alert-state record surviving a
        # replacement would resurrect the OLD rule's firing states and
        # sequence under the new definition at the next reopen
        self._install(rule, None)
        if rule.kind == "recording":
            await self._store.put_state(rule.name, {
                "kind": "recording", "high_wm": None, "data_hi": 0,
            })
        else:
            await self._store.put_state(rule.name, {
                "kind": "alert", "seq": 0, "states": [],
                "transitions": [],
            })
        self._export_registered()

    async def ensure_registered(self, rule) -> bool:
        """Boot-time idempotent registration (config-declared rules):
        register only when absent or the DEFINITION changed — an
        unchanged rule keeps its watermark and alert states."""
        async with self._tick_lock:
            cur = (self._recording.get(rule.name)
                   or self._alerts.get(rule.name))
            if cur is not None:
                if cur.rule.identity() == rule.identity():
                    return False
                if cur.rule.kind != rule.kind:
                    await self._delete_locked(rule.name)  # kind swap
            await self._register_locked(rule)
            return True

    async def delete(self, name: str) -> bool:
        async with self._tick_lock:
            return await self._delete_locked(name)

    async def _delete_locked(self, name: str) -> bool:
        known = name in self._recording or name in self._alerts
        if not known:
            return False
        await self._store.delete_rule(name)
        self._recording.pop(name, None)
        self._alerts.pop(name, None)
        self._export_registered()
        return True

    def list_rules(self) -> list:
        return sorted(
            [rt.rule for rt in self._recording.values()]
            + [art.rule for art in self._alerts.values()],
            key=lambda r: (r.kind, r.name),
        )

    def output_metrics(self) -> set:
        """Recording-rule output metric names (EXPLAIN provenance)."""
        return set(self._recording)

    def rule_for_metric(self, metric: str):
        rt = self._recording.get(metric)
        return rt.rule if rt is not None else None

    def alerts(self) -> list[dict]:
        """Active alerts, Prometheus /api/v1/alerts shape."""
        out = []
        for name in sorted(self._alerts):
            art = self._alerts[name]
            for st in art.states.values():
                out.append({
                    # alertname LAST: it is the alert's identity and must
                    # win over any rule/series label spelled "alertname"
                    "labels": {
                        **art.rule.labels,
                        **st["labels"],
                        "alertname": name,
                    },
                    "annotations": dict(art.rule.annotations),
                    "state": st["state"],
                    "activeAt": st["since_ms"] / 1000.0,
                    "value": str(st["value"]),
                })
        return out

    def transitions(self, name: str) -> list[dict]:
        """One alert rule's durable transition-log tail (runbooks + the
        chaos oracle)."""
        art = self._alerts.get(name)
        return list(art.transitions) if art is not None else []

    # -- the funnel subscription (jaxlint J014) -------------------------------
    def _refresh_roots(self) -> None:
        self._data_roots = {
            sub.data_table._root
            for sub in self._engine.sub_engines().values()
        }

    def _on_invalidate(self, root: str, reason: str, time_range) -> None:
        """Synchronous, cheap: record the dirty fact, return. Runs inside
        the mutation commit that fired it (serving/cache.py)."""
        if root not in self._data_roots:
            # the region set can GROW under us (split_region mints a
            # daughter root): refresh once before concluding the event
            # belongs to someone else's table
            self._refresh_roots()
            if root not in self._data_roots:
                return
        if reason == "compact":
            return  # content-neutral: deletes/retention already masked
        rng = None
        if time_range is not None:
            rng = (int(time_range.start), int(time_range.end))
        self._events.append(_Event(
            id=self._next_event, rng=rng, clear=(reason == "delete"),
            written=self._writing_names,
        ))
        self._next_event += 1
        self._max_data_ts_cache = None  # the frontier just moved

    def _relevant(self, ev: _Event, inputs: frozenset, own: str) -> bool:
        if ev.written is None:
            return True
        return bool((ev.written & inputs) - {own})

    def _events_after(self, last: int, inputs: frozenset, own: str) -> list:
        return [
            ev for ev in self._events
            if ev.id > last and self._relevant(ev, inputs, own)
        ]

    def _compact_events(self) -> None:
        floors = [rt.last_event for rt in self._recording.values()]
        floors += [a.last_event for a in self._alerts.values()]
        if not floors:
            self._events.clear()
            return
        floor = min(floors)
        self._events = [ev for ev in self._events if ev.id > floor]

    # -- segment fingerprints (crash recovery) --------------------------------
    def _seg_digests(self) -> dict:
        """{root: {"seg_ms", "segs": {seg: digest}, "tombs": [ids]}} over
        the engine's data tables — pure manifest state, no IO."""
        from horaedb_tpu.storage.types import TimeRange

        out: dict = {}
        for sub in self._engine.sub_engines().values():
            st = sub.data_table
            seg_ms = int(st.segment_duration_ms)
            segs: dict[int, list[int]] = {}
            for s in st.manifest.all_ssts():
                seg = int(s.meta.time_range.start) // seg_ms * seg_ms
                segs.setdefault(seg, []).append(int(s.id))
            tombs = st.manifest.all_tombstones()
            d = {}
            for seg, ids in segs.items():
                h = hashlib.blake2b(digest_size=12)
                h.update(",".join(map(str, sorted(ids))).encode())
                overlapping = sorted(
                    int(t.id) for t in tombs
                    if t.time_range.overlaps(TimeRange(seg, seg + seg_ms))
                )
                h.update(b"|")
                h.update(",".join(map(str, overlapping)).encode())
                d[str(seg)] = h.hexdigest()
            out[st._root] = {
                "seg_ms": seg_ms,
                "segs": d,
                "tombs": sorted(int(t.id) for t in tombs),
            }
        return out

    def _seed_reopen_dirty(self, prev: "dict | None") -> None:
        """Diff durable fingerprints vs live manifests into dirty events
        (module docstring leg 3). No checkpoint + existing rule state =
        everything is suspect: one full clear+recompute."""
        cur = self._seg_digests()
        self._last_epoch = None  # re-persisted only after a clean tick
        has_state = any(
            rt.high_wm is not None for rt in self._recording.values()
        ) or any(a.states or a.seq for a in self._alerts.values())
        if prev is None:
            if has_state:
                self._record_reopen_event(None, clear=True)
            return
        proots = prev.get("roots")
        if not isinstance(proots, dict):
            if has_state:
                self._record_reopen_event(None, clear=True)
            return
        if set(proots) != set(cur):
            self._record_reopen_event(None, clear=True)
            return
        for root, cinfo in cur.items():
            pinfo = proots[root]
            seg_ms = int(cinfo["seg_ms"])
            if int(pinfo.get("seg_ms", -1)) != seg_ms:
                self._record_reopen_event(None, clear=True)
                return
            psegs = dict(pinfo.get("segs") or {})
            csegs = cinfo["segs"]
            for seg in set(psegs) | set(csegs):
                if psegs.get(seg) == csegs.get(seg):
                    continue
                lo = int(seg)
                # vanished segment: rows can DISAPPEAR (retention expiry
                # fully applied + tombstone GC) — clear, then recompute
                clear = seg not in csegs
                self._record_reopen_event((lo, lo + seg_ms), clear=clear)
            # tombstones minted while no evaluator was running: their
            # ranges need a clear (output rows must disappear)
            new_tombs = set(cinfo["tombs"]) - set(pinfo.get("tombs") or [])
            if new_tombs:
                for sub in self._engine.sub_engines().values():
                    st = sub.data_table
                    if st._root != root:
                        continue
                    for t in st.manifest.all_tombstones():
                        if int(t.id) in new_tombs:
                            self._record_reopen_event(
                                (int(t.time_range.start),
                                 int(t.time_range.end)),
                                clear=True,
                            )

    def _record_reopen_event(self, rng, clear: bool) -> None:
        self._events.append(_Event(
            id=self._next_event, rng=rng, clear=clear, written=None,
        ))
        self._next_event += 1

    # -- the tick -------------------------------------------------------------
    async def tick(self, now_ms: "int | None" = None) -> dict:
        """One evaluation pass. Serialized: the server loop and any admin
        trigger share one lock, so ticks never interleave."""
        async with self._tick_lock:
            return await self._tick_locked(now_ms)

    async def _tick_locked(self, now_ms: "int | None") -> dict:
        now = int(now_ms if now_ms is not None else self._clock())
        snapshot = self._next_event - 1
        summary = {
            "evaluated": 0, "skipped": 0, "errors": 0, "shed": 0,
            "samples_written": 0, "transitions": 0, "deletes": 0,
        }
        with tracing.trace("rule_tick", rules=len(self._recording)
                           + len(self._alerts)):
            await self._tick_recording(now, snapshot, summary)
            await self._tick_alerts(now, summary)
        # epoch checkpoint — only when every rule has processed every
        # event it cares about (a premature checkpoint would claim
        # cleanliness for dirt that only lived in memory)
        if summary["errors"] == 0 and not self._pending_relevant():
            cur = self._seg_digests()
            if cur != self._last_epoch:
                try:
                    await self._store.put_epoch({"roots": cur})
                    self._last_epoch = cur
                except Exception:  # noqa: BLE001 — wider reopen dirty
                    logger.warning("rule epoch checkpoint failed; reopen "
                                   "will re-derive more", exc_info=True)
        self._compact_events()
        # lag: how far the newest materialized step trails the data the
        # rule could already see (quiescent rules are NOT lagging — their
        # un-materialized steps are provably empty)
        lags = []
        for rt in self._recording.values():
            if rt.high_wm is None:
                continue
            step = rt.rule.interval_ms
            # last COMPLETE grid step the rule could have materialized:
            # being mid-interval is not lag
            frontier = min(now, rt.data_hi + rt.smear) // step * step
            lags.append(max(0, frontier - rt.high_wm) / 1000.0)
        RULE_EVAL_LAG.set(round(max(lags), 3) if lags else 0)
        self._export_active()
        noop = summary["evaluated"] == 0 and summary["errors"] == 0
        RULE_TICKS.labels("noop" if noop else "ok").inc()
        summary["noop"] = noop
        return summary

    def _pending_relevant(self) -> bool:
        for rt in self._recording.values():
            if self._events_after(rt.last_event, rt.inputs, rt.rule.name):
                return True
        for art in self._alerts.values():
            if self._events_after(art.last_event, art.inputs,
                                  art.rule.name):
                return True
        return False

    # -- recording rules ------------------------------------------------------
    async def _tick_recording(self, now: int, snapshot: int,
                              summary: dict) -> None:
        """Ungrouped rules keep the batched one-write-back tick; rule
        GROUPS evaluate sequentially in (group_order, name) order with a
        per-member write-back, so a chain (B reads A's output) lands
        deterministically in ONE tick: A's write-back fires the funnel
        event B's per-member snapshot then includes."""
        grouped: dict[str, list[str]] = {}
        ungrouped: list[str] = []
        for name in sorted(self._recording):
            g = getattr(self._recording[name].rule, "group", "")
            if g:
                grouped.setdefault(g, []).append(name)
            else:
                ungrouped.append(name)
        await self._tick_recording_set(now, snapshot, summary, ungrouped)
        for g in sorted(grouped):
            members = sorted(
                grouped[g],
                key=lambda n: (self._recording[n].rule.group_order, n),
            )
            for name in members:
                if name not in self._recording:
                    continue  # deleted over HTTP mid-tick
                # per-member snapshot: predecessors' write-backs already
                # fired their events — the chain resolves this tick
                member_snapshot = self._next_event - 1
                await self._tick_recording_set(
                    now, member_snapshot, summary, [name]
                )

    async def _tick_recording_set(self, now: int, snapshot: int,
                                  summary: dict, names: list) -> None:
        plans = []  # (rt, target, data_hi', samples, clears)
        out_names = set()
        for name in names:
            rt = self._recording.get(name)
            if rt is None:
                continue  # deleted over HTTP while this tick awaited
            events = [
                ev for ev in self._events
                if ev.id <= snapshot and ev.id > rt.last_event
                and self._relevant(ev, rt.inputs, name)
            ]
            plan = self._recording_plan(rt, now, events)
            if plan is None:
                summary["skipped"] += 1
                RULE_DIRTY_SKIPS.labels("recording").inc()
                continue
            ranges, clears, target, data_hi = plan
            if not ranges:
                # bookkeeping-only advance (plan docstring): no
                # evaluation ran, so the watermark stays put — only the
                # observed data high-water mark moves
                changed = data_hi != rt.data_hi
                rt.data_hi = data_hi
                rt.last_event = snapshot
                summary["skipped"] += 1
                RULE_DIRTY_SKIPS.labels("recording").inc()
                if changed:
                    try:
                        await self._store.put_state(name, {
                            "kind": "recording", "high_wm": rt.high_wm,
                            "data_hi": rt.data_hi,
                        })
                    except Exception:  # noqa: BLE001 — reopen re-derives
                        logger.warning("rule state checkpoint failed for "
                                       "%s", name, exc_info=True)
                continue
            t0 = time.perf_counter()
            try:
                samples = await self._admitted(
                    self._eval_recording(rt, ranges)
                )
            except UnavailableError:
                summary["shed"] += 1
                RULE_EVALS.labels("recording", "shed").inc()
                continue
            except Exception:  # noqa: BLE001 — dirty set kept; next tick
                summary["errors"] += 1
                RULE_EVALS.labels("recording", "error").inc()
                logger.warning("recording rule %s evaluation failed",
                               name, exc_info=True)
                continue
            RULE_EVAL_SECONDS.labels("recording").observe(
                time.perf_counter() - t0
            )
            plans.append((rt, target, data_hi, samples, clears))
            out_names.add(name)
        if not plans:
            return
        # one guarded write-back for the whole tick: deletes first (their
        # sequences must predate the rewrites), then the batched payload,
        # then the flush barrier — all attributed to `out_names` so the
        # self-invalidation guard and rule chaining both see the author
        try:
            await self._write_back(plans, frozenset(out_names), summary)
        except Exception:  # noqa: BLE001 — nothing advanced; next tick
            summary["errors"] += len(plans)
            for _ in plans:
                RULE_EVALS.labels("recording", "error").inc()
            logger.warning("rule write-back failed; dirty sets kept",
                           exc_info=True)
            return
        for rt, target, data_hi, _samples, _clears in plans:
            changed = rt.high_wm != target or rt.data_hi != data_hi
            rt.high_wm = target
            rt.data_hi = data_hi
            rt.last_event = snapshot
            summary["evaluated"] += 1
            RULE_EVALS.labels("recording", "ok").inc()
            if changed:
                try:
                    await self._store.put_state(rt.rule.name, {
                        "kind": "recording", "high_wm": rt.high_wm,
                        "data_hi": rt.data_hi,
                    })
                except Exception:  # noqa: BLE001 — reopen re-derives
                    logger.warning("rule state checkpoint failed for %s",
                                   rt.rule.name, exc_info=True)

    def _recording_plan(self, rt: _RecRuntime, now: int, events: list):
        """(step ranges, clear ranges, new watermark, new data_hi) or
        None = nothing to do (the dirty-set skip).

        Evaluated steps are the union of: the full configured span on
        first materialization; the trailing window of previously-known
        data ((high_wm, data_hi + smear] — drains once, then quiet ticks
        go to zero); and each event's influence ((a, b + smear) for a
        mutation over [a, b)). Steps outside that union are provably
        empty under the presence-based subset, so the watermark jumps
        them for free whenever a plan runs at all."""
        rule = rt.rule
        step = rule.interval_ms
        first = -(-rule.since_ms // step) * step
        target = now // step * step
        data_hi = rt.data_hi
        for ev in events:
            data_hi = max(data_hi,
                          ev.rng[1] if ev.rng is not None else now)
        covered_hi = rt.high_wm
        if target < first:
            # grid not started (future since_ms): nothing can evaluate,
            # but events must still be CONSUMED (bookkeeping-only plan)
            # or they pin the event list and starve the epoch checkpoint
            if events:
                return [], [], covered_hi, data_hi
            return None
        ranges: list[list] = []   # [lo, hi, clear]
        if covered_hi is None:
            # first materialization covers the whole configured span
            # (the one pass that can see pre-registration data)
            ranges.append([first, target, False])
        else:
            if target > covered_hi:
                # trailing window of data the rule already knew about
                lo = covered_hi + step
                hi = min(target, (rt.data_hi + rt.smear) // step * step)
                if hi >= lo:
                    ranges.append([lo, hi, False])
            for ev in events:
                if ev.rng is None:
                    ranges.append([first, target, ev.clear])
                    continue
                a, b = ev.rng
                lo = max(first, a // step * step)
                hi = min(target, -(-(b + rt.smear) // step) * step)
                if lo <= hi:
                    ranges.append([lo, hi, ev.clear])
        if not ranges:
            if events:
                # events whose influence misses the grid entirely (e.g.
                # future-dated data beyond the current target): nothing
                # to evaluate NOW, but data_hi must advance — the
                # trailing window materializes it once the grid catches
                # up. Watermark unchanged (no evaluation ran).
                return [], [], covered_hi, data_hi
            return None
        # merge overlapping/adjacent step ranges, OR-ing the clear flags
        ranges.sort(key=lambda r: r[0])
        merged = [ranges[0][:]]
        for lo, hi, clear in ranges[1:]:
            cur = merged[-1]
            if lo <= cur[1] + step:
                cur[1] = max(cur[1], hi)
                cur[2] = cur[2] or clear
            else:
                merged.append([lo, hi, clear])
        clears = [(lo, hi) for lo, hi, clear in merged if clear]
        return [(lo, hi) for lo, hi, _ in merged], clears, target, data_hi

    async def _admitted(self, coro):
        """Run one rule evaluation under the low-weight rules tenant
        (admission present) so a rule storm queues/sheds behind
        dashboards instead of starving them."""
        if self._admission is None:
            return await coro
        slot = self._admission.slot(self._tenant)
        async with slot:
            return await coro

    async def _eval_recording(self, rt: _RecRuntime, ranges: list) -> list:
        """Evaluate the body over each step range (chunked under the
        evaluator's resolution cap); returns [(labels, [(ts, value)])].
        Runs the same RangeEvaluator a cold query_range runs — the
        bit-exactness anchor."""
        from horaedb_tpu.promql.eval import evaluate_range

        rule = rt.rule
        step = rule.interval_ms
        out: dict[tuple, list] = {}
        labels_of: dict[tuple, dict] = {}
        with tracing.span("rule_eval", rule=rule.name, kind="recording",
                          ranges=len(ranges)):
            for lo, hi in ranges:
                chunk_lo = lo
                while chunk_lo <= hi:
                    chunk_hi = min(hi, chunk_lo + (MAX_EVAL_STEPS - 1) * step)
                    steps, series = await evaluate_range(
                        self._engine, rt.parsed, chunk_lo, chunk_hi, step,
                    )
                    if isinstance(series, float):
                        raise HoraeError(
                            f"recording rule {rule.name} evaluates to a "
                            "scalar; bodies must produce a vector"
                        )
                    for sv in series:
                        labels = {
                            k: v for k, v in sv.labels.items()
                            if k != "__name__"
                        }
                        labels.update(rule.labels)
                        key = _labels_key(labels)
                        labels_of.setdefault(key, labels)
                        dst = out.setdefault(key, [])
                        vals = sv.values
                        for i in np.flatnonzero(~np.isnan(vals)):
                            dst.append((int(steps[i]), float(vals[i])))
                    chunk_lo = chunk_hi + step
        return [(labels_of[k], pts) for k, pts in out.items()]

    async def _write_back(self, plans: list, out_names: frozenset,
                          summary: dict) -> None:
        """Guarded write-back: tombstone the clear ranges, ingest the
        batched output through the NORMAL write path (cardinality budget
        included), then flush so everything is durable — and every
        funnel event the work fires is attributed to `out_names` while
        the guard holds."""
        from horaedb_tpu.ingest.cardinality import CardinalityLimited

        self._writing_names = out_names
        try:
            for rt, _t, _d, _samples, clears in plans:
                for lo, hi in clears:
                    with tracing.span("rule_clear", rule=rt.rule.name):
                        await self._engine.delete_series(
                            rt.rule.name.encode(),
                            start_ms=int(lo), end_ms=int(hi) + 1,
                        )
                    summary["deletes"] += 1
            total = 0
            for payload, n in self._payloads(plans):
                try:
                    await self._engine.write_payload(payload)
                except CardinalityLimited as e:
                    # PR 7 partial-degrade: in-budget output landed; the
                    # rejected new series are counted + sampled-logged —
                    # never a silent drop
                    RULE_WRITE_DEGRADED.inc()
                    self._degrade_events += 1
                    if (self._degrade_events == 1
                            or self._degrade_events % 100 == 0):
                        logger.warning(
                            "rule write-back cardinality-degraded "
                            "(event %d): %s", self._degrade_events, e,
                        )
                total += n
            if total:
                await self._engine.flush()
            summary["samples_written"] += total
            RULE_SAMPLES_WRITTEN.inc(total)
        finally:
            self._writing_names = None

    def _payloads(self, plans: list):
        """Batched remote-write protobuf chunks over every plan's output
        series (one ingest call per ~MAX_WRITE_SAMPLES)."""
        from horaedb_tpu.pb import remote_write_pb2

        req = remote_write_pb2.WriteRequest()
        n = 0
        for rt, _t, _d, samples, _c in plans:
            for labels, pts in samples:
                if not pts:
                    continue
                ts_entry = req.timeseries.add()
                lab = ts_entry.labels.add()
                lab.name = b"__name__"
                lab.value = rt.rule.name.encode()
                for k in sorted(labels):
                    lab = ts_entry.labels.add()
                    lab.name = k.encode()
                    lab.value = labels[k].encode()
                for ts, v in pts:
                    smp = ts_entry.samples.add()
                    smp.timestamp = ts
                    smp.value = v
                n += len(pts)
                if n >= MAX_WRITE_SAMPLES:
                    yield req.SerializeToString(), n
                    req = remote_write_pb2.WriteRequest()
                    n = 0
        if n:
            yield req.SerializeToString(), n

    # -- alert rules ----------------------------------------------------------
    async def _tick_alerts(self, now: int, summary: dict) -> None:
        for name in sorted(self._alerts):
            art = self._alerts.get(name)
            if art is None:
                continue  # deleted over HTTP while this tick awaited
            events = self._events_after(art.last_event, art.inputs, name)
            if (not events and not art.states and not art.force_eval
                    and now > art.data_hi + art.smear):
                # presence-based conditions cannot BECOME true without a
                # mutation — once the tick is past every known sample's
                # influence window (offset selectors and future-dated
                # samples shift presence FORWARD, hence the frontier
                # check). Active states still need their for/resolve
                # clocks; only settled-inactive quiet rules skip.
                summary["skipped"] += 1
                RULE_DIRTY_SKIPS.labels("alert").inc()
                continue
            seen = self._next_event - 1
            # advance the presence frontier up front so the checkpoint
            # inside _apply_alert records it; a failed eval re-derives
            # the same max from the kept events (idempotent, and a too-
            # large frontier only costs extra evaluations)
            for ev in events:
                art.data_hi = max(art.data_hi,
                                  ev.rng[1] if ev.rng is not None else now)
            t0 = time.perf_counter()
            try:
                active = await self._admitted(self._eval_alert(art, now))
            except UnavailableError:
                summary["shed"] += 1
                RULE_EVALS.labels("alert", "shed").inc()
                continue
            except Exception:  # noqa: BLE001 — dirty kept; next tick
                summary["errors"] += 1
                RULE_EVALS.labels("alert", "error").inc()
                logger.warning("alert rule %s evaluation failed", name,
                               exc_info=True)
                continue
            RULE_EVAL_SECONDS.labels("alert").observe(
                time.perf_counter() - t0
            )
            try:
                n_tr = await self._apply_alert(art, active, now)
            except Exception:  # noqa: BLE001 — checkpoint failed: state
                # unchanged, transition not visible; next tick re-derives
                # it ONCE (the exactly-once contract's crash side)
                summary["errors"] += 1
                RULE_EVALS.labels("alert", "error").inc()
                logger.warning("alert state checkpoint failed for %s",
                               name, exc_info=True)
                continue
            art.last_event = seen
            art.force_eval = False
            summary["evaluated"] += 1
            summary["transitions"] += n_tr
            RULE_EVALS.labels("alert", "ok").inc()

    async def _eval_alert(self, art: _AlertRuntime, now: int) -> dict:
        """Instant-vector evaluation at `now` (the HTTP instant-query
        construction): key -> (labels, value) for every present series.
        Rides the result cache through the engine's one query choke
        point — N alert rules over the same selector pay one scan."""
        from horaedb_tpu.promql.eval import LOOKBACK_MS, evaluate_range

        with tracing.span("rule_eval", rule=art.rule.name, kind="alert"):
            _steps, series = await evaluate_range(
                self._engine, art.parsed, now - LOOKBACK_MS, now,
                LOOKBACK_MS,
            )
        if isinstance(series, float):
            raise HoraeError(
                f"alert rule {art.rule.name} evaluates to a scalar; "
                "alert bodies must produce a vector"
            )
        active: dict[tuple, tuple] = {}
        for sv in series:
            v = sv.values[-1]
            if np.isnan(v):
                continue
            labels = {k: val for k, val in sv.labels.items()
                      if k != "__name__"}
            active[_labels_key(labels)] = (labels, float(v))
        return active

    async def _apply_alert(self, art: _AlertRuntime, active: dict,
                           now: int) -> int:
        """Drive the state machine, checkpoint durably, THEN make the
        transitions visible (module docstring: the PUT is the
        exactly-once commit point)."""
        rule = art.rule
        new_states: dict = {}
        transitions: list[dict] = []

        def note(frm: str, to: str, labels: dict, value: float) -> None:
            transitions.append({
                "seq": art.seq + len(transitions) + 1,
                "at_ms": now, "from": frm, "to": to,
                "labels": dict(labels), "value": value,
            })

        for key, (labels, value) in active.items():
            prev = art.states.get(key)
            if prev is None:
                if rule.for_ms <= 0:
                    new_states[key] = {
                        "state": "firing", "since_ms": now,
                        "fired_at": now, "labels": labels, "value": value,
                    }
                    note("inactive", "firing", labels, value)
                else:
                    new_states[key] = {
                        "state": "pending", "since_ms": now,
                        "fired_at": None, "labels": labels, "value": value,
                    }
                    note("inactive", "pending", labels, value)
            elif (prev["state"] == "pending"
                  and now - prev["since_ms"] >= rule.for_ms):
                new_states[key] = {
                    "state": "firing", "since_ms": prev["since_ms"],
                    "fired_at": now, "labels": labels, "value": value,
                }
                note("pending", "firing", labels, value)
            else:
                new_states[key] = {**prev, "labels": labels,
                                   "value": value}
        for key, prev in art.states.items():
            if key in active:
                continue
            note(prev["state"], "inactive", prev["labels"], prev["value"])
        if not transitions and new_states == art.states:
            return 0
        seq = art.seq + len(transitions)
        log = (art.transitions + transitions)[-TRANSITION_TAIL:]
        await self._store.put_state(rule.name, {
            "kind": "alert",
            "seq": seq,
            # presence frontier rides the checkpoint opportunistically;
            # reopen without one falls back to the conservative
            # _max_data_ts derivation (extra evals, never a miss)
            "data_hi": art.data_hi,
            "states": [
                {
                    "labels": st["labels"], "state": st["state"],
                    "since_ms": st["since_ms"], "fired_at": st["fired_at"],
                    "value": st["value"],
                }
                for _k, st in sorted(new_states.items())
            ],
            "transitions": log,
        })
        # durable: NOW the transitions exist
        art.seq = seq
        art.states = new_states
        art.transitions = log
        for tr in transitions:
            if tr["to"] == "firing":
                ALERT_TRANSITIONS.labels("firing").inc()
            elif tr["to"] == "pending":
                ALERT_TRANSITIONS.labels("pending").inc()
            elif tr["from"] == "firing":
                ALERT_TRANSITIONS.labels("resolved").inc()
        return len(transitions)
