"""Durable rule + alert-state storage: fenced manifest-level records.

Layout under the rule root (one per engine, e.g. ``metrics/rules``):

- ``{root}/manifest/rule/{digest(name)}`` — one JSON rule definition per
  rule (rules/__init__.py serde). The PUT is the registration's
  durability point; a registered rule survives crash/reopen.
- ``{root}/manifest/state/{digest(name)}`` — one JSON state record per
  rule: the recording watermark, or the alert rule's state machine
  (per-series states + the exactly-once transition log tail + the
  monotonic transition sequence).
- ``{root}/manifest/epoch`` — the evaluator's segment-fingerprint
  checkpoint: per data-table root, a digest of each segment's live SST
  ids + overlapping tombstone ids at the end of the last tick. At open,
  segments whose fingerprint differs from the checkpoint are exactly the
  data that changed while no evaluator was watching — they seed the
  reopen dirty set, so crash recovery re-derives only what it must.

Every mutation validates the engine's epoch fence first (storage/
fence.py) when one is installed: a deposed process must not advance rule
state over the new owner's — the same single-writer contract the data
manifests enforce. All paths live under ``manifest/``, which object-store
fault models (objstore/chaos.py) treat as control-plane: atomic, never
torn.

Load policy mirrors tombstones, not rollups: a corrupt RULE or STATE
record fails the open loudly. Silently skipping a rule record would
silently stop a standing query; silently skipping an alert-state record
could replay a transition the durable log already owns — the exactly-once
contract dies either way. The epoch checkpoint alone is best-effort (a
lost checkpoint only widens the reopen dirty set, never corrupts it).
"""

from __future__ import annotations

import hashlib
import json
import logging

from horaedb_tpu.common.error import context
from horaedb_tpu.objstore import NotFound
from horaedb_tpu.rules import rule_from_json

logger = logging.getLogger(__name__)

RULE_PREFIX = "manifest/rule"
STATE_PREFIX = "manifest/state"
EPOCH_PATH = "manifest/epoch"


def _digest(name: str) -> str:
    """Stable, path-safe key for a rule name (names are user input and
    may contain characters no object path should)."""
    return hashlib.blake2b(name.encode(), digest_size=16).hexdigest()


class RuleStore:
    """The durable half of the rule engine (rules/engine.py owns the
    in-memory half and all evaluation)."""

    def __init__(self, root: str, store, fence=None):
        self._root = root.strip("/")
        self._store = store
        self._fence = fence

    @property
    def root(self) -> str:
        return self._root

    def _rule_path(self, name: str) -> str:
        return f"{self._root}/{RULE_PREFIX}/{_digest(name)}"

    def _state_path(self, name: str) -> str:
        return f"{self._root}/{STATE_PREFIX}/{_digest(name)}"

    def _epoch_path(self) -> str:
        return f"{self._root}/{EPOCH_PATH}"

    async def _ensure_owner(self) -> None:
        if self._fence is not None:
            # single-writer fence: a superseded epoch must not commit
            # rule registrations, state checkpoints, or transitions
            await self._fence.ensure_valid()

    # -- rules ----------------------------------------------------------------
    async def load(self) -> tuple[dict, dict]:
        """(name -> rule, name -> state dict) from the durable records.
        Corrupt records fail loudly (module docstring); a state record
        whose rule is gone (crash between the two deletes) is dropped
        best-effort."""
        try:
            metas = await self._store.list(f"{self._root}/{RULE_PREFIX}")
        except NotFound:
            metas = []
        rules: dict = {}
        for meta in metas:
            blob = await self._store.get(meta.path)
            with context(f"decode rule record {meta.path}"):
                rule = rule_from_json(blob)
            rules[rule.name] = rule
        try:
            smetas = await self._store.list(f"{self._root}/{STATE_PREFIX}")
        except NotFound:
            smetas = []
        digests = {_digest(n): n for n in rules}
        states: dict = {}
        orphans = []
        for meta in smetas:
            key = meta.path.rsplit("/", 1)[-1]
            name = digests.get(key)
            if name is None:
                orphans.append(meta.path)
                continue
            blob = await self._store.get(meta.path)
            with context(f"decode rule state {meta.path}"):
                states[name] = json.loads(blob.decode())
        for p in orphans:
            try:
                await self._store.delete(p)
            except Exception as e:  # noqa: BLE001 — retried next open
                logger.warning("orphan rule state %s not deleted: %s", p, e)
        return rules, states

    async def put_rule(self, rule) -> None:
        """Registration durability point (fenced)."""
        await self._ensure_owner()
        with context(f"write rule record {rule.name}"):
            await self._store.put(self._rule_path(rule.name), rule.to_json())

    async def delete_rule(self, name: str) -> None:
        """Drop rule + state records. Rule first: a crash between the two
        leaves an orphan STATE record, which load() GCs — the reverse
        order would leave a rule evaluating with its state reset."""
        await self._ensure_owner()
        for path in (self._rule_path(name), self._state_path(name)):
            try:
                await self._store.delete(path)
            except NotFound:
                pass

    # -- per-rule durable state ----------------------------------------------
    async def put_state(self, name: str, state: dict) -> None:
        """One rule's state checkpoint (fenced). For alert rules this PUT
        *is* the exactly-once commit point: a transition exists iff it is
        in this record."""
        await self._ensure_owner()
        with context(f"write rule state {name}"):
            await self._store.put(
                self._state_path(name),
                json.dumps(state, sort_keys=True).encode(),
            )

    # -- the evaluator's segment-fingerprint checkpoint ----------------------
    async def load_epoch(self) -> dict | None:
        """None = no checkpoint (fresh store, or it was unreadable — the
        caller must then treat everything as potentially dirty)."""
        try:
            blob = await self._store.get(self._epoch_path())
        except NotFound:
            return None
        try:
            d = json.loads(blob.decode())
            return d if isinstance(d, dict) else None
        except Exception as e:  # noqa: BLE001 — best-effort (docstring)
            logger.warning("rule epoch checkpoint unreadable (%s); "
                           "treating all segments dirty", e)
            return None

    async def put_epoch(self, epoch: dict) -> None:
        await self._ensure_owner()
        with context("write rule epoch checkpoint"):
            await self._store.put(
                self._epoch_path(),
                json.dumps(epoch, sort_keys=True).encode(),
            )
