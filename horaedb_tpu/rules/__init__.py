"""Streaming rule engine: the push half of a production TSDB.

Every other workload in the tree is pull-at-query-time; this package adds
STANDING queries — the compute production metric platforms spend on
Prometheus recording rules and alert rules firing continuously. Taurus
NDP's argument (arXiv:2506.20010) is to push compute to where data
already flows; here that is the ingest→flush→compaction path, whose
serving-tier invalidation funnel (serving/cache.py `serving_subscribe`)
already names exactly which (root, reason, time range) just changed — so
a rule-evaluation tick with no overlapping mutations touches NOTHING.

Two rule kinds (rules/engine.py holds the evaluator):

- **Recording rules**: PromQL-bodied standing queries materialized on an
  interval-aligned step grid and written back through the NORMAL ingest
  path — first-class series: queryable, cacheable, retained, deletable,
  counted against the table's cardinality budget. Evaluation is
  INCREMENTAL: the dirty set (fed by the invalidation funnel, smeared by
  the body's max lookback window) names the output steps a mutation can
  influence; only those recompute, via the same promql evaluator a cold
  /api/v1/query_range runs — so incremental output is bit-exact vs cold
  evaluation by construction, and write-back is LWW-idempotent (re-
  evaluating a step rewrites the same value under a newer sequence).

- **Alert rules**: Prometheus semantics — the expr is evaluated as an
  instant vector at tick time (riding the serving tier's result cache
  through the engine's one query choke point); a non-empty result makes
  the series' alert active; `for` holds it pending until the duration
  elapses, then firing. State machines checkpoint through the fenced
  rule store BEFORE a transition becomes visible, so transitions are
  exactly-once across crash/reopen: a crash before the checkpoint
  re-derives the transition once; after it, the durable log already owns
  the (rule, seq) identity and re-derivation is a no-op.

Discipline: the evaluator is the ONLY invalidation-funnel consumer
besides the cache itself (jaxlint J014), evaluations run admission-
controlled as a distinct low-weight tenant ("rules") so rule storms
cannot starve dashboards, and `horaedb_rules_*` families below cover
eval latency/lag, dirty skips, alert transitions, and write degrades.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field

from horaedb_tpu.common.error import HoraeError, ensure
from horaedb_tpu.server.metrics import GLOBAL_METRICS

# -- metric families (pre-registered zero states so /metrics shows them
# -- from boot, the PR2 convention) ------------------------------------------

RULES_REGISTERED = GLOBAL_METRICS.gauge(
    "horaedb_rules_registered",
    help="Rules currently registered in the durable rule store, by kind.",
    labelnames=("kind",),
)
RULE_EVAL_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_rules_eval_seconds",
    help="One rule's evaluation (query + state/write-back) inside a "
         "tick, by kind.",
    labelnames=("kind",),
)
RULE_EVALS = GLOBAL_METRICS.counter(
    "horaedb_rules_evals_total",
    help="Rule evaluations by kind and result: ok, error (evaluation "
         "failed; retried next tick because the dirty set is only "
         "cleared on success), shed (the admission scheduler refused "
         "the low-weight rules tenant a slot — dashboards were "
         "starving it out, the design working as intended).",
    labelnames=("kind", "result"),
)
RULE_DIRTY_SKIPS = GLOBAL_METRICS.counter(
    "horaedb_rules_dirty_skips_total",
    help="Rules SKIPPED by a tick because no mutation overlapped them "
         "since their last evaluation (the dirty-set fast path: a "
         "quiet tick is O(changed rules), not O(rules)).",
    labelnames=("kind",),
)
RULE_TICKS = GLOBAL_METRICS.counter(
    "horaedb_rules_ticks_total",
    help="Evaluator ticks by result: ok (evaluated at least one rule), "
         "noop (nothing dirty, nothing active — zero evaluations).",
    labelnames=("result",),
)
RULE_EVAL_LAG = GLOBAL_METRICS.gauge(
    "horaedb_rules_eval_lag_seconds",
    help="Worst recording-rule lag at the last tick: now minus the "
         "newest materialized output step, maximized over rules. "
         "Sustained growth = the tick cannot keep up (see the "
         "rule-storm runbook in docs/operations.md).",
)
RULE_SAMPLES_WRITTEN = GLOBAL_METRICS.counter(
    "horaedb_rules_samples_written_total",
    help="Recording-rule output samples written back through the "
         "normal ingest path (first-class series).",
)
RULE_WRITE_DEGRADED = GLOBAL_METRICS.counter(
    "horaedb_rules_write_degraded_total",
    help="Recording-rule write-backs partially degraded by the table's "
         "series-cardinality budget (PR 7): rule output counts against "
         "the same limit as scrape traffic; rejected new series are "
         "counted + sampled-logged, never silently dropped.",
)
ALERT_TRANSITIONS = GLOBAL_METRICS.counter(
    "horaedb_rules_alert_transitions_total",
    help="Durable alert state transitions by edge (pending, firing, "
         "resolved). Incremented only AFTER the fenced checkpoint "
         "landed — the counter mirrors the exactly-once log.",
    labelnames=("transition",),
)
ALERTS_ACTIVE = GLOBAL_METRICS.gauge(
    "horaedb_rules_alerts_active",
    help="Alert (rule, series) pairs currently in a non-inactive "
         "state, by state.",
    labelnames=("state",),
)

for _k in ("recording", "alert"):
    RULES_REGISTERED.labels(_k).set(0)
    RULE_EVALS.labels(_k, "ok")
    RULE_EVALS.labels(_k, "error")
    RULE_EVALS.labels(_k, "shed")
    RULE_DIRTY_SKIPS.labels(_k)
for _r in ("ok", "noop"):
    RULE_TICKS.labels(_r)
for _t in ("pending", "firing", "resolved"):
    ALERT_TRANSITIONS.labels(_t)
for _s in ("pending", "firing"):
    ALERTS_ACTIVE.labels(_s).set(0)
RULE_EVAL_LAG.set(0)


_METRIC_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")


def _validate_labels(labels: dict, what: str) -> dict:
    out = {}
    for k, v in (labels or {}).items():
        ensure(bool(_LABEL_NAME_RE.match(str(k))),
               f"{what}: invalid label name {k!r}")
        ensure(str(k) != "__name__",
               f"{what}: __name__ is derived from the rule name")
        out[str(k)] = str(v)
    return out


def rule_input_metrics(expr) -> tuple:
    """Metric names the body reads (every selector), sorted — the dirty
    set's relevance filter and the self-invalidation loop guard key.
    Takes a body string or an already-parsed node."""
    from horaedb_tpu.promql import parse
    from horaedb_tpu.promql.eval import selector_metrics

    return selector_metrics(parse(expr) if isinstance(expr, str) else expr)


@dataclass(frozen=True)
class RecordingRule:
    """A PromQL-bodied standing query materialized as the first-class
    series `name` on an `interval_ms`-aligned step grid starting at
    `since_ms` (steps strictly before `since_ms` are never produced).

    `group`/`group_order`: rule-group semantics (Prometheus groups):
    members of one group share ONE interval (enforced at registration)
    and evaluate SEQUENTIALLY in (`group_order`, name) order within a
    tick, each member's write-back landing before the next member
    evaluates — so chained recording rules (B reads A's output)
    materialize deterministically in one tick instead of one tick per
    chain link. Ungrouped rules keep the batched one-write-back tick."""

    name: str
    expr: str
    interval_ms: int
    labels: dict = field(default_factory=dict)
    since_ms: int = 0
    group: str = ""
    group_order: int = 0

    kind = "recording"

    def validate(self) -> "RecordingRule":
        from horaedb_tpu.promql import parse

        ensure(bool(_METRIC_NAME_RE.match(self.name)),
               f"invalid recording rule name {self.name!r} "
               "(must be a valid metric name)")
        ensure(self.interval_ms > 0,
               f"rule {self.name}: interval must be > 0")
        ensure("\n" not in self.group and len(self.group) <= 256,
               f"rule {self.name}: invalid group name")
        parse(self.expr)  # raises PromQLError on a bad body
        _validate_labels(self.labels, f"rule {self.name}")
        return self

    @property
    def input_metrics(self) -> tuple:
        return rule_input_metrics(self.expr)

    def identity(self) -> tuple:
        """Definition identity WITHOUT since_ms (which defaults to the
        registration clock): a config-declared rule re-asserted at every
        boot must compare equal to its durable self, or each restart
        would reset its watermark."""
        return ("recording", self.name, self.expr, self.interval_ms,
                tuple(sorted(self.labels.items())),
                self.group, self.group_order)

    def to_json(self) -> bytes:
        return json.dumps({
            "kind": "recording", "name": self.name, "expr": self.expr,
            "interval_ms": self.interval_ms, "labels": self.labels,
            "since_ms": self.since_ms,
            "group": self.group, "group_order": self.group_order,
        }).encode()


@dataclass(frozen=True)
class AlertRule:
    """Prometheus-style alert: `expr` evaluated as an instant vector at
    tick time; each returned series is an active alert, held `pending`
    for `for_ms` before `firing` (for_ms=0 fires immediately)."""

    name: str
    expr: str
    for_ms: int = 0
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)

    kind = "alert"

    def validate(self) -> "AlertRule":
        from horaedb_tpu.promql import parse

        ensure(bool(_METRIC_NAME_RE.match(self.name)),
               f"invalid alert rule name {self.name!r}")
        ensure(self.for_ms >= 0, f"rule {self.name}: for must be >= 0")
        parse(self.expr)
        _validate_labels(self.labels, f"rule {self.name}")
        ensure("alertname" not in self.labels,
               f"rule {self.name}: 'alertname' is the alert's identity "
               "(derived from the rule name)")
        return self

    @property
    def input_metrics(self) -> tuple:
        return rule_input_metrics(self.expr)

    def identity(self) -> tuple:
        return ("alert", self.name, self.expr, self.for_ms,
                tuple(sorted(self.labels.items())),
                tuple(sorted((str(k), str(v))
                             for k, v in self.annotations.items())))

    def to_json(self) -> bytes:
        return json.dumps({
            "kind": "alert", "name": self.name, "expr": self.expr,
            "for_ms": self.for_ms, "labels": self.labels,
            "annotations": {str(k): str(v)
                            for k, v in self.annotations.items()},
        }).encode()


def rule_from_json(data: bytes):
    """Decode one durable rule record. Raises HoraeError on corruption —
    silently skipping a rule record would silently stop a standing query
    (the tombstone-load policy, not the rollup one: rules are
    correctness-bearing state, not a performance artifact)."""
    try:
        d = json.loads(data.decode())
        kind = d["kind"]
        if kind == "recording":
            return RecordingRule(
                name=str(d["name"]), expr=str(d["expr"]),
                interval_ms=int(d["interval_ms"]),
                labels=dict(d.get("labels") or {}),
                since_ms=int(d.get("since_ms", 0)),
                group=str(d.get("group", "")),
                group_order=int(d.get("group_order", 0)),
            ).validate()
        if kind == "alert":
            return AlertRule(
                name=str(d["name"]), expr=str(d["expr"]),
                for_ms=int(d.get("for_ms", 0)),
                labels=dict(d.get("labels") or {}),
                annotations=dict(d.get("annotations") or {}),
            ).validate()
        raise HoraeError(f"unknown rule kind {kind!r}")
    except HoraeError:
        raise
    except Exception as e:  # noqa: BLE001 — corrupt record, typed error
        raise HoraeError(f"corrupt rule record: {e}") from e


def rule_from_dict(d: dict, now_ms: int):
    """Build + validate one rule from an API/config dict."""
    from horaedb_tpu.common.time_ext import ReadableDuration

    ensure(isinstance(d, dict), "rule must be an object")
    kind = str(d.get("kind", "")).lower()
    unknown_base = set(d) - {
        "kind", "name", "expr", "interval", "for", "labels", "annotations",
        "since_ms", "group", "group_order",
    }
    ensure(not unknown_base, f"unknown rule keys: {sorted(unknown_base)}")
    ensure(bool(d.get("name")), "rule needs a name")
    ensure(bool(d.get("expr")), "rule needs an expr")

    def dur_ms(key: str, default_ms: int) -> int:
        v = d.get(key)
        if v in (None, ""):
            return default_ms
        if isinstance(v, (int, float)):
            return int(v * 1000)  # bare seconds, Prometheus-style
        return ReadableDuration.parse(str(v)).as_millis()

    if kind == "recording":
        ensure("for" not in d, "recording rules take no `for`")
        ensure("annotations" not in d,
               "recording rules take no annotations")
        return RecordingRule(
            name=str(d["name"]), expr=str(d["expr"]),
            interval_ms=dur_ms("interval", 60_000),
            labels=dict(d.get("labels") or {}),
            since_ms=int(d.get("since_ms", now_ms)),
            group=str(d.get("group", "") or ""),
            group_order=int(d.get("group_order", 0)),
        ).validate()
    if kind == "alert":
        ensure("group" not in d and "group_order" not in d,
               "groups order recording-rule chains; alert rules "
               "evaluate every tick already")
        ensure("interval" not in d,
               "alert rules evaluate on the engine tick; no per-rule "
               "interval")
        ensure("since_ms" not in d, "alert rules take no since_ms")
        return AlertRule(
            name=str(d["name"]), expr=str(d["expr"]),
            for_ms=dur_ms("for", 0),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
        ).validate()
    raise HoraeError(
        f"rule kind must be 'recording' or 'alert', got {kind!r}"
    )
