"""Asyncio compatibility shims.

`TaskGroup` is `asyncio.TaskGroup` on Python >= 3.11; on 3.10 images a
minimal structured-concurrency backport with the same contract the
engine relies on: children run concurrently, the first child failure
cancels the siblings, and exiting the block never leaks a running task
(including tasks a child spawned during the drain, and on parent
cancellation mid-drain).
Documented divergences from the real one (acceptable for the engine's
exit-block-immediately call sites; revisit before leaning on them):
- a lone child failure re-raises the exception itself rather than
  wrapping it in an ExceptionGroup (no caller uses `except*`);
- a child failure does NOT abort the body mid-flight — siblings are
  only cancelled at block exit, where the real TaskGroup cancels the
  moment the child fails;
- if the BODY raises, children are cancelled and reaped but their own
  exceptions are discarded rather than grouped with the body's.
"""

from __future__ import annotations

import asyncio

try:
    TaskGroup = asyncio.TaskGroup  # Python >= 3.11
except AttributeError:

    class TaskGroup:  # type: ignore[no-redef]
        def __init__(self) -> None:
            self._tasks: list[asyncio.Task] = []
            self._entered = False
            self._finished = False

        async def __aenter__(self) -> "TaskGroup":
            self._entered = True
            return self

        def create_task(self, coro, *, name=None) -> asyncio.Task:
            # like the real TaskGroup: spawning before entry or after
            # exit is a bug (nobody would supervise the task), and
            # calling from sync code must raise (get_running_loop), not
            # queue on a fresh never-run loop
            if not self._entered:
                coro.close()
                raise RuntimeError("TaskGroup has not been entered")
            if self._finished:
                coro.close()
                raise RuntimeError("TaskGroup is finished")
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                coro.close()  # refuse cleanly: no orphan coroutine warning
                raise
            t = loop.create_task(coro, name=name)
            self._tasks.append(t)
            return t

        async def _reap_all(self) -> None:
            """Cancel and await every outstanding child. Loops on a FRESH
            snapshot each round: a child's except/finally handler may
            spawn more tasks via create_task while we reap, and those
            must not outlive the block either. A SECOND parent
            cancellation delivered mid-reap must not abort the reap —
            finish reaping first, then re-raise it, or children outlive
            the block."""
            interrupted: BaseException | None = None
            while True:
                pending = [t for t in self._tasks if not t.done()]
                if not pending:
                    break
                for t in pending:
                    t.cancel()
                try:
                    await asyncio.gather(*pending, return_exceptions=True)
                except BaseException as e:  # re-delivered parent cancel
                    interrupted = e
            if interrupted is not None:
                raise interrupted

        async def __aexit__(self, exc_type, exc, tb) -> bool:
            try:
                if exc is not None:
                    # body raised (incl. CancelledError): abort children
                    await self._reap_all()
                    return False
                first: BaseException | None = None
                try:
                    while True:
                        # re-snapshot each round: a child may have
                        # spawned siblings during the drain — the real
                        # TaskGroup joins those too
                        pending = {t for t in self._tasks if not t.done()}
                        if not pending:
                            break
                        if first is not None:
                            await self._reap_all()
                            continue
                        done, _ = await asyncio.wait(
                            pending, return_when=asyncio.FIRST_EXCEPTION
                        )
                        for t in done:
                            if t.cancelled():
                                continue
                            e = t.exception()
                            if e is not None and first is None:
                                first = e
                except BaseException:
                    # the PARENT was cancelled (or the wait machinery
                    # failed) mid-drain: children must not outlive the
                    # block — reap before propagating, or shutdown-time
                    # cancels leave writers running against a closing
                    # store
                    await self._reap_all()
                    raise
                if first is not None:
                    raise first
                return False
            finally:
                self._finished = True
