"""Buffer-lineage ledger + copy-tax accounting for the data plane.

ROADMAP item 2 (the Arrow-native zero-copy data plane) demands
allocation-count regression tests on the scan path — but nothing in the
engine could SEE an allocation or a copy: ROOFLINE §4's copy-tax figure
was hand-derived. This module is the instrument. Every data-plane
hand-off (pooled-parser append, memtable seal/drain, flush encode,
parquet materialize, encoded-lane decode, host_prep lane conversion,
`jax.device_put` staging, cache/residency fills, the cluster wire codec)
reports through ONE cheap funnel:

    track(buf, "materialize", "copy")        # size read off the buffer
    track_bytes(n, "h2d", "copy")            # size known directly
    arr = tracked_contiguous(arr, "wire_codec")   # the J024 funnel
    out = tracked_combine(table, "materialize")   # copy vs view decided
                                                  # by the chunk layout

Aggregation is two-level, mirroring storage/scanstats.py:

- **process-wide**: `horaedb_mem_bytes_total{stage,kind}` /
  `horaedb_mem_events_total{stage,kind}` counter families (+ the
  `horaedb_mem_device_staging_bytes_total` staging odometer) — the
  copy-tax table `GET /debug/memory` renders comes straight from these.
- **per-query**: a `MemLedger` contextvar opened by
  `scanstats.scan_stats()`, folded into the pinned `memory` EXPLAIN
  verdict (bytes allocated, copies vs views per stage, device staging
  bytes, peak-delta under deep mode).

Modes (`HORAEDB_MEMTRACE`, overridable via `[metric_engine.memory]`):

- `""` (default) — cheap lineage: one dict update on the per-query
  ledger + one cached counter inc per event. No tracemalloc.
- `"deep"` — per-query tracemalloc sampling: peak-delta bytes and the
  top allocation sites ride the verdict. Opt-in; attribution quality
  over speed.
- `"off"`  — `track()` returns its argument immediately; the funnel
  helpers still perform the underlying operation (the data path is
  IDENTICAL in every mode — only the accounting varies). mem-smoke
  measures this mode against the default to pin the <2% overhead bound.

Kinds are a closed vocabulary:

- `alloc` — a fresh buffer with no parent (arena growth, np.empty)
- `copy`  — bytes physically duplicated from a parent buffer
- `view`  — a new handle over existing bytes (zero-copy)
- `reuse` — a pooled buffer re-issued without allocation
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from contextvars import ContextVar

from horaedb_tpu.server.metrics import GLOBAL_METRICS

KINDS = ("alloc", "copy", "view", "reuse")

# Canonical lineage stages (the hand-off inventory in the module
# docstring). track() accepts any stage string — these are pre-registered
# so /metrics exposes the full copy-tax surface from boot (zero-count
# children), the same eager zero-state contract every other family keeps.
STAGES = (
    "parse", "append", "seal", "flush_encode", "materialize", "host_prep",
    "decode", "h2d", "result_fill", "residency_fill", "rollup_fill",
    "wire_codec",
)

MEM_BYTES = GLOBAL_METRICS.counter(
    "horaedb_mem_bytes_total",
    help="Data-plane bytes by lineage stage and kind (alloc|copy|view|"
         "reuse): the process-lifetime copy-tax ledger.",
    labelnames=("stage", "kind"),
)
MEM_EVENTS = GLOBAL_METRICS.counter(
    "horaedb_mem_events_total",
    help="Data-plane buffer hand-off events by lineage stage and kind.",
    labelnames=("stage", "kind"),
)
DEVICE_STAGING = GLOBAL_METRICS.counter(
    "horaedb_mem_device_staging_bytes_total",
    help="Bytes staged host->device through the tracked jax.device_put "
         "hand-offs (a subset of the copy rows above, split out because "
         "transfer is its own roofline lane).",
)

# Label-resolution is a dict probe + lock in the registry; the hot path
# caches children per (stage, kind) so steady-state cost is one dict hit
# + one locked float add per family.
_BYTES_CHILD: dict = {}
_EVENTS_CHILD: dict = {}
for _s in STAGES:
    for _k in KINDS:
        _BYTES_CHILD[(_s, _k)] = MEM_BYTES.labels(_s, _k)
        _EVENTS_CHILD[(_s, _k)] = MEM_EVENTS.labels(_s, _k)
del _s, _k

_VALID_MODES = ("", "deep", "off")
MODES = _VALID_MODES  # public face (server/config.py validation)


def env_default() -> str:
    mode = os.environ.get("HORAEDB_MEMTRACE", "")
    return mode if mode in _VALID_MODES else ""


_MODE = env_default()


def configure(mode: str) -> None:
    """Set the tracing mode ("" | "deep" | "off"). build_app applies
    `[metric_engine.memory] memtrace`; tests pin modes explicitly."""
    global _MODE
    if mode not in _VALID_MODES:
        from horaedb_tpu.common.error import HoraeError

        raise HoraeError(
            f"memory.memtrace must be one of {_VALID_MODES}, got {mode!r}"
        )
    _MODE = mode


def mode() -> str:
    return _MODE


class MemLedger:
    """Per-query lineage accumulator. Unlocked dict updates, the same
    concurrency posture as ScanStats: concurrent per-SST workers under
    one query share the ledger via the copied context and the GIL makes
    torn totals vanishingly unlikely next to segment-sized work."""

    __slots__ = ("events", "device_bytes", "peak_delta", "top_sites")

    def __init__(self) -> None:
        # (stage, kind) -> [events, bytes]
        self.events: dict[tuple[str, str], list] = {}
        self.device_bytes = 0
        self.peak_delta: int | None = None
        self.top_sites: list[dict] = []

    def add(self, stage: str, kind: str, nbytes: int) -> None:
        cell = self.events.get((stage, kind))
        if cell is None:
            self.events[(stage, kind)] = [1, nbytes]
        else:
            cell[0] += 1
            cell[1] += nbytes

    def merge(self, other: "MemLedger") -> None:
        """Fold a fragment's ledger in (the cluster coordinator grafts
        computing-node verdicts through verdict_merge, not this)."""
        for key, (n, b) in other.events.items():
            cell = self.events.get(key)
            if cell is None:
                self.events[key] = [n, b]
            else:
                cell[0] += n
                cell[1] += b
        self.device_bytes += other.device_bytes


_ACTIVE: ContextVar[MemLedger | None] = ContextVar(
    "horaedb_mem_ledger", default=None
)


@contextmanager
def mem_trace():
    """Open a per-query ledger (scan_stats() does this for every query
    route). Yields None in `off` mode — callers treat the ledger as
    opaque and read it back through verdict()."""
    if _MODE == "off":
        yield None
        return
    ledger = MemLedger()
    deep = _MODE == "deep"
    baseline = 0
    started_here = False
    if deep:
        import tracemalloc

        if not tracemalloc.is_tracing():
            tracemalloc.start()
            started_here = True
        baseline = tracemalloc.get_traced_memory()[0]
        tracemalloc.reset_peak()
    token = _ACTIVE.set(ledger)
    try:
        yield ledger
    finally:
        _ACTIVE.reset(token)
        if deep:
            import tracemalloc

            if tracemalloc.is_tracing():
                _current, peak = tracemalloc.get_traced_memory()
                ledger.peak_delta = max(0, peak - baseline)
                stats = tracemalloc.take_snapshot().statistics("lineno")
                ledger.top_sites = [
                    {
                        "site": f"{st.traceback[0].filename}:"
                                f"{st.traceback[0].lineno}",
                        "kib": round(st.size / 1024, 1),
                        "count": st.count,
                    }
                    for st in stats[:8]
                ]
                if started_here:
                    tracemalloc.stop()


def active() -> "MemLedger | None":
    return _ACTIVE.get()


def _nbytes(buf) -> int:
    """Best-effort size of a buffer-ish object: numpy arrays, jax arrays,
    pyarrow Tables/Arrays/Buffers all expose .nbytes; bytes-like fall
    back to len; everything else counts 0 (the EVENT still counts)."""
    nb = getattr(buf, "nbytes", None)
    if nb is not None:
        try:
            return int(nb)
        except (TypeError, ValueError):
            return 0
    if isinstance(buf, (bytes, bytearray, memoryview)):
        return len(buf)
    return 0


def track(buf, stage: str, kind: str = "copy"):
    """Record one buffer hand-off; returns `buf` so call sites can wrap
    expressions in-line. Off mode: one string compare, nothing else."""
    if _MODE == "off":
        return buf
    track_bytes(_nbytes(buf), stage, kind)
    return buf


def track_bytes(nbytes: int, stage: str, kind: str = "copy") -> None:
    """track() when the size is already known (spares the attr probe)."""
    if _MODE == "off":
        return
    key = (stage, kind)
    bc = _BYTES_CHILD.get(key)
    if bc is None:  # non-canonical stage: resolve once, then cached
        bc = _BYTES_CHILD[key] = MEM_BYTES.labels(*key)
        _EVENTS_CHILD[key] = MEM_EVENTS.labels(*key)
    bc.inc(nbytes)
    _EVENTS_CHILD[key].inc()
    ledger = _ACTIVE.get()
    if ledger is not None:
        ledger.add(stage, kind, nbytes)


def device_staged(nbytes: int, stage: str = "h2d") -> None:
    """Record a host->device staging transfer (jax.device_put and the
    Block upload paths): a copy row under `stage` PLUS the dedicated
    staging odometer and the verdict's device_staging_bytes."""
    if _MODE == "off":
        return
    track_bytes(nbytes, stage, "copy")
    DEVICE_STAGING.inc(nbytes)
    ledger = _ACTIVE.get()
    if ledger is not None:
        ledger.device_bytes += nbytes


# ---------------------------------------------------------------------------
# Funnel helpers — the J024-sanctioned spellings of the raw copy
# primitives on data-plane modules. Each performs EXACTLY the underlying
# operation and decides copy-vs-view honestly from the result.


def tracked_contiguous(arr, stage: str):
    """np.ascontiguousarray through the funnel: `view` when the input was
    already contiguous (numpy returns it unchanged), `copy` otherwise."""
    import numpy as np

    out = np.ascontiguousarray(arr)
    if _MODE != "off":
        track_bytes(
            int(out.nbytes), stage, "view" if out is arr else "copy"
        )
    return out


def tracked_copy(arr, stage: str):
    """Explicit `.copy()` through the funnel — always a copy."""
    out = arr.copy()
    if _MODE != "off":
        track_bytes(_nbytes(out), stage, "copy")
    return out


def tracked_concat(arrays, stage: str, axis: int = 0):
    """np.concatenate through the funnel — always materializes."""
    import numpy as np

    out = np.concatenate(arrays, axis=axis)
    if _MODE != "off":
        track_bytes(int(out.nbytes), stage, "copy")
    return out


def tracked_combine(obj, stage: str):
    """`.combine_chunks()` through the funnel: a single-chunk (or empty)
    Table/ChunkedArray combines without moving bytes (`view`); multiple
    chunks physically concatenate (`copy`)."""
    columns = getattr(obj, "columns", None)
    if columns is not None:  # pa.Table
        multi = any(col.num_chunks > 1 for col in columns)
    else:  # pa.ChunkedArray
        multi = obj.num_chunks > 1
    out = obj.combine_chunks()
    if _MODE != "off":
        track_bytes(_nbytes(out), stage, "copy" if multi else "view")
    return out


def tracked_concat_tables(tables, stage: str, **kw):
    """pa.concat_tables through the funnel — chunk aggregation, zero-copy
    (`view`): the result references the input buffers."""
    import pyarrow as pa

    out = pa.concat_tables(tables, **kw)
    if _MODE != "off":
        track_bytes(_nbytes(out), stage, "view")
    return out


# ---------------------------------------------------------------------------
# Verdict — the pinned EXPLAIN `memory` payload.

VERDICT_KEYS = (
    "enabled", "deep", "bytes_allocated", "bytes_copied", "allocs",
    "copies", "views", "reuses", "device_staging_bytes",
    "peak_delta_bytes", "per_stage", "top_sites",
)


def verdict(ledger: "MemLedger | None") -> dict:
    """Fold a ledger into the pinned `memory` EXPLAIN schema. None (off
    mode) renders the same keys with zero values and enabled=False, so
    dashboards never branch on key presence."""
    out = {
        "enabled": ledger is not None,
        "deep": False,
        "bytes_allocated": 0,
        "bytes_copied": 0,
        "allocs": 0,
        "copies": 0,
        "views": 0,
        "reuses": 0,
        "device_staging_bytes": 0,
        "peak_delta_bytes": None,
        "per_stage": {},
        "top_sites": [],
    }
    if ledger is None:
        return out
    per_stage: dict[str, dict] = {}
    for (stage, kind), (n, b) in sorted(ledger.events.items()):
        row = per_stage.setdefault(stage, {})
        row[kind] = n
        row[f"{kind}_bytes"] = b
        out[f"{kind}s" if kind != "copy" else "copies"] += n
        if kind in ("alloc", "copy"):
            out["bytes_allocated"] += b
        if kind == "copy":
            out["bytes_copied"] += b
    out["per_stage"] = per_stage
    out["device_staging_bytes"] = ledger.device_bytes
    out["peak_delta_bytes"] = ledger.peak_delta
    out["deep"] = ledger.peak_delta is not None
    out["top_sites"] = ledger.top_sites
    return out


def verdict_merge(base: dict, fragment: dict) -> dict:
    """Fold a computing node's shipped `memory` verdict into the
    coordinator's (the fleet-EXPLAIN graft): scalars add, per-stage rows
    add, peak-delta takes the max (peaks on different nodes do not sum),
    top sites concatenate and re-rank."""
    if not fragment or not fragment.get("enabled"):
        return base
    out = dict(base)
    out["enabled"] = True
    for k in ("bytes_allocated", "bytes_copied", "allocs", "copies",
              "views", "reuses", "device_staging_bytes"):
        out[k] = out.get(k, 0) + fragment.get(k, 0)
    per = {s: dict(row) for s, row in out.get("per_stage", {}).items()}
    for stage, row in fragment.get("per_stage", {}).items():
        mine = per.setdefault(stage, {})
        for k, v in row.items():
            mine[k] = mine.get(k, 0) + v
    out["per_stage"] = per
    peaks = [p for p in (out.get("peak_delta_bytes"),
                         fragment.get("peak_delta_bytes")) if p is not None]
    out["peak_delta_bytes"] = max(peaks) if peaks else None
    out["deep"] = out["peak_delta_bytes"] is not None
    sites = list(out.get("top_sites", ())) + list(
        fragment.get("top_sites", ()))
    out["top_sites"] = sorted(
        sites, key=lambda s: -s.get("kib", 0))[:8]
    return out


def copy_tax_table() -> list[dict]:
    """The process-lifetime per-stage copy-tax table (/debug/memory):
    one row per (stage, kind) seen since boot, ranked by bytes."""
    rows = []
    for (stage, kind), child in list(_BYTES_CHILD.items()):
        b = child.value
        n = _EVENTS_CHILD[(stage, kind)].value
        if n:
            rows.append({"stage": stage, "kind": kind,
                         "events": int(n), "bytes": int(b)})
    rows.sort(key=lambda r: -r["bytes"])
    return rows
