"""Shared accelerator-tunnel probe with a disk-cached verdict.

BENCH_r03–r05 each burned 5–10 minutes re-proving the same wedged tunnel:
the tiny-matmul probe timed out at 120–400 s per attempt, several attempts
per round, and bench.py AND server startup each paid it separately. The
tunnel's health does not flip between those callers, so the verdict is
cached on disk with a TTL and shared:

- `device_responsive()` — the subprocess probe bench.py uses (a wedged
  remote-TPU runtime hangs uninterruptibly inside `jax.devices()`, so the
  probe must be killable). Consults the cache first, writes it after.
- `HORAEDB_LINK_PROFILE={host|device|skip}` skips probing entirely:
  `host`/`skip` mean "plan as if the device is unreachable, pay nothing",
  `device` means "trust the device without proving it". Anything else
  (or unset) means auto.
- `HORAEDB_PROBE_TTL_S` (default 1800) bounds verdict staleness;
  `HORAEDB_PROBE_CACHE` overrides the cache file path.

Import-light by design: bench.py must be able to import this BEFORE the
jax runtime initializes (probing after `import jax` is too late — the
import itself can hang on a wedged tunnel).
"""

from __future__ import annotations

import json
import os
import tempfile
import time

VALID_OVERRIDES = ("host", "device", "skip")
DEFAULT_TIMEOUTS = (60, 150)  # one fast attempt + one cold-compile budget
_PROBE_CODE = (
    "import jax, jax.numpy as jnp, numpy as np;"
    "x = jnp.ones((128, 128));"
    "print(float(np.asarray((x @ x).sum())))"
)


def override() -> str | None:
    """The HORAEDB_LINK_PROFILE override, or None for auto. Unknown values
    fail loudly — a typo'd override silently probing would re-pay exactly
    the minutes this knob exists to save."""
    mode = os.environ.get("HORAEDB_LINK_PROFILE", "").strip().lower()
    if not mode or mode == "auto":
        return None
    if mode not in VALID_OVERRIDES:
        raise ValueError(
            f"HORAEDB_LINK_PROFILE={mode!r} is not one of "
            f"{'/'.join(VALID_OVERRIDES)} (or auto/unset)"
        )
    return mode


def cache_path() -> str:
    env = os.environ.get("HORAEDB_PROBE_CACHE")
    if env:
        return env
    return os.path.join(tempfile.gettempdir(), "horaedb-tpu",
                        "linkprobe.json")


def _ttl_s() -> float:
    try:
        return float(os.environ.get("HORAEDB_PROBE_TTL_S", "1800"))
    except ValueError:
        return 1800.0


def cached_verdict() -> tuple[bool, str] | None:
    """(ok, reason) from a fresh-enough cached probe, else None."""
    try:
        with open(cache_path(), encoding="utf-8") as f:
            data = json.load(f)
        age = time.time() - float(data["unix"])
        if 0 <= age <= _ttl_s():
            return bool(data["ok"]), (
                f"{data.get('reason', 'cached probe')} "
                f"[cached {int(age)}s ago]"
            )
    except (OSError, ValueError, KeyError, TypeError):
        pass
    return None


def store_verdict(ok: bool, reason: str) -> None:
    path = cache_path()
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                   prefix=".linkprobe.")
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump({"ok": ok, "reason": reason, "unix": time.time()}, f)
        os.replace(tmp, path)
    except OSError:
        pass  # cache is an optimization, never a failure


def _probe_subprocess(timeouts) -> tuple[bool, str]:
    """Tiny-matmul probe in killable subprocesses, growing budgets."""
    import subprocess
    import sys

    reasons = []
    for attempt, timeout_s in enumerate(timeouts):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, timeout=timeout_s,
            )
            if out.returncode == 0:
                return True, f"probe ok (attempt {attempt + 1})"
            reasons.append(
                f"attempt {attempt + 1}: rc={out.returncode} "
                f"{out.stderr.decode(errors='replace')[-200:]}"
            )
        except subprocess.TimeoutExpired:
            # the probe is a 128x128 matmul — worst-case legitimate cost is
            # one cold compile (~40 s); a 60 s+ timeout is the TUNNEL
            # wedged, not a slow kernel (VERDICT r03 #1)
            reasons.append(
                f"attempt {attempt + 1}: tunnel wedged "
                f"(tiny-matmul probe timed out after {timeout_s}s)"
            )
        if attempt + 1 < len(timeouts):
            time.sleep(10)
    return False, "; ".join(reasons)


def device_responsive(
    timeouts=DEFAULT_TIMEOUTS, use_cache: bool = True
) -> tuple[bool, str]:
    """Is the default accelerator reachable? Order: env override (free) >
    fresh cached verdict (free) > killable subprocess probe (cached after).
    `use_cache=False` forces a live probe — the bench's last-chance
    recovery retry must not read back the wedged verdict it just wrote."""
    mode = override()
    if mode in ("host", "skip"):
        return False, f"HORAEDB_LINK_PROFILE={mode}: probe skipped"
    if mode == "device":
        return True, "HORAEDB_LINK_PROFILE=device: probe skipped"
    if use_cache:
        cached = cached_verdict()
        if cached is not None:
            return cached
    ok, reason = _probe_subprocess(timeouts)
    store_verdict(ok, reason)
    return ok, reason
