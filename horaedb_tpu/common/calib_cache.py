"""Shared disk-backed calibration-cache for the self-calibrating
dispatchers (`ops/agg_registry.py`, `ops/decode.py`).

Both registries memoize micro-A/B verdicts the same way: a JSON file
under the engine's data root (env-overridable per registry), an
in-memory view guarded by a lock, a `version` stamp plus optional
inventory fields that invalidate the whole file when the impl set
changes, and an atomic mkstemp + os.replace publish so readers never
see a torn file. This is the ONE copy of that machinery — a fix here
(e.g. the store-ordering guarantee below) reaches every registry.

The single lock covers mutation AND the file write: a concurrent
store_entry can never clobber a newer payload with a stale one (the
old per-registry copies serialized the payload under the lock but
raced the os.replace outside it)."""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Callable


class CalibCache:
    """One registry's calibration file: `env_var` overrides the full
    path, otherwise `filename` under the configured dir (engine data
    root) or the tmpdir fallback. `inventory`, when given, returns
    extra top-level fields that must match on load (impl-set change
    => full recalibration) and are rewritten on every store."""

    def __init__(self, *, env_var: str, filename: str, version: int,
                 tmp_prefix: str,
                 inventory: Callable[[], dict] | None = None) -> None:
        self._env_var = env_var
        self._filename = filename
        self._version = version
        self._tmp_prefix = tmp_prefix
        self._inventory = inventory
        self._lock = threading.Lock()
        self._dir_override: str | None = None
        self._mem: dict | None = None

    def configure_dir(self, path: str) -> None:
        """Point the cache under the engine's data root (called by
        storage bring-up); the env var still overrides with a full
        file path."""
        with self._lock:
            self._dir_override = path
            self._mem = None

    def path(self) -> str:
        env = os.environ.get(self._env_var)
        if env:
            return env
        base = self._dir_override or os.path.join(
            tempfile.gettempdir(), "horaedb-tpu"
        )
        return os.path.join(base, self._filename)

    def reset(self, memory_only: bool = False) -> None:
        """Drop the in-memory view (tests); optionally leave the file."""
        with self._lock:
            self._mem = None
        if not memory_only:
            try:
                os.unlink(self.path())
            except OSError:
                pass

    def load(self) -> dict:
        with self._lock:
            if self._mem is not None:
                return self._mem
            data: dict = {}
            try:
                with open(self.path(), encoding="utf-8") as f:
                    raw = json.load(f)
                expect = self._inventory() if self._inventory else {}
                if (
                    isinstance(raw, dict)
                    and raw.get("version") == self._version
                    and all(raw.get(k) == v for k, v in expect.items())
                ):
                    data = raw
                # registry changed (new/removed impls or format):
                # recalibrate from scratch
            except (OSError, ValueError):
                pass
            self._mem = data
            return data

    def store_entry(self, key: str, entry: dict) -> None:
        path = self.path()
        with self._lock:
            data = self._mem if self._mem else {}
            data.setdefault("version", self._version)
            if self._inventory:
                data.update(self._inventory())
            data.setdefault("entries", {})[key] = entry
            self._mem = data
            payload = json.dumps(data, indent=1, sort_keys=True)
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                fd, tmp = tempfile.mkstemp(
                    dir=os.path.dirname(path) or ".",
                    prefix=self._tmp_prefix,
                )
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    f.write(payload)
                # atomic publish: readers never see a torn file
                os.replace(tmp, path)
            except OSError:
                # cache is an optimization; an unwritable root costs a
                # re-A/B, nothing else
                pass
