"""SeaHash — the framework's shared byte-hash.

Reference: src/metric_engine/src/types.rs:18-41 pins seahash as the id hash;
the storage layer reuses it for SST bloom-filter probes so the same function
serves both. From-scratch implementation of the public portable algorithm
(seed-fixed variant of the seahash crate's `hash()`); conformance is pinned
by the crate's documented test vector in tests/test_engine.py, and the C++
port in native/remote_write_parser.cc is differentially tested against this
one.
"""

from __future__ import annotations

import struct

_MASK = (1 << 64) - 1
_P = 0x6EED_0E9D_A4D9_4A4F
# Default seeds of seahash::hash (crate src: lib.rs).
_SEEDS = (
    0x16F1_1FE8_9B0D_677C,
    0xB480_A793_D8E6_C86C,
    0x6FE2_E5AA_F078_EBC9,
    0x14F9_94A4_C525_9381,
)


def _diffuse(x: int) -> int:
    x = (x * _P) & _MASK
    x ^= (x >> 32) >> (x >> 60)
    return (x * _P) & _MASK


def seahash(data: bytes) -> int:
    """SeaHash of `data` with the default seeds."""
    a, b, c, d = _SEEDS
    n = len(data)
    # full 8-byte little-endian chunks, round-robin over the four lanes
    full = n & ~7
    lanes = [a, b, c, d]
    i = 0
    lane = 0
    while i < full:
        (chunk,) = struct.unpack_from("<Q", data, i)
        lanes[lane] = _diffuse(lanes[lane] ^ chunk)
        lane = (lane + 1) & 3
        i += 8
    if i < n:
        tail = data[i:] + b"\x00" * (8 - (n - i))
        (chunk,) = struct.unpack_from("<Q", tail, 0)
        lanes[lane] = _diffuse(lanes[lane] ^ chunk)
    a, b, c, d = lanes
    a ^= b
    c ^= d
    a ^= c
    a ^= n
    return _diffuse(a)
