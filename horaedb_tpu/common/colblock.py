"""Arrow-compatible column blocks: ONE typed, contiguous buffer contract
from memtable to HBM (ROADMAP item 2, the Arrow-native zero-copy spine).

Every data-plane layer used to re-materialize its own private copy of
the same columns — the pooled parser into arena arrays, the memtable
seal into concatenated lanes, the reader through `combine_chunks`, the
device staging through `np.ascontiguousarray`. memtrace (PR 19) made
each of those hand-offs visible as a `copy` event; this module makes
them unnecessary by giving all layers one block type to pass BY
REFERENCE:

- **ColBlock** — named, typed, 1-D column lanes over contiguous
  64-byte-aligned backing with a mutability contract: a block starts
  writable (single owner), `freeze()` bumps its epoch and flips every
  public lane read-only. After the freeze any number of consumers may
  hold the block; sharing it is a `reuse` event, mutating it requires
  the sanctioned `cow()` (a tracked copy) — writes through a frozen
  lane raise. Device staging (`to_device`) exports the internal
  writable backing straight through `jax.device_put`, so the H2D
  transfer is charged exactly once (`device_staged`) with NO
  intermediate host staging copy.
- **GrowableColBlock** — the ingest arena: geometric growth (tracked
  `alloc`), steady-state appends into preallocated capacity (tracked
  `reuse` via adopt_spare), `seal()` detaches the filled prefix as a
  frozen ColBlock of zero-copy views and returns the backing for the
  double-buffer spare pool.
- **ArrowLanes** — chunk-aware lane access over a (possibly chunked)
  pyarrow Table: per-chunk zero-copy numpy views (`chunks`), a
  sorted-index gather that never materializes the full column
  (`gather_sorted`), and a contiguous-lane fallback (`lane`) that is a
  view for single-chunk columns and ONE sanctioned tracked copy
  otherwise. The scan merge consumes lanes chunk-wise, so the four
  per-column `combine_chunks` copies the r19 baseline pinned on
  host_prep disappear.

Constructing a fresh numpy array from a block's data OUTSIDE these
sanctioned APIs in data-plane modules is a jaxlint J025 finding — the
static twin of the memtrace runtime gate.
"""

from __future__ import annotations

import numpy as np

from horaedb_tpu.common import memtrace
from horaedb_tpu.common.error import HoraeError, ensure

# One TPU lane / x86 cacheline: jax.device_put on XLA:CPU can reuse
# aligned contiguous host buffers without an intermediate repack, and
# parquet/dlpack consumers never see a misaligned lane.
ALIGNMENT = 64


def aligned_empty(n: int, dtype) -> np.ndarray:
    """Uninitialized 1-D array whose data pointer is ALIGNMENT-aligned
    (numpy only guarantees 16). Over-allocates one alignment unit of u8
    and slices to the aligned offset; the returned array keeps the raw
    buffer alive via .base."""
    dt = np.dtype(dtype)
    nbytes = int(n) * dt.itemsize
    raw = np.empty(nbytes + ALIGNMENT, dtype=np.uint8)
    off = (-raw.ctypes.data) % ALIGNMENT
    return raw[off:off + nbytes].view(dt)


class ColBlock:
    """Named typed column lanes with a stable memory contract.

    Ownership protocol:

    1. build writable (``alloc`` / ``wrap``), fill lanes in place;
    2. ``freeze()`` — epoch bump, public lanes flip read-only;
    3. hand the block around by reference: ``share()`` records the
       `reuse`, ``lane()`` hands out read-only views, ``to_device()``
       stages via the internal writable backing (one `device_staged`
       charge, no host-side staging copy), ``to_arrow_batch()`` wraps
       the lanes zero-copy for the parquet/.enc writers;
    4. a consumer that must mutate calls ``cow()`` — the ONE sanctioned
       copy, tracked — and gets a fresh writable block at a new epoch.

    Optional per-lane validity rides along as boolean masks (arrow
    semantics: True = valid); lanes without nulls carry None.
    """

    __slots__ = ("_lanes", "_public", "_validity", "_frozen", "_epoch")

    def __init__(
        self,
        lanes: dict[str, np.ndarray],
        validity: dict[str, np.ndarray] | None = None,
    ) -> None:
        n = None
        for name, arr in lanes.items():
            ensure(arr.ndim == 1, f"column lane {name!r} must be 1-D")
            if n is None:
                n = len(arr)
            ensure(
                len(arr) == n,
                f"ragged column block: lane {name!r} has {len(arr)} rows, "
                f"expected {n}",
            )
        self._lanes = dict(lanes)
        self._public: dict[str, np.ndarray] = {}
        self._validity = dict(validity) if validity else None
        self._frozen = False
        self._epoch = 0

    # -- construction -------------------------------------------------------

    @classmethod
    def alloc(
        cls, schema: dict[str, np.dtype], n: int, stage: str
    ) -> "ColBlock":
        """Fresh writable block: one aligned allocation per lane, each a
        tracked `alloc` under `stage`."""
        lanes = {}
        for name, dt in schema.items():
            a = aligned_empty(n, dt)
            memtrace.track(a, stage, "alloc")
            lanes[name] = a
        return cls(lanes)

    @classmethod
    def wrap(cls, lanes: dict[str, np.ndarray]) -> "ColBlock":
        """Adopt existing arrays BY REFERENCE (ownership transfer, not a
        hand-off — no lineage event). The caller must not mutate them
        behind the block's back after freeze()."""
        return cls(lanes)

    # -- contract surface ---------------------------------------------------

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(self._lanes)

    @property
    def n_rows(self) -> int:
        first = next(iter(self._lanes.values()), None)
        return 0 if first is None else len(first)

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self._lanes.values())

    @property
    def frozen(self) -> bool:
        return self._frozen

    @property
    def epoch(self) -> int:
        """Mutability epoch: bumped by freeze() and by every cow(), so a
        consumer that cached derived state can detect it is stale."""
        return self._epoch

    def aligned(self) -> bool:
        return all(
            a.ctypes.data % ALIGNMENT == 0 for a in self._lanes.values()
        )

    def validity(self, name: str) -> np.ndarray | None:
        if self._validity is None:
            return None
        v = self._validity.get(name)
        return None if v is None else self._read_only_of(v)

    # -- mutability protocol ------------------------------------------------

    def writable_lane(self, name: str) -> np.ndarray:
        """The backing lane, writable — single-owner fill phase only."""
        if self._frozen:
            raise HoraeError(
                f"column block is frozen (epoch {self._epoch}); "
                f"mutate through cow(), not writable_lane({name!r})"
            )
        return self._lanes[name]

    def freeze(self) -> "ColBlock":
        """End the fill phase: epoch bump, public lanes flip read-only.
        Idempotent. The internal backing stays writable so dlpack/device
        export never needs a defensive copy."""
        if not self._frozen:
            self._frozen = True
            self._epoch += 1
            self._public.clear()
        return self

    def share(self, stage: str) -> "ColBlock":
        """Hand the frozen block to another consumer by reference — a
        `reuse` event (bytes exist once, a new holder appears)."""
        ensure(self._frozen, "only frozen column blocks may be shared")
        memtrace.track_bytes(self.nbytes, stage, "reuse")
        return self

    def cow(self, stage: str) -> "ColBlock":
        """Copy-on-write: a frozen block yields a fresh WRITABLE block at
        a new epoch (the one sanctioned whole-block copy, tracked per
        lane); an unfrozen block is single-owner and returns itself."""
        if not self._frozen:
            return self
        lanes = {}
        for name, a in self._lanes.items():
            dst = aligned_empty(len(a), a.dtype)
            dst[:] = a
            memtrace.track(dst, stage, "copy")
            lanes[name] = dst
        out = ColBlock(lanes, self._validity)
        out._epoch = self._epoch + 1
        return out

    # -- lane access --------------------------------------------------------

    def _read_only_of(self, arr: np.ndarray) -> np.ndarray:
        v = arr.view()
        v.flags.writeable = False
        return v

    def lane(self, name: str) -> np.ndarray:
        """Zero-copy view of one lane; read-only once frozen (a write
        through it raises), cached per name."""
        got = self._public.get(name)
        if got is None:
            a = self._lanes[name]
            got = self._read_only_of(a) if self._frozen else a
            self._public[name] = got
        return got

    def lanes(self) -> dict[str, np.ndarray]:
        return {name: self.lane(name) for name in self._lanes}

    def copy_lane(self, name: str, stage: str) -> np.ndarray:
        """Sanctioned single-lane materialization — always a tracked
        copy, always writable and aligned."""
        a = self._lanes[name]
        dst = aligned_empty(len(a), a.dtype)
        dst[:] = a
        memtrace.track(dst, stage, "copy")
        return dst

    # -- export -------------------------------------------------------------

    def to_device(
        self, stage: str = "h2d", names: tuple[str, ...] | None = None
    ):
        """Stage lanes to the default device: `jax.device_put` straight
        off the internal WRITABLE backing (numpy refuses dlpack export of
        read-only arrays, so the public frozen views would force exactly
        the defensive copy this type exists to kill). ONE `device_staged`
        charge for the transfer — no intermediate host alloc, no
        double-charged staging bytes."""
        import jax

        picked = self.names if names is None else names
        out = {n: jax.device_put(self._lanes[n]) for n in picked}
        memtrace.device_staged(
            sum(int(self._lanes[n].nbytes) for n in picked), stage
        )
        return out

    def to_arrow_batch(self, schema, stage: str = "flush_encode"):
        """The block as a pyarrow RecordBatch of zero-copy lane views
        (primitive lanes wrap without moving bytes) — the parquet/.enc
        writers' feed. Tracked as one `view` of the block's bytes."""
        import pyarrow as pa

        arrays = []
        for field in schema:
            lane = self._lanes[field.name]
            v = self._validity.get(field.name) if self._validity else None
            arrays.append(pa.array(lane, type=field.type, mask=(
                None if v is None else ~v
            )))
        memtrace.track_bytes(self.nbytes, stage, "view")
        return pa.RecordBatch.from_arrays(arrays, schema=schema)


class GrowableColBlock:
    """The ingest-side arena: appends land in preallocated capacity,
    growth is geometric (tracked `alloc`), and `seal()` detaches the
    filled prefix as a frozen ColBlock of zero-copy views — the memtable
    double-buffer without the recycled-array copy.

    `adopt_spare()` re-issues a previous generation's backing (a `reuse`
    event — the pooled analog of DecodeArena's steady state)."""

    __slots__ = ("_schema", "_stage", "_lanes", "_fill", "_cap")

    def __init__(
        self,
        schema: dict[str, np.dtype],
        capacity: int = 1024,
        stage: str = "append",
    ) -> None:
        self._schema = {k: np.dtype(v) for k, v in schema.items()}
        self._stage = stage
        self._cap = max(int(capacity), 1)
        self._lanes = {
            name: aligned_empty(self._cap, dt)
            for name, dt in self._schema.items()
        }
        for a in self._lanes.values():
            memtrace.track(a, stage, "alloc")
        self._fill = 0

    @classmethod
    def adopt_spare(
        cls, spare: dict[str, np.ndarray], stage: str = "append"
    ) -> "GrowableColBlock":
        """Rebuild an arena over a recycled backing (the flush executor
        returns the previous generation's lanes once its write-out
        lands): capacity already exists, so this is a `reuse`."""
        self = cls.__new__(cls)
        self._schema = {k: a.dtype for k, a in spare.items()}
        self._stage = stage
        self._lanes = dict(spare)
        self._cap = min((len(a) for a in spare.values()), default=0)
        self._fill = 0
        memtrace.track_bytes(
            sum(int(a.nbytes) for a in spare.values()), stage, "reuse"
        )
        return self

    @property
    def n_rows(self) -> int:
        return self._fill

    @property
    def capacity(self) -> int:
        return self._cap

    def reserve(self, n: int) -> None:
        """Ensure room for `n` more rows; geometric growth, filled prefix
        carried over (the ONE copy growth pays, tracked)."""
        need = self._fill + int(n)
        if need <= self._cap:
            return
        cap = max(2 * self._cap, need)
        grown = {}
        for name, a in self._lanes.items():
            g = aligned_empty(cap, a.dtype)
            memtrace.track(g, self._stage, "alloc")
            g[: self._fill] = a[: self._fill]
            grown[name] = g
        self._lanes = grown
        self._cap = cap

    def append(self, rows: dict[str, np.ndarray]) -> None:
        """Append one batch of rows (whole-column slice assignment into
        the preallocated lanes — no per-row work, no new buffers)."""
        n = min((len(a) for a in rows.values()), default=0)
        if n == 0:
            return
        self.reserve(n)
        f = self._fill
        for name, src in rows.items():
            self._lanes[name][f:f + n] = src
        self._fill = f + n

    def writable_lane(self, name: str) -> np.ndarray:
        """The full-capacity backing lane (parsers fill `[fill:fill+n]`
        in place, then commit(n))."""
        return self._lanes[name]

    def commit(self, n: int) -> None:
        """Account rows a caller wrote directly into writable_lane()."""
        ensure(
            self._fill + n <= self._cap,
            "commit() past the reserved arena capacity",
        )
        self._fill += int(n)

    def seal(self) -> tuple[ColBlock, dict[str, np.ndarray]]:
        """Detach the filled prefix as a frozen ColBlock (zero-copy
        views, tracked `seal` view once) and hand back the raw backing
        for the spare pool. The arena is empty afterwards."""
        fill = self._fill
        views = {name: a[:fill] for name, a in self._lanes.items()}
        block = ColBlock.wrap(views).freeze()
        memtrace.track_bytes(block.nbytes, "seal", "view")
        backing = self._lanes
        self._lanes = {
            name: aligned_empty(0, dt) for name, dt in self._schema.items()
        }
        self._cap = 0
        self._fill = 0
        return block, backing


def as_lane(arr, dtype, stage: str) -> np.ndarray:
    """Coerce an array to a contiguous typed lane through the funnel:
    a `view` when the input already satisfies the contract (no bytes
    move), ONE tracked `copy` when a dtype/layout conversion is
    unavoidable — the sanctioned staging-prep spelling (the old
    `tracked_contiguous(np.asarray(...))` pattern mis-filed conversion
    copies as views because the fresh asarray output was already
    contiguous by the time the funnel looked)."""
    a = np.asarray(arr)
    out = np.ascontiguousarray(a, dtype=dtype)
    memtrace.track_bytes(
        int(out.nbytes), stage, "view" if out is a else "copy"
    )
    return out


# ---------------------------------------------------------------------------
# Arrow-side lanes: chunk-aware zero-copy access over pyarrow tables.


def _chunk_to_numpy(chunk) -> tuple[np.ndarray, bool]:
    """One arrow chunk as numpy: (array, was_zero_copy). Null-free
    primitive chunks view the arrow buffer directly; nulls or bit-packed
    bools force a real conversion (arrow_column_to_numpy's fill path)."""
    import pyarrow as pa

    from horaedb_tpu.ops.blocks import arrow_column_to_numpy

    t = chunk.type
    zero_copy = chunk.null_count == 0 and not pa.types.is_boolean(t) and (
        pa.types.is_integer(t)
        or pa.types.is_floating(t)
        or pa.types.is_timestamp(t)
    )
    return arrow_column_to_numpy(chunk), zero_copy


class ArrowLanes:
    """Chunk-aware column access over a (possibly chunked) pyarrow
    Table: the reader's merge consumes lanes chunk-wise instead of
    paying one `combine_chunks` copy per touched column.

    - ``chunks(name)`` — per-chunk zero-copy numpy views, sliced to ONE
      common chunk layout (the first accessed column's); a column whose
      native chunking disagrees is materialized once through the
      sanctioned funnel and re-sliced (views).
    - ``gather_sorted(name, idx)`` — compacted gather for a sorted index
      vector (np.nonzero output) without materializing the column.
    - ``lane(name)`` — full contiguous lane: a view for single-chunk
      columns, ONE tracked copy otherwise (the device-route fallback).

    First access to a column records one lineage event under `stage`:
    `view` when every chunk wrapped zero-copy, `copy` otherwise.
    ``presorted_cache`` memoizes the chunk-aware sortedness probe
    (storage/read.py `_lanes_presorted`) across planner probes."""

    __slots__ = (
        "_table", "_stage", "_chunks", "_lanes", "_bounds",
        "presorted_cache",
    )

    def __init__(self, table, stage: str = "host_prep") -> None:
        self._table = table
        self._stage = stage
        self._chunks: dict[str, list[np.ndarray]] = {}
        self._lanes: dict[str, np.ndarray] = {}
        self._bounds: np.ndarray | None = None
        self.presorted_cache: dict[tuple, bool] = {}

    @property
    def n_rows(self) -> int:
        return self._table.num_rows

    @property
    def bounds(self) -> np.ndarray:
        """Common chunk layout: row offsets of chunk starts + final n."""
        if self._bounds is None:
            if self._table.num_columns == 0:
                self._bounds = np.array([0, self._table.num_rows])
            else:
                lens = [len(c) for c in self._table.column(0).chunks]
                self._bounds = np.concatenate(
                    [[0], np.cumsum(lens, dtype=np.int64)]
                ) if lens else np.array([0, 0])
        return self._bounds

    def chunks(self, name: str) -> list[np.ndarray]:
        got = self._chunks.get(name)
        if got is not None:
            return got
        bounds = self.bounds
        col = self._table.column(name)
        native = [len(c) for c in col.chunks]
        common = list(np.diff(bounds))
        if native == common:
            views, all_zero_copy = [], True
            for ch in col.chunks:
                a, zc = _chunk_to_numpy(ch)
                all_zero_copy &= zc
                views.append(a)
        else:
            # layout disagrees with the common one: materialize once
            # through the funnel, re-slice into aligned views
            full = self._materialize(name)
            views = [
                full[int(bounds[i]):int(bounds[i + 1])]
                for i in range(len(bounds) - 1)
            ]
            self._chunks[name] = views
            return views
        memtrace.track_bytes(
            int(col.nbytes), self._stage,
            "view" if all_zero_copy else "copy",
        )
        self._chunks[name] = views
        return views

    def _materialize(self, name: str) -> np.ndarray:
        from horaedb_tpu.ops.blocks import arrow_column_to_numpy

        a = arrow_column_to_numpy(
            memtrace.tracked_combine(self._table.column(name), self._stage)
        )
        self._lanes[name] = a
        return a

    def lane(self, name: str) -> np.ndarray:
        """Full contiguous lane — the fallback for consumers that need
        one flat array (device staging, lexsort). Single-chunk columns
        come back as the existing chunk view; multi-chunk columns pay
        ONE sanctioned copy, cached."""
        got = self._lanes.get(name)
        if got is not None:
            return got
        views = self.chunks(name)
        if len(views) == 1:
            a = views[0]
        elif len(views) == 0:
            a = np.empty(0, dtype=object)
        else:
            a = memtrace.tracked_concat(views, self._stage)
        self._lanes[name] = a
        return a

    def gather_sorted(self, name: str, idx: np.ndarray) -> np.ndarray:
        """Gather `lane[idx]` for a SORTED index vector (np.nonzero
        order) chunk-by-chunk — derived compute, no full-column
        materialization."""
        views = self.chunks(name)
        if len(views) == 1:
            return views[0][idx]
        bounds = self.bounds
        out = np.empty(
            len(idx),
            dtype=views[0].dtype if views else np.int64,
        )
        lo = 0
        for i, v in enumerate(views):
            hi = int(np.searchsorted(idx, int(bounds[i + 1]), side="left"))
            if hi > lo:
                out[lo:hi] = v[idx[lo:hi] - int(bounds[i])]
            lo = hi
        return out

    def eval_chunked(self, fn, names: list[str]) -> np.ndarray:
        """Evaluate `fn({name: chunk_lane})` per chunk, concatenating
        the (derived, boolean) results into one mask — the predicate
        path's chunk-wise spelling."""
        bounds = self.bounds
        nch = len(bounds) - 1
        if nch <= 1:
            return fn({c: self.lane(c) for c in names})
        per = {c: self.chunks(c) for c in names}
        out = np.empty(int(bounds[-1]), dtype=bool)
        for i in range(nch):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            if hi > lo:
                out[lo:hi] = fn({c: per[c][i] for c in names})
        return out
