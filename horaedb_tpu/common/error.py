"""Error type + failure taxonomy for the whole framework.

The reference funnels every failure into a single `Error::Internal(anyhow::Error)`
with pervasive `.context(...)` chains (src/common/src/error.rs:4-13). The Python
analog is one exception type plus helpers that mirror `ensure!` / `.context()`.

On top of the single base type sits the fault-tolerance taxonomy the
object-store data plane (objstore/resilient.py) and the flush pipeline
(engine/flush_executor.py) route on:

- ``RetryableError`` — transient: a later identical attempt may succeed
  (network blip, 5xx burst, timeout). Retry with backoff, park-and-replay.
- ``PersistentError`` — deterministic: the same request will fail the same
  way every time (4xx, malformed payload, too-large object). Retrying
  burns budget without hope; surface it to the caller instead.
- ``FatalError`` — a process-level invariant broke (deposed writer epoch,
  corrupt snapshot): the current actor must stop, not retry.
- ``UnavailableError`` — a RetryableError that additionally means "the
  backend is down or this process is overloaded RIGHT NOW": circuit
  breaker open, retry budget exhausted against a dead store, flush queue
  stalled past deadline. The HTTP layer sheds these as 503 +
  ``Retry-After`` (server/errors.py) instead of hanging or 500ing.

``classify()`` maps any exception into the three retry classes. Unknown
exception types classify ``retryable`` on purpose: transports raise
arbitrary errors for transient faults, and the retry caps bound the cost
of optimism, while a mis-classified ``persistent`` would drop work that
one retry could have saved.
"""

from __future__ import annotations

from contextlib import contextmanager


class HoraeError(Exception):
    """Single internal error type; message carries the context chain."""

    def __init__(self, msg: str, cause: BaseException | None = None):
        super().__init__(msg)
        self.__cause__ = cause

    def __str__(self) -> str:  # render the full context chain like anyhow
        parts = [self.args[0] if self.args else self.__class__.__name__]
        cur = self.__cause__
        while cur is not None:
            parts.append(str(cur))
            cur = cur.__cause__
        return ": ".join(parts)


class RetryableError(HoraeError):
    """Transient failure: an identical retry may succeed."""


class PersistentError(HoraeError):
    """Deterministic failure: retrying the same request cannot succeed."""


class FatalError(HoraeError):
    """Process-level invariant broken: stop the current actor, don't retry."""


class UnavailableError(RetryableError):
    """The backend is down / this process is overloaded right now.

    ``retry_after_s`` is the hint the HTTP layer surfaces as a
    ``Retry-After`` header on the 503 it sheds (server/errors.py)."""

    def __init__(self, msg: str, cause: BaseException | None = None,
                 retry_after_s: float | None = None):
        super().__init__(msg, cause=cause)
        self.retry_after_s = retry_after_s


class ReplicaReadOnlyError(PersistentError):
    """This process holds a READ-ONLY replica view of the data (cluster
    role = "replica", or a non-owned region on a writer): the mutation
    must run on the owning writer instead. Persistent in the taxonomy —
    retrying HERE can never succeed; the HTTP router forwards the write
    to the owner (cluster/router.py) rather than 500ing."""


class DeadlineExceeded(HoraeError):
    """The end-to-end deadline of the request driving this work expired
    (common/deadline.py carries the token; every natural yield point of
    the scan path checks it cooperatively).

    Deliberately NOT Retryable: under the SAME (already expired) deadline
    an identical retry cannot succeed — retry ladders must stop, not burn
    budget on work nobody will read. The HTTP layer answers 504 with
    partial-progress provenance (server/errors.py), distinct from the
    503/Retry-After overload shed: a 503 says "back off and resend", a
    504 says "your budget ran out; widen timeout= or narrow the query"."""

    def __init__(self, msg: str, cause: BaseException | None = None,
                 budget_s: float | None = None,
                 elapsed_s: float | None = None, at: str = ""):
        super().__init__(msg, cause=cause)
        self.budget_s = budget_s
        self.elapsed_s = elapsed_s
        self.at = at


def classify(exc: BaseException) -> str:
    """Map any exception to ``"retryable" | "persistent" | "fatal"``.

    Order matters: UnavailableError is Retryable, and mixed-lineage types
    (e.g. a retries-exhausted transport error that subclasses both a
    backend error and RetryableError) resolve retryable-first. The stdlib
    transient families (timeouts, connection resets, OS-level IO) are
    retryable without needing the marker class."""
    if isinstance(exc, FatalError):
        return "fatal"
    if isinstance(exc, DeadlineExceeded):
        # the deadline that killed the attempt also kills any retry of it
        return "persistent"
    if isinstance(exc, RetryableError):
        return "retryable"
    if isinstance(exc, PersistentError):
        return "persistent"
    # everything else — stdlib transients (timeouts, connection resets)
    # and unknown types alike — defaults retryable (see docstring)
    return "retryable"


def ensure(cond: bool, msg: str) -> None:
    """`ensure!` analog (src/columnar_storage/src/macros.rs:18-30)."""
    if not cond:
        raise HoraeError(msg)


@contextmanager
def context(msg: str):
    """`.context(msg)` analog: wrap any raised exception in HoraeError(msg).

    Taxonomy-preserving: wrapping an UnavailableError (or any
    Retryable/Persistent/Fatal subclass) re-raises the SAME class — a
    context frame must never demote a typed failure back to the plain
    base, or the layers that route on the class (503 shedding, flush
    classification, retry policy) silently lose it. The Retry-After hint
    rides along."""
    try:
        yield
    except HoraeError as e:
        cls = HoraeError
        if isinstance(e, (RetryableError, PersistentError, FatalError,
                          DeadlineExceeded)):
            cls = type(e)
        try:
            wrapped = cls(msg, cause=e)
        except TypeError:  # exotic subclass __init__: keep the class's
            # nearest taxonomy ancestor rather than losing the class
            for base in (UnavailableError, RetryableError, PersistentError,
                         FatalError, DeadlineExceeded):
                if isinstance(e, base):
                    wrapped = base(msg, cause=e)
                    break
            else:
                wrapped = HoraeError(msg, cause=e)
        if isinstance(e, UnavailableError) and isinstance(wrapped, UnavailableError):
            wrapped.retry_after_s = e.retry_after_s
        if isinstance(e, DeadlineExceeded) and isinstance(wrapped, DeadlineExceeded):
            wrapped.budget_s = e.budget_s
            wrapped.elapsed_s = e.elapsed_s
            wrapped.at = e.at
        raise wrapped from e
    except Exception as e:  # noqa: BLE001 - deliberate funnel
        raise HoraeError(msg, cause=e) from e
