"""Error type for the whole framework.

The reference funnels every failure into a single `Error::Internal(anyhow::Error)`
with pervasive `.context(...)` chains (src/common/src/error.rs:4-13). The Python
analog is one exception type plus helpers that mirror `ensure!` / `.context()`.
"""

from __future__ import annotations

from contextlib import contextmanager


class HoraeError(Exception):
    """Single internal error type; message carries the context chain."""

    def __init__(self, msg: str, cause: BaseException | None = None):
        super().__init__(msg)
        self.__cause__ = cause

    def __str__(self) -> str:  # render the full context chain like anyhow
        parts = [self.args[0] if self.args else self.__class__.__name__]
        cur = self.__cause__
        while cur is not None:
            parts.append(str(cur))
            cur = cur.__cause__
        return ": ".join(parts)


def ensure(cond: bool, msg: str) -> None:
    """`ensure!` analog (src/columnar_storage/src/macros.rs:18-30)."""
    if not cond:
        raise HoraeError(msg)


@contextmanager
def context(msg: str):
    """`.context(msg)` analog: wrap any raised exception in HoraeError(msg)."""
    try:
        yield
    except HoraeError as e:
        raise HoraeError(msg, cause=e) from e
    except Exception as e:  # noqa: BLE001 - deliberate funnel
        raise HoraeError(msg, cause=e) from e
