"""Human-readable byte sizes.

Contract (reference: src/common/src/size_ext.rs:26-188, forked-from-TiKV idiom):
- parse "2GiB", "512MiB", "0.5e6 B", "4KB" (KB == KiB: binary multiples),
  optional whitespace before the unit, scientific notation allowed.
- serialize to the largest binary unit that divides evenly, else raw bytes
  with a decimal.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from horaedb_tpu.common.error import HoraeError

_B = 1
_KIB = 1024
_MIB = _KIB * 1024
_GIB = _MIB * 1024
_TIB = _GIB * 1024
_PIB = _TIB * 1024

_UNITS = {
    "B": _B,
    "KB": _KIB, "KIB": _KIB,
    "MB": _MIB, "MIB": _MIB,
    "GB": _GIB, "GIB": _GIB,
    "TB": _TIB, "TIB": _TIB,
    "PB": _PIB, "PIB": _PIB,
}
_PATTERN = re.compile(
    r"^\s*(?P<value>[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)\s*(?P<unit>[a-zA-Z]*)\s*$"
)


@dataclass(frozen=True, order=True)
class ReadableSize:
    """A byte count, (de)serialized human-readably."""

    bytes: int

    @classmethod
    def kb(cls, v: int | float) -> "ReadableSize":
        return cls(int(v * _KIB))

    @classmethod
    def mb(cls, v: int | float) -> "ReadableSize":
        return cls(int(v * _MIB))

    @classmethod
    def gb(cls, v: int | float) -> "ReadableSize":
        return cls(int(v * _GIB))

    @classmethod
    def parse(cls, s: str | int | float | "ReadableSize") -> "ReadableSize":
        if isinstance(s, ReadableSize):
            return s
        if isinstance(s, (int, float)):
            return cls(int(s))
        m = _PATTERN.match(s)
        if not m:
            raise HoraeError(f"invalid size string: {s!r}")
        value = float(m.group("value"))
        unit = m.group("unit").upper()
        if unit == "":
            unit = "B"
        if unit not in _UNITS:
            raise HoraeError(f"unknown size unit in: {s!r}")
        if value < 0:
            raise HoraeError(f"negative size: {s!r}")
        return cls(int(value * _UNITS[unit]))

    def __str__(self) -> str:
        for label, size in (("PiB", _PIB), ("TiB", _TIB), ("GiB", _GIB),
                            ("MiB", _MIB), ("KiB", _KIB)):
            if self.bytes >= size and self.bytes % size == 0:
                return f"{self.bytes // size}{label}"
        return f"{self.bytes}B"

    def as_bytes(self) -> int:
        return self.bytes

    def __bool__(self) -> bool:
        return self.bytes != 0
