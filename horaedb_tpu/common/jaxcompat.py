"""JAX version-compatibility shims (one home, no per-module drift).

`shard_map` moved to a top-level export in jax 0.4.31; older images
still spell it `jax.experimental.shard_map.shard_map`. Import it from
here so the fallback lives in exactly one place — when jax removes the
experimental path, this is the only edit site.
"""

try:
    from jax import shard_map  # jax >= 0.4.31 top-level export
except ImportError:
    from jax.experimental.shard_map import shard_map

__all__ = ["shard_map"]
