"""Foundation utilities (reference: src/common/src/lib.rs:22-26)."""

from horaedb_tpu.common.error import HoraeError, ensure, context
from horaedb_tpu.common.time_ext import ReadableDuration, now_ms
from horaedb_tpu.common.size_ext import ReadableSize

__all__ = [
    "HoraeError",
    "ensure",
    "context",
    "ReadableDuration",
    "ReadableSize",
    "now_ms",
]
