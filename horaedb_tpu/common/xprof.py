"""Device-side profiling: instrumented jit + a process-wide kernel catalog.

PR 2 made the host side observable (request traces, per-stage lane
histograms) and PR 3 added dispatcher provenance — but the device side
stayed a black box: nothing recorded when JAX recompiled a hot kernel,
what a compiled kernel's FLOPs/bytes envelope was, or how much of a slow
request was compile time rather than steady-state execution. "When Is a
Columnar Scan Bandwidth-Bound?" (PAPERS.md) argues the attribution that
matters is predicted arithmetic intensity vs achieved throughput; this
module supplies the predicted side.

`xjit` wraps `jax.jit` and every hot-path jitted entry point (ops/,
parallel/, storage/read.py — enforced by jaxlint J007) routes through it:

    @xjit(kernel="block_sum_count", static_argnames=("num_cells",))
    def _block_sum_count_xla(...): ...

    fn = xjit(mapped, kernel="sharded_downsample")   # inline form

Per kernel it records:

- compile/retrace events: `horaedb_jit_compile_total{kernel}` and
  `horaedb_jit_compile_seconds{kernel}` on /metrics, plus the
  arg-signature (shapes/dtypes/static values) that triggered the
  retrace — the #1 question when a steady workload suddenly stalls is
  "what shape churned the cache";
- distinct-signature count: `horaedb_jit_cache_entries{kernel}`;
- where the backend supports it, `lowered.compile().cost_analysis()` /
  `memory_analysis()` — the predicted FLOPs/bytes envelope served at
  GET /debug/kernels and folded into query EXPLAIN.

Detection mechanism: the traced wrapper body only executes when JAX
(re)traces — a cache hit never enters Python beyond the jit dispatch — so
a sentinel in the body is an EXACT retrace detector with zero
steady-state cost beyond one contextvar set/reset per call. No per-call
device sync, ever (the overhead bar tests/test_xprof.py pins).

Honest accounting notes:
- `compile_seconds` is the wall time of the triggering call (trace +
  XLA compile + async dispatch) — the latency the REQUEST paid, which is
  the quantity operators attribute. Nested retraces (an xjit kernel
  traced inside an outer xjit compile) count their trace time under both
  kernels, so per-kernel compile sums can exceed wall clock, exactly
  like overlapping scanstats stages.
- cost/memory analysis requires an extra `lower().compile()` per
  captured signature. `HORAEDB_XPROF_COST` bounds it: `first` (default)
  pays it once per kernel, `all` per new signature, `off` never.

Knobs:
    HORAEDB_XPROF       off -> xjit degrades to plain jax.jit (no
                        telemetry, no catalog)
    HORAEDB_XPROF_COST  first | all | off (cost-analysis capture)
"""

from __future__ import annotations

import functools
import logging
import os
import threading
import time
from contextvars import ContextVar

import jax

logger = logging.getLogger(__name__)

__all__ = ["xjit", "XJit", "catalog", "snapshot", "kernel_entries", "reset",
           "register_metrics"]

# Compile-latency buckets: traces are >=ms, XLA compiles span 10ms-minutes.
COMPILE_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0,
)

# The horaedb_jit_* families, created on first use instead of at import:
# this module sits BELOW ops/ (every hot kernel module imports it), and a
# top-level `from horaedb_tpu.server.metrics import ...` would close the
# cycle server -> config -> storage -> ops -> xprof -> server. Registration
# is idempotent; server/main.py calls register_metrics() at boot so the
# zero-state families render on /metrics before the first compile.
_metric_families = None
_metrics_lock = threading.Lock()


def register_metrics():
    """(compile_total, compile_seconds, cache_entries) families, creating
    them in the process registry on first call."""
    global _metric_families
    if _metric_families is None:
        with _metrics_lock:
            if _metric_families is None:
                from horaedb_tpu.server.metrics import GLOBAL_METRICS

                _metric_families = (
                    GLOBAL_METRICS.counter(
                        "horaedb_jit_compile_total",
                        help="JIT trace/compile events per instrumented "
                             "kernel (a steady workload should flatline "
                             "after warmup; growth = retrace churn).",
                        labelnames=("kernel",),
                    ),
                    GLOBAL_METRICS.histogram(
                        "horaedb_jit_compile_seconds",
                        help="Wall seconds the triggering call paid for a "
                             "trace+compile, per kernel.",
                        labelnames=("kernel",),
                        buckets=COMPILE_BUCKETS,
                    ),
                    GLOBAL_METRICS.gauge(
                        "horaedb_jit_cache_entries",
                        help="Distinct arg-signatures seen per instrumented "
                             "kernel (the lower bound of the jit cache's "
                             "entry count).",
                        labelnames=("kernel",),
                    ),
                )
    return _metric_families

# Sentinel box: a list the traced wrapper appends the triggering signature
# to. Context-local so concurrent asyncio requests cannot claim each
# other's compiles; None outside an XJit.__call__ (which also makes the
# wrapper a no-op during internal cost-capture lowering — no recursion).
_TRACE_BOX: ContextVar["list | None"] = ContextVar("horaedb_xprof_box",
                                                   default=None)

_REG_LOCK = threading.Lock()
# kernel name -> shared telemetry. Memoized builders (lru_cache'd kernel
# factories) create one XJit per shape variant and may EVICT them; the
# telemetry lives on this per-name object instead of the instance so (a)
# an evicted instance — and its compiled executables — is garbage like
# any other jitted function (the registry never pins it), and (b) the
# compile history it accumulated survives the eviction.
_REGISTRY: dict[str, "_KernelStats"] = {}

_MAX_SIGNATURES = 64      # per-instance signature memory bound
_SIG_LEAVES = 16          # leaves rendered per signature


def _cost_mode() -> str:
    mode = os.environ.get("HORAEDB_XPROF_COST", "first")
    return mode if mode in ("first", "all", "off") else "first"


def _signature(args: tuple, kwargs: dict) -> str:
    """Render the call's abstract signature: dtype[shape] per array leaf,
    repr for static/aux leaves. Runs at TRACE time only (leaves are
    tracers), so cost is irrelevant."""
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    parts = []
    for leaf in leaves[:_SIG_LEAVES]:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(
                f"{getattr(dtype, 'name', dtype)}"
                f"[{','.join(str(d) for d in shape)}]"
            )
        else:
            parts.append(repr(leaf)[:32])
    if len(leaves) > _SIG_LEAVES:
        parts.append(f"+{len(leaves) - _SIG_LEAVES} more")
    return "(" + ", ".join(parts) + ")"


_scanstats_mod = None


def _scanstats():
    """Lazy storage.scanstats import (runtime only: common/ must not
    import storage/ at module load — scanstats itself imports this
    package's tracing)."""
    global _scanstats_mod
    if _scanstats_mod is None:
        from horaedb_tpu.storage import scanstats

        _scanstats_mod = scanstats
    return _scanstats_mod


def _has_tracer(args: tuple, kwargs: dict) -> bool:
    return any(
        isinstance(leaf, jax.core.Tracer)
        for leaf in jax.tree_util.tree_leaves((args, kwargs))
    )


def _memory_dict(mem) -> dict | None:
    """Flatten a backend memory_analysis object to plain ints (the exposed
    attribute set varies by backend/version; probe, don't assume)."""
    if mem is None:
        return None
    out = {}
    for attr in (
        "argument_size_in_bytes", "output_size_in_bytes",
        "temp_size_in_bytes", "generated_code_size_in_bytes",
        "alias_size_in_bytes", "peak_memory_in_bytes",
    ):
        v = getattr(mem, attr, None)
        if isinstance(v, (int, float)):
            out[attr] = int(v)
    return out or None


def _cost_dict(cost) -> dict | None:
    """Scalar entries of cost_analysis (list-wrapped on some versions);
    per-operand breakdowns are dropped — the envelope is flops + bytes."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    out = {
        k: float(v) for k, v in cost.items()
        if isinstance(v, (int, float)) and "{" not in str(k)
    }
    return dict(sorted(out.items())[:24]) or None


class _KernelStats:
    """Per-kernel-NAME telemetry, shared by every XJit instance carrying
    the name (one per memoized shape variant). Own lock — instances come
    and go, the stats object is process-lifetime."""

    __slots__ = ("kernel", "lock", "instances", "compiles",
                 "compile_seconds", "signatures", "cost", "memory",
                 "last_compile_ms")

    def __init__(self, kernel: str):
        self.kernel = kernel
        self.lock = threading.Lock()
        self.instances = 0          # XJit constructions, not live objects
        self.compiles = 0
        self.compile_seconds = 0.0
        self.signatures: dict[str, int] = {}
        self.cost: dict | None = None
        self.memory: dict | None = None
        self.last_compile_ms: float | None = None

    def snapshot(self) -> dict:
        with self.lock:
            cost = dict(self.cost) if self.cost else None
            mem = dict(self.memory) if self.memory else None
            sigs = dict(self.signatures)
            out = {
                "kernel": self.kernel,
                "instances": self.instances,
                "compiles": self.compiles,
                "compile_seconds": round(self.compile_seconds, 6),
                "cache_entries": len(sigs),
                "signatures": sigs,
                "last_compile_ms": self.last_compile_ms,
            }
        flops = (cost or {}).get("flops")
        bytes_accessed = (cost or {}).get("bytes accessed")
        out.update({
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            "arithmetic_intensity": (
                round(flops / bytes_accessed, 4)
                if flops and bytes_accessed else None
            ),
            "cost": cost,
            "memory": mem,
        })
        return out


def _stats_for(kernel: str) -> _KernelStats:
    with _REG_LOCK:
        stats = _REGISTRY.get(kernel)
        if stats is None:
            stats = _REGISTRY[kernel] = _KernelStats(kernel)
        return stats


class XJit:
    """One instrumented jit-wrapped callable. Exposes the jit surface the
    codebase uses (`__call__`, `lower`) plus telemetry accessors."""

    def __init__(self, fn, kernel: str, jit_kwargs: dict):
        self.kernel = kernel
        self._fn = fn
        self._jit_kwargs = jit_kwargs
        self._stats = _stats_for(kernel)
        with self._stats.lock:
            self._stats.instances += 1

        def _traced(*args, **kwargs):
            box = _TRACE_BOX.get()
            if box is not None:
                box.append(_signature(args, kwargs))
            return fn(*args, **kwargs)

        # __wrapped__ lets inspect.signature (which jax uses to resolve
        # static_argnames to positions) see the REAL parameter list
        # through the (*args, **kwargs) wrapper
        functools.update_wrapper(_traced, fn)
        self._jitted = jax.jit(_traced, **jit_kwargs)

    def __call__(self, *args, **kwargs):
        box: list = []
        token = _TRACE_BOX.set(box)
        t0 = time.perf_counter()
        try:
            out = self._jitted(*args, **kwargs)
        finally:
            _TRACE_BOX.reset(token)
        if box:
            self._record_compile(box[-1], time.perf_counter() - t0,
                                 args, kwargs)
        _scanstats().kernel_use(self.kernel)
        return out

    def lower(self, *args, **kwargs):
        """AOT lowering passthrough (plan-shape tests, cost capture)."""
        return self._jitted.lower(*args, **kwargs)

    # -- telemetry ----------------------------------------------------------

    def _record_compile(self, sig: str, dt: float, args, kwargs) -> None:
        stats = self._stats
        with stats.lock:
            stats.compiles += 1
            stats.compile_seconds += dt
            stats.signatures[sig] = stats.signatures.get(sig, 0) + 1
            while len(stats.signatures) > _MAX_SIGNATURES:
                stats.signatures.pop(next(iter(stats.signatures)))
            stats.last_compile_ms = time.time() * 1000.0
            n_sigs = len(stats.signatures)
            want_cost = (
                (_cost_mode() == "first" and stats.cost is None)
                or _cost_mode() == "all"
            )
        compile_total, compile_seconds, cache_entries = register_metrics()
        compile_total.labels(self.kernel).inc()
        compile_seconds.labels(self.kernel).observe(dt)
        cache_entries.labels(self.kernel).set(n_sigs)
        # feed the query-scoped collector + the stage histogram + the
        # active trace span: compile becomes a first-class lane next to
        # io/transfer/kernel in the roofline attribution
        _scanstats().record("compile", dt)
        if want_cost and not _has_tracer(args, kwargs):
            self._capture_cost(args, kwargs)

    def _capture_cost(self, args, kwargs) -> None:
        """Predicted FLOPs/bytes envelope via AOT compile. Pays one extra
        XLA compile (the _TRACE_BOX default of None makes the wrapper
        inert here, so this never re-enters _record_compile); bounded by
        HORAEDB_XPROF_COST. Backends without analysis support just leave
        the catalog entry envelope-less."""
        try:
            compiled = self._jitted.lower(*args, **kwargs).compile()
        except Exception:  # noqa: BLE001 — AOT quirks must never fail a query
            logger.debug("xprof: cost-capture lowering failed for %s",
                         self.kernel, exc_info=True)
            return
        cost = mem = None
        try:
            cost = _cost_dict(compiled.cost_analysis())
        except Exception:  # noqa: BLE001 — backend-dependent surface
            pass
        try:
            mem = _memory_dict(compiled.memory_analysis())
        except Exception:  # noqa: BLE001 — backend-dependent surface
            pass
        with self._stats.lock:
            if cost is not None:
                self._stats.cost = cost
            if mem is not None:
                self._stats.memory = mem

    def stats(self) -> dict:
        """This kernel NAME's merged telemetry (shared across shape
        variants)."""
        return self._stats.snapshot()


def xjit(fn=None, *, kernel: str | None = None, **jit_kwargs):
    """Instrumented drop-in for `jax.jit`.

    Decorator factory (`@xjit(kernel="...", static_argnames=...)`),
    bare decorator (`@xjit`), or inline wrapper (`xjit(f, kernel="...")`).
    `kernel` is the catalog/metric label; defaults to the function name.
    All other kwargs pass through to `jax.jit`. `HORAEDB_XPROF=off`
    degrades to plain `jax.jit` (no telemetry, no catalog entry).
    """
    if fn is None:
        return lambda f: xjit(f, kernel=kernel, **jit_kwargs)
    if os.environ.get("HORAEDB_XPROF", "on").lower() in ("off", "0", "false"):
        return jax.jit(fn, **jit_kwargs)
    name = kernel or getattr(fn, "__name__", "kernel").lstrip("_") or "kernel"
    return XJit(fn, name, jit_kwargs)


# -- process-wide catalog ---------------------------------------------------


def _all_stats() -> list[_KernelStats]:
    with _REG_LOCK:
        return list(_REGISTRY.values())


def catalog() -> list[dict]:
    """Per-kernel telemetry, compiled-kernels first (the
    GET /debug/kernels payload)."""
    out = [s.snapshot() for s in _all_stats()]
    out.sort(key=lambda d: (-d["compiles"], d["kernel"]))
    return out


def kernel_entries(names) -> list[dict]:
    """Catalog entries for the named kernels only (query EXPLAIN embeds
    the envelope of just the kernels the request invoked)."""
    wanted = set(names)
    with _REG_LOCK:
        stats = [v for k, v in sorted(_REGISTRY.items()) if k in wanted]
    return [s.snapshot() for s in stats]


def snapshot() -> dict:
    """Process totals (bench.py's compile/steady split)."""
    total = 0
    seconds = 0.0
    for s in _all_stats():
        with s.lock:
            total += s.compiles
            seconds += s.compile_seconds
    return {
        "kernels": len(_REGISTRY),
        "total_compiles": total,
        "total_compile_seconds": round(seconds, 6),
    }


def reset() -> None:
    """Zero per-kernel counters (tests). Kernel names stay registered —
    the wrapped functions are module-level; only their telemetry clears.
    Prometheus counters are monotone by contract and are NOT reset."""
    for s in _all_stats():
        with s.lock:
            s.compiles = 0
            s.compile_seconds = 0.0
            s.signatures.clear()
            s.cost = None
            s.memory = None
            s.last_compile_ms = None
