"""Lightweight request tracing: contextvar-propagated span trees.

The engine's perf story spans three lanes (object-store IO/decode,
host<->device transfer, XLA kernel) and VERDICT r02 proved attribution
cannot be an afterthought ("assumed kernel-bound, measured 95%
transfer-bound"). scanstats answers "which lane, per stage, inside one
scan"; this module answers "which request, which layer, end to end" —
every HTTP request (and any internal operation that opts in) becomes a
trace: a tree of named spans with wall-clock durations and attributes,
kept in a bounded in-memory ring served at /debug/traces.

Design constraints:
- zero overhead when sampling is off: `span()` is one contextvar get;
- contextvar propagation: spans opened in `asyncio` child tasks and in
  `asyncio.to_thread` workers attach to the caller's trace (both copy
  the context at spawn);
- no deps beyond the stdlib (storage/ and ingest/ import this).

Usage:

    with tracing.trace("query", metric="cpu") as t:      # root span
        with tracing.span("scan", segment=3):
            ...
    t.trace_id  # echoed to clients as X-Horaedb-Trace-Id

Knobs (env, overridable via configure()):
    HORAEDB_TRACE_SAMPLE   sample rate in [0,1]; 0 disables (default 1)
    HORAEDB_TRACE_SLOW_S   slow-trace WARNING threshold (default 1.0)
    HORAEDB_TRACE_RING     recent-trace ring capacity (default 256)
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import random
import re
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from contextvars import ContextVar

logger = logging.getLogger(__name__)

# Cross-node propagation headers (cluster observability plane). They live
# HERE — not in server/main.py or cluster/router.py — because both the
# HTTP tier and the router funnel need them and this module is the only
# stdlib-clean common ground (router importing server would cycle).
TRACE_HEADER = "X-Horaedb-Trace-Id"
PARENT_SPAN_HEADER = "X-Horaedb-Parent-Span"
SPANS_HEADER = "X-Horaedb-Trace-Spans"

# Serialized-subtree ship budget: the callee returns its span list in a
# response header, and aiohttp's client rejects header fields over ~8190
# bytes — blowing that budget would fail the FORWARDED REQUEST to report
# on it. Stay well under, degrading detail instead (export_spans).
SHIP_BUDGET_BYTES = 4096

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{8,64}$")


def valid_trace_id(s) -> bool:
    """Is `s` shaped like one of our trace ids? Remote peers are trusted
    cluster members, but the id lands in filenames (slowlog spool) and
    log lines — refuse anything that isn't plain bounded hex."""
    return isinstance(s, str) and _TRACE_ID_RE.match(s) is not None


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def env_defaults() -> tuple[float, float, int]:
    """(sample, slow_s, ring) from the HORAEDB_TRACE_* env vars, falling
    back to the compiled defaults. The server's TracingConfig seeds its
    field defaults from this, so the env knobs stay live when the config
    file has no [tracing] section (explicit config values win)."""
    return (
        min(1.0, max(0.0, _env_float("HORAEDB_TRACE_SAMPLE", 1.0))),
        _env_float("HORAEDB_TRACE_SLOW_S", 1.0),
        max(1, int(_env_float("HORAEDB_TRACE_RING", 256))),
    )


_sample_rate, _slow_s, _ring_cap = env_defaults()


class Span:
    __slots__ = ("span_id", "parent_id", "name", "start_ms", "duration_s",
                 "attrs")

    def __init__(self, span_id: int, parent_id: int | None, name: str,
                 attrs: dict):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_ms = time.time() * 1000.0
        self.duration_s: float | None = None  # None while open
        self.attrs = attrs

    def as_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "name": self.name,
            "start_ms": round(self.start_ms, 3),
            "duration_s": (round(self.duration_s, 6)
                           if self.duration_s is not None else None),
            # copy (one level deep for add_stage's nested dict): a span of
            # a still-running background task may mutate attrs while the
            # serialized dict is being JSON-encoded
            "attrs": {
                k: (dict(v) if isinstance(v, dict) else v)
                for k, v in list(self.attrs.items())
            },
        }


class Trace:
    """One request's span set. Spans append from any task/thread of the
    request (list.append is atomic under the GIL; span identity is never
    shared across appenders)."""

    __slots__ = ("trace_id", "spans", "_ids")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.spans: list[Span] = []
        self._ids = itertools.count(1)

    def new_span(self, parent_id: int | None, name: str, attrs: dict) -> Span:
        sp = Span(next(self._ids), parent_id, name, attrs)
        self.spans.append(sp)
        return sp

    @property
    def root(self) -> Span | None:
        return self.spans[0] if self.spans else None

    def as_dict(self) -> dict:
        """Span tree: children nested under their parent. Iterates ONE
        snapshot of the span list: a background task spawned inside the
        request (e.g. an ingest flush) may still be appending spans after
        the trace landed in the ring, and two live iterations could see
        different lengths (KeyError on the second). Parents are created
        before their children, so any snapshot is self-consistent."""
        spans = list(self.spans)
        nodes = {s.span_id: dict(s.as_dict(), children=[]) for s in spans}
        roots = []
        for s in spans:
            node = nodes[s.span_id]
            parent = nodes.get(s.parent_id) if s.parent_id else None
            (parent["children"] if parent else roots).append(node)
        root = self.root
        return {
            "trace_id": self.trace_id,
            "name": root.name if root else "",
            "start_ms": root.start_ms if root else 0.0,
            "duration_s": root.duration_s if root else None,
            "spans": len(self.spans),
            "root": roots[0] if roots else None,
        }


# (trace, current span) of the running context; None outside any trace
_ACTIVE: ContextVar[tuple[Trace, Span] | None] = ContextVar(
    "horaedb_trace", default=None
)

_ring_lock = threading.Lock()
_ring: "OrderedDict[str, Trace]" = OrderedDict()


def configure(sample: float | None = None, slow_s: float | None = None,
              ring: int | None = None) -> None:
    """Override the env-derived knobs (server config, tests)."""
    global _sample_rate, _slow_s, _ring_cap
    if sample is not None:
        _sample_rate = min(1.0, max(0.0, float(sample)))
    if slow_s is not None:
        _slow_s = float(slow_s)
    if ring is not None:
        _ring_cap = max(1, int(ring))
        with _ring_lock:
            while len(_ring) > _ring_cap:
                _ring.popitem(last=False)


def sampling_enabled() -> bool:
    return _sample_rate > 0.0


def _sampled() -> bool:
    if _sample_rate >= 1.0:
        return True
    if _sample_rate <= 0.0:
        return False
    return random.random() < _sample_rate


@contextmanager
def trace(name: str, *, remote_id: str | None = None,
          remote_parent: int | None = None, **attrs):
    """Root span context: starts a new trace (subject to sampling) and
    registers it in the recent-trace ring on exit. Yields the Trace, or
    None when this request is not sampled. Nested calls degrade to a
    child span of the enclosing trace.

    `remote_id` adopts a trace id minted by a peer (a forwarded request's
    X-Horaedb-Trace-Id) instead of minting one: the sampling decision was
    the ORIGIN's — it only sent headers because it sampled — so adoption
    bypasses the local sampler; an unsampled origin sends nothing and the
    callee falls through to its own sampling. A malformed id is ignored
    (normal local trace). `remote_parent` records the origin-side span id
    this request hangs under, so the shipped-back subtree is attributable
    even when read raw."""
    cur = _ACTIVE.get()
    if cur is not None:
        with span(name, **attrs):
            yield cur[0]
        return
    if remote_id is not None and valid_trace_id(remote_id):
        t = Trace(remote_id)
        if remote_parent is not None:
            attrs = dict(attrs, remote_parent=remote_parent)
    elif not _sampled():
        yield None
        return
    else:
        t = Trace(os.urandom(8).hex())
    root = t.new_span(None, name, attrs)
    token = _ACTIVE.set((t, root))
    t0 = time.perf_counter()
    try:
        yield t
    finally:
        root.duration_s = time.perf_counter() - t0
        _ACTIVE.reset(token)
        _finish(t)


def _finish(t: Trace) -> None:
    with _ring_lock:
        _ring[t.trace_id] = t
        while len(_ring) > _ring_cap:
            _ring.popitem(last=False)
    root = t.root
    if root is not None and root.duration_s is not None \
            and root.duration_s >= _slow_s:
        logger.warning(
            "slow trace %s: %s took %.3fs (%d spans; threshold %.3fs) "
            "GET /debug/traces/%s for the span tree",
            t.trace_id, root.name, root.duration_s, len(t.spans), _slow_s,
            t.trace_id,
        )


@contextmanager
def span(name: str, **attrs):
    """Child span of the active trace; a no-op (one contextvar get) when
    no trace is active. Yields the Span or None."""
    cur = _ACTIVE.get()
    if cur is None:
        yield None
        return
    t, parent = cur
    sp = t.new_span(parent.span_id, name, attrs)
    token = _ACTIVE.set((t, sp))
    t0 = time.perf_counter()
    try:
        yield sp
    finally:
        sp.duration_s = time.perf_counter() - t0
        _ACTIVE.reset(token)


def current_trace_id() -> str | None:
    cur = _ACTIVE.get()
    return cur[0].trace_id if cur is not None else None


def add_attr(**kw) -> None:
    """Attach attributes to the current span (no-op outside a trace)."""
    cur = _ACTIVE.get()
    if cur is not None:
        cur[1].attrs.update(kw)


def add_stage(stage: str, seconds: float) -> None:
    """Fold one scanstats stage timing into the current span (accumulated
    under a 'stages' attr — per-chunk stages would flood the tree as
    individual spans)."""
    cur = _ACTIVE.get()
    if cur is None:
        return
    stages = cur[1].attrs.setdefault("stages", {})
    stages[stage] = round(stages.get(stage, 0.0) + seconds, 6)


def recent(limit: int = 50, min_ms: float | None = None) -> list[dict]:
    """Most-recent-first trace summaries (no span bodies). `min_ms` keeps
    only traces at least that slow — the "last 10 slow traces" operator
    pull — applied BEFORE `limit`, so the newest `limit` traces ABOVE the
    threshold come back, not however many slow ones survive inside the
    newest `limit`."""
    with _ring_lock:
        traces = list(_ring.values())
    if min_ms is not None:
        traces = [
            t for t in traces
            if t.root is not None and t.root.duration_s is not None
            and t.root.duration_s * 1000.0 >= min_ms
        ]
    out = []
    for t in reversed(traces[-limit:] if limit else traces):
        root = t.root
        out.append({
            "trace_id": t.trace_id,
            "name": root.name if root else "",
            "start_ms": root.start_ms if root else 0.0,
            "duration_s": (round(root.duration_s, 6)
                           if root and root.duration_s is not None else None),
            "spans": len(t.spans),
        })
    return out


def get(trace_id: str) -> dict | None:
    with _ring_lock:
        t = _ring.get(trace_id)
    return t.as_dict() if t is not None else None


def reset() -> None:
    """Clear the ring (tests)."""
    with _ring_lock:
        _ring.clear()


# -- cross-node stitching ----------------------------------------------------
# The callee EXPORTS its finished span list (flat, compact JSON) in the
# response's SPANS_HEADER; the origin GRAFTS it under the router funnel's
# client span. Flat-with-parent-ids beats a nested tree on the wire: the
# graft is one pass, and a record whose parent got truncated away still
# attaches (to the anchor span) instead of orphaning.

# root attrs that must NOT ride the ship header: the EXPLAIN payload and
# scanstats already travel in the response BODY (the federated-EXPLAIN
# fragment); duplicating them here would blow the budget on every query
_NOSHIP_ATTRS = frozenset({"explain", "scanstats"})


def current_span_id() -> int | None:
    """Span id of the running context's current span (the funnel puts it
    in PARENT_SPAN_HEADER so the callee can name its origin anchor)."""
    cur = _ACTIVE.get()
    return cur[1].span_id if cur is not None else None


def export_spans(t: Trace, budget: int = SHIP_BUDGET_BYTES) -> str:
    """Serialize a finished trace's span list for the SPANS_HEADER,
    degrading under `budget` instead of failing the response: full
    records -> records without attrs -> one root summary carrying a
    `truncated_spans` count. Always returns header-safe ASCII JSON."""
    spans = list(t.spans)

    def enc(recs) -> str:
        return json.dumps(recs, separators=(",", ":"), ensure_ascii=True,
                          default=str)

    def record(s: Span, with_attrs: bool) -> dict:
        rec = {
            "id": s.span_id,
            "parent": s.parent_id,
            "name": s.name,
            "start_ms": round(s.start_ms, 3),
            "duration_s": round(s.duration_s or 0.0, 6),
        }
        if with_attrs and s.attrs:
            attrs = {k: v for k, v in list(s.attrs.items())
                     if k not in _NOSHIP_ATTRS}
            if attrs:
                rec["attrs"] = attrs
        return rec

    for with_attrs in (True, False):
        try:
            out = enc([record(s, with_attrs) for s in spans])
        except (TypeError, ValueError):
            continue  # a non-JSON attr value: retry without attrs
        if len(out) <= budget:
            return out
    root = t.root
    return enc([{
        "id": root.span_id if root else 1,
        "parent": None,
        "name": root.name if root else "",
        "start_ms": round(root.start_ms, 3) if root else 0.0,
        "duration_s": round(root.duration_s or 0.0, 6) if root else 0.0,
        "attrs": {"truncated_spans": len(spans)},
    }])


def graft_remote(payload, node: str) -> int:
    """Attach a peer's exported span list under the CURRENT span, re-ided
    from the local trace's counter and labeled `node=<peer>`. A record
    whose parent is unknown (truncated ship, malformed entry) anchors to
    the current span — the stitched tree has no orphans by construction.
    Returns spans grafted; 0 (never a raise) on any malformed payload —
    a peer's bad header must not fail the origin's request."""
    cur = _ACTIVE.get()
    if cur is None or not payload:
        return 0
    if isinstance(payload, (str, bytes)):
        try:
            payload = json.loads(payload)
        except (ValueError, UnicodeDecodeError):
            return 0
    if not isinstance(payload, list):
        return 0
    t, anchor = cur
    idmap: dict[int, int] = {}
    grafted = 0
    for rec in payload:
        if not isinstance(rec, dict):
            continue
        attrs = rec.get("attrs")
        attrs = dict(attrs) if isinstance(attrs, dict) else {}
        attrs["node"] = node
        rparent = rec.get("parent")
        parent = (idmap.get(rparent, anchor.span_id)
                  if isinstance(rparent, int) else anchor.span_id)
        sp = t.new_span(parent, str(rec.get("name", "?")), attrs)
        try:
            sp.start_ms = float(rec.get("start_ms", sp.start_ms))
            sp.duration_s = float(rec.get("duration_s", 0.0))
        except (TypeError, ValueError):
            sp.duration_s = 0.0
        rid = rec.get("id")
        if isinstance(rid, int):
            idmap[rid] = sp.span_id
        grafted += 1
    return grafted
