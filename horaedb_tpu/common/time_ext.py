"""Human-readable durations and the engine clock.

Contract (reference: src/common/src/time_ext.rs:39-217, TiKV-style):
- parse strings like "1d2h3m4s5ms" — any subset of units, in order d,h,m,s,ms,
  each count may be fractional; bare numbers are milliseconds.
- serialize back to the compact "2h5m" form.
- `now_ms()` is the engine wall clock in milliseconds (used for TTL expiry).
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass

from horaedb_tpu.common.error import HoraeError

_MS = 1
_SECOND = 1000 * _MS
_MINUTE = 60 * _SECOND
_HOUR = 60 * _MINUTE
_DAY = 24 * _HOUR

_UNITS = {"d": _DAY, "h": _HOUR, "m": _MINUTE, "s": _SECOND, "ms": _MS}
# Units must appear in strictly decreasing order; regex tokenizes value+unit.
_TOKEN = re.compile(r"(?P<value>\d+(?:\.\d*)?)(?P<unit>d|h|ms|m|s)")
_UNIT_ORDER = ["d", "h", "m", "s", "ms"]


@dataclass(frozen=True, order=True)
class ReadableDuration:
    """A duration stored as integer milliseconds, (de)serialized human-readably."""

    ms: int

    # -- constructors -----------------------------------------------------
    @classmethod
    def millis(cls, v: int | float) -> "ReadableDuration":
        return cls(int(v))

    @classmethod
    def secs(cls, v: int | float) -> "ReadableDuration":
        return cls(int(v * _SECOND))

    @classmethod
    def minutes(cls, v: int | float) -> "ReadableDuration":
        return cls(int(v * _MINUTE))

    @classmethod
    def hours(cls, v: int | float) -> "ReadableDuration":
        return cls(int(v * _HOUR))

    @classmethod
    def days(cls, v: int | float) -> "ReadableDuration":
        return cls(int(v * _DAY))

    # -- parse / serialize ------------------------------------------------
    @classmethod
    def parse(cls, s: str | int | float | "ReadableDuration") -> "ReadableDuration":
        if isinstance(s, ReadableDuration):
            return s
        if isinstance(s, (int, float)):
            return cls(int(s))
        text = s.strip()
        if not text:
            raise HoraeError("empty duration string")
        # bare number == milliseconds
        try:
            return cls(int(float(text)))
        except ValueError:
            pass
        total = 0.0
        pos = 0
        last_unit_idx = -1
        for m in _TOKEN.finditer(text):
            if m.start() != pos:
                raise HoraeError(f"invalid duration string: {s!r}")
            unit = m.group("unit")
            idx = _UNIT_ORDER.index(unit)
            if idx <= last_unit_idx:
                raise HoraeError(f"duration units out of order: {s!r}")
            last_unit_idx = idx
            total += float(m.group("value")) * _UNITS[unit]
            pos = m.end()
        if pos != len(text):
            raise HoraeError(f"invalid duration string: {s!r}")
        return cls(int(round(total)))

    def __str__(self) -> str:
        if self.ms == 0:
            return "0s"
        rest = self.ms
        out = []
        for unit in _UNIT_ORDER:
            size = _UNITS[unit]
            n, rest = divmod(rest, size)
            if n:
                out.append(f"{n}{unit}")
        return "".join(out)

    # -- conversions ------------------------------------------------------
    @property
    def seconds(self) -> float:
        return self.ms / _SECOND

    def as_millis(self) -> int:
        return self.ms

    def __bool__(self) -> bool:
        return self.ms != 0


def now_ms() -> int:
    """Current wall-clock in ms (reference: src/common/src/time_ext.rs:212-217)."""
    return time.time_ns() // 1_000_000
