"""End-to-end request deadlines: a contextvar token + cooperative checks.

Every query gets a ``Deadline`` budget at the HTTP layer (config default
in ``[metric_engine.query]``, per-request override via Prometheus-style
``timeout=``), installed with :func:`deadline_scope` so it propagates —
like tracing's spans and scanstats' collector — into every coroutine,
``asyncio.gather`` fan-out, and ``to_thread`` hop the query spawns,
without threading a parameter through thirty call sites.

The scan path then calls :func:`check` at its natural yield points
(region fan-out, per-SST reads, between device-lane launches, per-segment
scans): an expired or abandoned query raises
:class:`~horaedb_tpu.common.error.DeadlineExceeded` at the NEXT check
instead of finishing a scan nobody will read, releasing its admission
slot (server/admission.py) and its device/IO budget promptly. The check
is built to be free on the hot path: one contextvar get when no deadline
is installed (the write path, background work), one ``perf_counter``-
class clock read + compare when one is.

Background durability work spawned FROM a request context (flush-executor
workers kicked by a query's flush barrier) must not inherit the request's
budget — ``asyncio`` tasks copy the spawning context — so those tasks
call :func:`detach` first; killing a half-done SST upload because a
dashboard panel gave up would turn a slow query into parked memtables.

Object-store reads issued on behalf of a query respect the budget too:
``objstore/resilient.py`` caps each attempt's ``op_deadline`` at
:func:`remaining_s` and stops its retry ladder once the budget cannot
cover another attempt — a black-holed store under a 1 s query deadline
costs ~1 s, not the full ladder.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from contextvars import ContextVar

from horaedb_tpu.common.error import DeadlineExceeded


class Deadline:
    """One request's time budget, measured on the monotonic clock.

    ``clock`` is injectable so tests drive expiry without sleeping."""

    __slots__ = ("budget_s", "_t0", "_clock")

    def __init__(self, budget_s: float, clock=time.monotonic):
        self.budget_s = float(budget_s)
        self._clock = clock
        self._t0 = clock()

    def elapsed_s(self) -> float:
        return self._clock() - self._t0

    def remaining_s(self) -> float:
        """Seconds left (negative once expired)."""
        return self.budget_s - self.elapsed_s()

    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def check(self, at: str = "") -> None:
        """Raise :class:`DeadlineExceeded` if the budget is spent.
        ``at`` names the yield point for the 504 body / logs."""
        elapsed = self.elapsed_s()
        if elapsed >= self.budget_s:
            raise DeadlineExceeded(
                f"query deadline exceeded after {elapsed:.3f}s "
                f"(budget {self.budget_s:.3f}s)"
                + (f" at {at}" if at else ""),
                budget_s=self.budget_s, elapsed_s=elapsed, at=at,
            )

    def __repr__(self) -> str:  # debugging / trace attrs
        return f"Deadline(budget={self.budget_s:.3f}s, remaining={self.remaining_s():.3f}s)"


_ACTIVE: ContextVar[Deadline | None] = ContextVar(
    "horaedb_deadline", default=None
)


def current() -> Deadline | None:
    """The active deadline token, or None (no budget installed)."""
    return _ACTIVE.get()


def remaining_s() -> float | None:
    """Remaining budget of the active deadline; None without one."""
    d = _ACTIVE.get()
    return None if d is None else d.remaining_s()


def check(at: str = "") -> None:
    """Cooperative checkpoint: no-op without an active deadline, raises
    DeadlineExceeded past one. THE call scan-path yield points make."""
    d = _ACTIVE.get()
    if d is not None:
        d.check(at)


@contextmanager
def deadline_scope(deadline: Deadline | None):
    """Install ``deadline`` as the active token for the block (and every
    task/thread spawned inside it). ``None`` explicitly clears any
    inherited deadline for the block."""
    token = _ACTIVE.set(deadline)
    try:
        yield deadline
    finally:
        _ACTIVE.reset(token)


def detach() -> None:
    """Clear any inherited deadline in THIS task's context, permanently
    (background durability work — flush workers, compaction tasks —
    spawned from a request context must not be killed by the request's
    budget). Safe because each asyncio task owns a COPY of the spawning
    context: the set never leaks back to the spawner."""
    _ACTIVE.set(None)
