"""Unified byte-budget pool registry.

The engine grew five byte-bounded caches, each tracking its own bytes
with its own gauge family and its own eviction discipline:

- ``scan``      — decoded row-group block cache (storage/read.py
                  `_blk_cache`, per ParquetReader)
- ``sidecar``   — encoded-lane sidecar cache (storage/read.py
                  `_enc_cache`, per ParquetReader)
- ``result``    — serving result cache (serving/cache.py RESULT_CACHE)
- ``residency`` — device block residency (serving/residency.py
                  RESIDENCY_CACHE; charges host table + device lanes)
- ``rollup``    — decoded rollup artifacts (storage/rollup.py _CACHE)

This module re-homes them behind ONE registry: each cache keeps its own
data structure and locking, but registers a *provider* (a weakly-held
owner + an accessor returning (bytes, entries)) and routes eviction
counts through `note_eviction`. The registry exports the unified
`horaedb_pool_bytes{pool}` / `horaedb_pool_entries{pool}` /
`horaedb_pool_capacity_bytes{pool}` / `horaedb_pool_evictions_total{pool}`
families and the `GET /debug/memory` occupancy snapshot.

Providers rather than pushed deltas because pools are process-global
while some owners are not: every ParquetReader carries its own scan +
sidecar caches, and readers come and go with engines (tests open dozens
per process). A pushed-delta gauge would drift up with every dropped
reader; the weakref-provider snapshot sums only the caches that are
still alive, so `horaedb_pool_bytes` is resident-byte honest by
construction. `refresh()` is called on every /metrics render and
/debug/memory hit — a handful of attribute reads per pool."""

from __future__ import annotations

import threading
import weakref

from horaedb_tpu.server.metrics import GLOBAL_METRICS

# The five pools, pre-registered so the families render from boot.
POOLS = ("scan", "sidecar", "result", "residency", "rollup")

POOL_BYTES = GLOBAL_METRICS.gauge(
    "horaedb_pool_bytes",
    help="Resident bytes per byte-budgeted pool (unified registry view; "
         "summed over live owners, refreshed on every /metrics render).",
    labelnames=("pool",),
)
POOL_ENTRIES = GLOBAL_METRICS.gauge(
    "horaedb_pool_entries",
    help="Entries per byte-budgeted pool.",
    labelnames=("pool",),
)
POOL_CAPACITY = GLOBAL_METRICS.gauge(
    "horaedb_pool_capacity_bytes",
    help="Configured byte budget per pool (0 = disabled).",
    labelnames=("pool",),
)
POOL_EVICTIONS = GLOBAL_METRICS.counter(
    "horaedb_pool_evictions_total",
    help="Budget-pressure evictions per pool (invalidation-driven "
         "removals are not evictions and do not count here).",
    labelnames=("pool",),
)


class PoolRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        # pool -> list of (weakref(owner), accessor(owner) -> (bytes, n))
        self._providers: dict[str, list] = {p: [] for p in POOLS}
        self._capacity: dict[str, int] = {p: 0 for p in POOLS}
        self._evict_child = {p: POOL_EVICTIONS.labels(p) for p in POOLS}
        for p in POOLS:  # eager zero-state
            POOL_BYTES.labels(p)
            POOL_ENTRIES.labels(p)
            POOL_CAPACITY.labels(p)

    def register_provider(self, pool: str, owner, accessor) -> None:
        """Attach one owner's occupancy view to `pool`. `accessor(owner)`
        must return (resident_bytes, entries) without taking the owner's
        lock order into anything registry-side (the registry only reads
        plain ints). Dead owners fall out on the next refresh."""
        ref = weakref.ref(owner)
        with self._lock:
            lst = self._providers.setdefault(pool, [])
            lst[:] = [(r, a) for (r, a) in lst if r() is not None]
            lst.append((ref, accessor))

    def set_capacity(self, pool: str, nbytes: int) -> None:
        with self._lock:
            self._capacity[pool] = int(nbytes)
        POOL_CAPACITY.labels(pool).set(int(nbytes))

    def note_eviction(self, pool: str, n: int = 1) -> None:
        child = self._evict_child.get(pool)
        if child is None:
            with self._lock:
                child = self._evict_child.setdefault(
                    pool, POOL_EVICTIONS.labels(pool)
                )
        child.inc(n)

    def refresh(self) -> dict:
        """Sum live providers, update the gauge families, and return the
        /debug/memory occupancy map
        {pool: {bytes, entries, capacity_bytes, evictions, owners}}."""
        with self._lock:
            views = {
                p: list(lst) for p, lst in self._providers.items()
            }
            caps = dict(self._capacity)
        out: dict[str, dict] = {}
        for pool, lst in views.items():
            total_b = 0
            total_n = 0
            owners = 0
            for ref, accessor in lst:
                owner = ref()
                if owner is None:
                    continue
                try:
                    b, n = accessor(owner)
                except Exception:  # noqa: BLE001 — a torn read costs a tick
                    continue
                total_b += int(b)
                total_n += int(n)
                owners += 1
            POOL_BYTES.labels(pool).set(total_b)
            POOL_ENTRIES.labels(pool).set(total_n)
            cap = caps.get(pool, 0)
            out[pool] = {
                "bytes": total_b,
                "entries": total_n,
                "capacity_bytes": cap,
                "utilization": round(total_b / cap, 4) if cap else None,
                "evictions": int(self._evict_child[pool].value)
                if pool in self._evict_child else 0,
                "owners": owners,
            }
        return out


GLOBAL_POOLS = PoolRegistry()


def rss_bytes() -> "int | None":
    """Process resident-set bytes from /proc/self/statm (None where the
    procfs file is unavailable — macOS dev boxes)."""
    import os

    try:
        # jaxlint: disable=J018 procfs pseudo-file: a memory read, not IO — never blocks
        with open("/proc/self/statm") as f:
            fields = f.read().split()
        return int(fields[1]) * os.sysconf("SC_PAGESIZE")
    except (OSError, IndexError, ValueError):
        return None
