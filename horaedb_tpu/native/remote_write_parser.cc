// Pooled zero-copy Prometheus remote-write wire parser.
//
// TPU-native equivalent of the reference's hand-rolled Rust decoder
// (src/remote_write/src/pb_reader.rs, pooled_parser.rs, pooled_types.rs,
// repeated_field.rs). Design points carried over:
//   - unrolled 10-byte varint fast path (pb_reader.rs:98-174, which credits
//     Go's encoding/binary);
//   - strings are NEVER copied or UTF-8 validated: labels land as
//     (offset, length) slices into the caller's buffer
//     (pooled_parser.rs:18-24 makes validation the caller's job);
//   - arena reuse: all output vectors keep their capacity across parses —
//     clear() without dealloc is the pooled-object trick the reference
//     vendors RepeatedField for (repeated_field.rs:21-23).
//
// The output is COLUMNAR, not an object tree: flat sample/label arrays plus
// per-series ranges, exactly the layout the engine ships to device HBM
// (SURVEY R1: "labels/samples land as flat arrays ready for device
// transfer"). Exposed as a C ABI for ctypes.
//
// Build: make -C horaedb_tpu/native   (g++ -O3 -shared -fPIC)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// SeaHash (the metric-engine id hash, reference
// src/metric_engine/src/types.rs:18-41: name_id = hash(name), series_id =
// hash(sorted labels), hash = seahash). Port of the public portable
// algorithm; conformance is pinned against the Python oracle
// (horaedb_tpu/engine/types.py::seahash, itself pinned to the crate's
// documented test vector) by tests/test_ingest.py.
// ---------------------------------------------------------------------------

constexpr uint64_t kSeaP = 0x6EED0E9DA4D94A4FULL;
constexpr uint64_t kSeaSeeds[4] = {
    0x16F11FE89B0D677CULL, 0xB480A793D8E6C86CULL,
    0x6FE2E5AAF078EBC9ULL, 0x14F994A4C5259381ULL};

inline uint64_t sea_diffuse(uint64_t x) {
  x *= kSeaP;
  x ^= (x >> 32) >> (x >> 60);
  x *= kSeaP;
  return x;
}

uint64_t seahash(const uint8_t* data, size_t n) {
  uint64_t lanes[4] = {kSeaSeeds[0], kSeaSeeds[1], kSeaSeeds[2], kSeaSeeds[3]};
  size_t full = n & ~static_cast<size_t>(7);
  size_t i = 0;
  int lane = 0;
  for (; i < full; i += 8) {
    uint64_t chunk;
    std::memcpy(&chunk, data + i, 8);  // little-endian hosts only
    lanes[lane] = sea_diffuse(lanes[lane] ^ chunk);
    lane = (lane + 1) & 3;
  }
  if (i < n) {
    uint8_t tail[8] = {0};
    std::memcpy(tail, data + i, n - i);
    uint64_t chunk;
    std::memcpy(&chunk, tail, 8);
    lanes[lane] = sea_diffuse(lanes[lane] ^ chunk);
  }
  uint64_t a = lanes[0] ^ lanes[1];
  uint64_t c = lanes[2] ^ lanes[3];
  a ^= c;
  a ^= static_cast<uint64_t>(n);
  return sea_diffuse(a);
}

// ---------------------------------------------------------------------------
// wire reading
// ---------------------------------------------------------------------------

struct Reader {
  const uint8_t* p;
  const uint8_t* end;

  bool eof() const { return p >= end; }
  size_t remaining() const { return static_cast<size_t>(end - p); }
};

// Unrolled LEB128 decode; returns false on truncation/overflow.
// Mirrors the reference's unrolled loop (pb_reader.rs:98-174).
inline bool read_varint(Reader& r, uint64_t* out) {
  const uint8_t* p = r.p;
  size_t n = r.remaining();
  if (n == 0) return false;
  uint64_t b = p[0];
  if ((b & 0x80) == 0) { *out = b; r.p += 1; return true; }
  uint64_t v = b & 0x7f;
#define STEP(i)                                        \
  if (n <= (i)) return false;                          \
  b = p[i];                                            \
  v |= (b & 0x7f) << (7 * (i));                        \
  if ((b & 0x80) == 0) { *out = v; r.p += (i) + 1; return true; }
  STEP(1) STEP(2) STEP(3) STEP(4) STEP(5) STEP(6) STEP(7) STEP(8)
#undef STEP
  if (n <= 9) return false;
  b = p[9];
  if (b > 1) return false;  // 10th byte: only the lowest bit may be set
  v |= b << 63;
  *out = v;
  r.p += 10;
  return true;
}

// Tag = field number + wire type; field 0 is malformed per the proto spec
// (single enforcement point for every parse loop).
inline bool read_tag(Reader& r, uint32_t* field, uint32_t* wt) {
  uint64_t tag;
  if (!read_varint(r, &tag)) return false;
  *field = static_cast<uint32_t>(tag >> 3);
  *wt = tag & 7;
  return *field != 0;
}

inline bool read_fixed64_as_double(Reader& r, double* out) {
  if (r.remaining() < 8) return false;
  std::memcpy(out, r.p, 8);
  r.p += 8;
  return true;
}

inline bool read_len(Reader& r, uint64_t* len) {
  if (!read_varint(r, len)) return false;
  return *len <= r.remaining();
}

inline bool skip_field(Reader& r, uint32_t wire_type) {
  switch (wire_type) {
    case 0: {  // varint
      uint64_t v;
      return read_varint(r, &v);
    }
    case 1:  // fixed64
      if (r.remaining() < 8) return false;
      r.p += 8;
      return true;
    case 2: {  // length-delimited
      uint64_t len;
      if (!read_len(r, &len)) return false;
      r.p += len;
      return true;
    }
    case 5:  // fixed32
      if (r.remaining() < 4) return false;
      r.p += 4;
      return true;
    default:  // groups (3/4) unsupported, as in the reference
      return false;
  }
}

// ---------------------------------------------------------------------------
// columnar output arena
// ---------------------------------------------------------------------------

struct Parser {
  const uint8_t* base = nullptr;  // current parse's buffer start

  // per-series ranges
  std::vector<int64_t> series_label_start, series_label_count;
  std::vector<int64_t> series_sample_start, series_sample_count;
  // flattened labels: byte ranges into the input buffer (zero-copy)
  std::vector<int64_t> label_name_off, label_name_len;
  std::vector<int64_t> label_value_off, label_value_len;
  // flattened samples
  std::vector<double> sample_value;
  std::vector<int64_t> sample_ts;
  std::vector<int64_t> sample_series;  // owning series index
  // flattened exemplars (per series)
  std::vector<double> exemplar_value;
  std::vector<int64_t> exemplar_ts;
  std::vector<int64_t> exemplar_series;
  // exemplar labels: per-exemplar ranges into flat ex-label lanes
  std::vector<int64_t> exemplar_label_start, exemplar_label_count;
  std::vector<int64_t> ex_label_name_off, ex_label_name_len;
  std::vector<int64_t> ex_label_value_off, ex_label_value_len;
  // metadata entries: {type, family name range, help range, unit range}
  std::vector<int64_t> meta_type;
  std::vector<int64_t> meta_name_off, meta_name_len;

  // metric-engine id lanes (filled by compute_hashes, not the wire parse):
  // per-series metric_id/tsid seahashes, the __name__ value slice, and the
  // canonical sorted series key materialized into key_arena
  std::vector<uint64_t> series_metric_id, series_tsid;
  std::vector<int64_t> series_name_off, series_name_len;  // -1 len = missing
  std::vector<int64_t> series_key_off, series_key_len;    // into key_arena
  std::vector<uint8_t> key_arena;
  std::vector<int32_t> sort_buf;  // scratch: label indices being sorted
  // inverted-index lanes, one entry per sorted non-name label pair
  // (tag_hash_of contract, engine/types.py:43): posting hash + payload
  // slices. Series s owns [series_tag_start[s], series_tag_start[s+1]) —
  // series_tag_start has n_series+1 entries (last = total pair count).
  std::vector<uint64_t> tag_hash;
  std::vector<int64_t> tag_k_off, tag_k_len, tag_v_off, tag_v_len;
  std::vector<int64_t> series_tag_start;
  std::vector<uint8_t> hash_scratch;  // scratch: one (u32 klen)+k+v image

  void clear() {  // keeps capacity: the pooled-reuse contract
    series_label_start.clear(); series_label_count.clear();
    series_sample_start.clear(); series_sample_count.clear();
    label_name_off.clear(); label_name_len.clear();
    label_value_off.clear(); label_value_len.clear();
    sample_value.clear(); sample_ts.clear(); sample_series.clear();
    exemplar_value.clear(); exemplar_ts.clear(); exemplar_series.clear();
    exemplar_label_start.clear(); exemplar_label_count.clear();
    ex_label_name_off.clear(); ex_label_name_len.clear();
    ex_label_value_off.clear(); ex_label_value_len.clear();
    meta_type.clear(); meta_name_off.clear(); meta_name_len.clear();
    series_metric_id.clear(); series_tsid.clear();
    series_name_off.clear(); series_name_len.clear();
    series_key_off.clear(); series_key_len.clear();
    key_arena.clear();
    tag_hash.clear();
    tag_k_off.clear(); tag_k_len.clear();
    tag_v_off.clear(); tag_v_len.clear();
    series_tag_start.clear();
  }
};

// bytes-compare with Python `sorted()` semantics: memcmp on the common
// prefix, shorter wins ties
inline int bytes_cmp(const uint8_t* a, int64_t alen, const uint8_t* b,
                     int64_t blen) {
  int64_t n = alen < blen ? alen : blen;
  int c = n ? std::memcmp(a, b, static_cast<size_t>(n)) : 0;
  if (c != 0) return c;
  return alen < blen ? -1 : (alen > blen ? 1 : 0);
}

inline void arena_put_u32le(std::vector<uint8_t>& arena, uint32_t v) {
  uint8_t b[4] = {static_cast<uint8_t>(v), static_cast<uint8_t>(v >> 8),
                  static_cast<uint8_t>(v >> 16), static_cast<uint8_t>(v >> 24)};
  arena.insert(arena.end(), b, b + 4);
}

constexpr char kNameLabel[] = "__name__";
constexpr int64_t kNameLabelLen = 8;

// Fill the metric-engine id lanes: metric_id = seahash(__name__ value),
// series key = sorted non-name labels length-prefixed
// (engine/types.py::series_key_of contract), tsid = seahash(series key).
void compute_hashes(Parser& ps, const uint8_t* buf) {
  size_t n_series = ps.series_label_start.size();
  ps.series_metric_id.resize(n_series);
  ps.series_tsid.resize(n_series);
  ps.series_name_off.resize(n_series);
  ps.series_name_len.resize(n_series);
  ps.series_key_off.resize(n_series);
  ps.series_key_len.resize(n_series);
  for (size_t s = 0; s < n_series; ++s) {
    int64_t lstart = ps.series_label_start[s];
    int64_t lcount = ps.series_label_count[s];
    // find __name__, collect the rest
    int64_t name_off = 0, name_len = -1;
    ps.sort_buf.clear();
    for (int64_t i = lstart; i < lstart + lcount; ++i) {
      if (ps.label_name_len[i] == kNameLabelLen &&
          std::memcmp(buf + ps.label_name_off[i], kNameLabel,
                      kNameLabelLen) == 0) {
        name_off = ps.label_value_off[i];
        name_len = ps.label_value_len[i];
      } else {
        ps.sort_buf.push_back(static_cast<int32_t>(i));
      }
    }
    ps.series_name_off[s] = name_off;
    ps.series_name_len[s] = name_len;
    ps.series_metric_id[s] =
        name_len >= 0 ? seahash(buf + name_off, static_cast<size_t>(name_len))
                      : 0;
    // sort remaining labels by (key bytes, value bytes)
    std::sort(ps.sort_buf.begin(), ps.sort_buf.end(),
              [&ps, buf](int32_t a, int32_t b) {
                int c = bytes_cmp(buf + ps.label_name_off[a],
                                  ps.label_name_len[a],
                                  buf + ps.label_name_off[b],
                                  ps.label_name_len[b]);
                if (c != 0) return c < 0;
                return bytes_cmp(buf + ps.label_value_off[a],
                                 ps.label_value_len[a],
                                 buf + ps.label_value_off[b],
                                 ps.label_value_len[b]) < 0;
              });
    // materialize the canonical key: <u32 klen> k <u32 vlen> v per pair;
    // the same walk fills the inverted-index lanes (posting hash over
    // <u32 klen> k v — the tag_hash_of contract — plus payload slices)
    int64_t key_off = static_cast<int64_t>(ps.key_arena.size());
    ps.series_tag_start.push_back(static_cast<int64_t>(ps.tag_hash.size()));
    for (int32_t i : ps.sort_buf) {
      arena_put_u32le(ps.key_arena,
                      static_cast<uint32_t>(ps.label_name_len[i]));
      ps.key_arena.insert(ps.key_arena.end(), buf + ps.label_name_off[i],
                          buf + ps.label_name_off[i] + ps.label_name_len[i]);
      arena_put_u32le(ps.key_arena,
                      static_cast<uint32_t>(ps.label_value_len[i]));
      ps.key_arena.insert(ps.key_arena.end(), buf + ps.label_value_off[i],
                          buf + ps.label_value_off[i] + ps.label_value_len[i]);
      ps.hash_scratch.clear();
      arena_put_u32le(ps.hash_scratch,
                      static_cast<uint32_t>(ps.label_name_len[i]));
      ps.hash_scratch.insert(ps.hash_scratch.end(), buf + ps.label_name_off[i],
                             buf + ps.label_name_off[i] + ps.label_name_len[i]);
      ps.hash_scratch.insert(ps.hash_scratch.end(),
                             buf + ps.label_value_off[i],
                             buf + ps.label_value_off[i] + ps.label_value_len[i]);
      ps.tag_hash.push_back(seahash(ps.hash_scratch.data(),
                                    ps.hash_scratch.size()));
      ps.tag_k_off.push_back(ps.label_name_off[i]);
      ps.tag_k_len.push_back(ps.label_name_len[i]);
      ps.tag_v_off.push_back(ps.label_value_off[i]);
      ps.tag_v_len.push_back(ps.label_value_len[i]);
    }
    ps.series_key_off[s] = key_off;
    ps.series_key_len[s] = static_cast<int64_t>(ps.key_arena.size()) - key_off;
  }
  ps.series_tag_start.push_back(static_cast<int64_t>(ps.tag_hash.size()));
  // hash pass after arena building: insertions above may reallocate the arena
  for (size_t s = 0; s < n_series; ++s) {
    ps.series_tsid[s] =
        seahash(ps.key_arena.data() + ps.series_key_off[s],
                static_cast<size_t>(ps.series_key_len[s]));
  }
}

inline int64_t off_of(const Parser& ps, const uint8_t* p) {
  return static_cast<int64_t>(p - ps.base);
}

bool parse_label(Parser& ps, Reader r) {
  int64_t noff = 0, nlen = 0, voff = 0, vlen = 0;
  while (!r.eof()) {
    uint32_t field, wt;
    if (!read_tag(r, &field, &wt)) return false;
    if (field == 1 && wt == 2) {
      uint64_t len;
      if (!read_len(r, &len)) return false;
      noff = off_of(ps, r.p); nlen = static_cast<int64_t>(len);
      r.p += len;
    } else if (field == 2 && wt == 2) {
      uint64_t len;
      if (!read_len(r, &len)) return false;
      voff = off_of(ps, r.p); vlen = static_cast<int64_t>(len);
      r.p += len;
    } else if (!skip_field(r, wt)) {
      return false;
    }
  }
  ps.label_name_off.push_back(noff);
  ps.label_name_len.push_back(nlen);
  ps.label_value_off.push_back(voff);
  ps.label_value_len.push_back(vlen);
  return true;
}

bool parse_sample(Parser& ps, Reader r, int64_t series_idx) {
  double value = 0;
  int64_t ts = 0;
  while (!r.eof()) {
    uint32_t field, wt;
    if (!read_tag(r, &field, &wt)) return false;
    if (field == 1 && wt == 1) {
      if (!read_fixed64_as_double(r, &value)) return false;
    } else if (field == 2 && wt == 0) {
      uint64_t v;
      if (!read_varint(r, &v)) return false;
      ts = static_cast<int64_t>(v);
    } else if (!skip_field(r, wt)) {
      return false;
    }
  }
  ps.sample_value.push_back(value);
  ps.sample_ts.push_back(ts);
  ps.sample_series.push_back(series_idx);
  return true;
}

bool parse_exemplar_label(Parser& ps, Reader r) {
  int64_t noff = 0, nlen = 0, voff = 0, vlen = 0;
  while (!r.eof()) {
    uint32_t field, wt;
    if (!read_tag(r, &field, &wt)) return false;
    if (field == 1 && wt == 2) {
      uint64_t len;
      if (!read_len(r, &len)) return false;
      noff = off_of(ps, r.p); nlen = static_cast<int64_t>(len);
      r.p += len;
    } else if (field == 2 && wt == 2) {
      uint64_t len;
      if (!read_len(r, &len)) return false;
      voff = off_of(ps, r.p); vlen = static_cast<int64_t>(len);
      r.p += len;
    } else if (!skip_field(r, wt)) {
      return false;
    }
  }
  ps.ex_label_name_off.push_back(noff);
  ps.ex_label_name_len.push_back(nlen);
  ps.ex_label_value_off.push_back(voff);
  ps.ex_label_value_len.push_back(vlen);
  return true;
}

bool parse_exemplar(Parser& ps, Reader r, int64_t series_idx) {
  double value = 0;
  int64_t ts = 0;
  ps.exemplar_label_start.push_back(
      static_cast<int64_t>(ps.ex_label_name_off.size()));
  while (!r.eof()) {
    uint32_t field, wt;
    if (!read_tag(r, &field, &wt)) return false;
    if (field == 1 && wt == 2) {  // exemplar labels
      uint64_t len;
      if (!read_len(r, &len)) return false;
      if (!parse_exemplar_label(ps, Reader{r.p, r.p + len})) return false;
      r.p += len;
    } else if (field == 2 && wt == 1) {
      if (!read_fixed64_as_double(r, &value)) return false;
    } else if (field == 3 && wt == 0) {
      uint64_t v;
      if (!read_varint(r, &v)) return false;
      ts = static_cast<int64_t>(v);
    } else if (!skip_field(r, wt)) {
      return false;
    }
  }
  ps.exemplar_label_count.push_back(
      static_cast<int64_t>(ps.ex_label_name_off.size()) - ps.exemplar_label_start.back());
  ps.exemplar_value.push_back(value);
  ps.exemplar_ts.push_back(ts);
  ps.exemplar_series.push_back(series_idx);
  return true;
}

bool parse_timeseries(Parser& ps, Reader r) {
  int64_t series_idx = static_cast<int64_t>(ps.series_label_start.size());
  ps.series_label_start.push_back(static_cast<int64_t>(ps.label_name_off.size()));
  ps.series_sample_start.push_back(static_cast<int64_t>(ps.sample_value.size()));
  while (!r.eof()) {
    uint32_t field, wt;
    if (!read_tag(r, &field, &wt)) return false;
    uint64_t len;
    switch (field) {
      case 1:  // labels
        if (wt != 2 || !read_len(r, &len)) return false;
        if (!parse_label(ps, Reader{r.p, r.p + len})) return false;
        r.p += len;
        break;
      case 2:  // samples
        if (wt != 2 || !read_len(r, &len)) return false;
        if (!parse_sample(ps, Reader{r.p, r.p + len}, series_idx)) return false;
        r.p += len;
        break;
      case 3:  // exemplars
        if (wt != 2 || !read_len(r, &len)) return false;
        if (!parse_exemplar(ps, Reader{r.p, r.p + len}, series_idx)) return false;
        r.p += len;
        break;
      default:
        if (!skip_field(r, wt)) return false;
    }
  }
  ps.series_label_count.push_back(
      static_cast<int64_t>(ps.label_name_off.size()) - ps.series_label_start.back());
  ps.series_sample_count.push_back(
      static_cast<int64_t>(ps.sample_value.size()) - ps.series_sample_start.back());
  return true;
}

bool parse_metadata(Parser& ps, Reader r) {
  int64_t type = 0, noff = 0, nlen = 0;
  while (!r.eof()) {
    uint32_t field, wt;
    if (!read_tag(r, &field, &wt)) return false;
    if (field == 1 && wt == 0) {
      uint64_t v;
      if (!read_varint(r, &v)) return false;
      type = static_cast<int64_t>(v);
    } else if (field == 2 && wt == 2) {
      uint64_t len;
      if (!read_len(r, &len)) return false;
      noff = off_of(ps, r.p); nlen = static_cast<int64_t>(len);
      r.p += len;
    } else if (!skip_field(r, wt)) {
      return false;
    }
  }
  ps.meta_type.push_back(type);
  ps.meta_name_off.push_back(noff);
  ps.meta_name_len.push_back(nlen);
  return true;
}

bool parse_write_request(Parser& ps, Reader r) {
  while (!r.eof()) {
    uint32_t field, wt;
    if (!read_tag(r, &field, &wt)) return false;
    uint64_t len;
    switch (field) {
      case 1:  // timeseries
        if (wt != 2 || !read_len(r, &len)) return false;
        if (!parse_timeseries(ps, Reader{r.p, r.p + len})) return false;
        r.p += len;
        break;
      case 3:  // metadata
        if (wt != 2 || !read_len(r, &len)) return false;
        if (!parse_metadata(ps, Reader{r.p, r.p + len})) return false;
        r.p += len;
        break;
      default:
        if (!skip_field(r, wt)) return false;
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// Ingest accumulator: the native write buffer behind the metric engine's
// buffered ingest (engine/data.py). Replaces the reference's write-side
// batching intent (the RFC's data table batches many samples per stored
// row, docs/rfcs/20240827-metric-engine.md:218-232) with a C++ structure:
// a (metric_id, tsid) -> dense-id hash map plus flat per-sample lanes.
// Flush emits lanes already sorted by (metric_id, tsid, ts) — series keys
// std::sort'ed (k log k over UNIQUE series), samples placed by stable
// counting sort (O(n + k)), per-series time order verified and locally
// repaired — so the storage write's sortedness fast path skips its sort.
// ---------------------------------------------------------------------------

struct SeriesKey {
  uint64_t mid, tsid;
  bool operator==(const SeriesKey& o) const {
    return mid == o.mid && tsid == o.tsid;
  }
};

struct SeriesKeyHash {
  size_t operator()(const SeriesKey& k) const {
    // ids are already seahash outputs (uniform); fold them
    return static_cast<size_t>(k.mid ^ (k.tsid * 0x9E3779B97F4A7C15ULL));
  }
};

struct Accum {
  std::unordered_map<SeriesKey, int32_t, SeriesKeyHash> dense;
  std::vector<SeriesKey> keys;          // dense id -> key
  std::vector<int32_t> sample_dense;
  std::vector<int64_t> sample_ts;
  std::vector<double> sample_val;
  // flush output lanes (valid until clear/free)
  std::vector<uint64_t> out_mid, out_tsid;
  std::vector<int64_t> out_ts;
  std::vector<double> out_val;

  void clear() {  // keeps capacity
    dense.clear();
    keys.clear();
    sample_dense.clear();
    sample_ts.clear();
    sample_val.clear();
  }
};

// Append one parsed request's samples (parser arena must still hold the
// parse, i.e. call between rw_parse_hashed and the next parse).
int64_t accum_add(Accum& ac, const Parser& ps) {
  size_t n_series = ps.series_label_start.size();
  std::vector<int32_t> dense_of(n_series);
  for (size_t s = 0; s < n_series; ++s) {
    SeriesKey k{ps.series_metric_id[s], ps.series_tsid[s]};
    auto it = ac.dense.find(k);
    if (it == ac.dense.end()) {
      int32_t d = static_cast<int32_t>(ac.keys.size());
      ac.dense.emplace(k, d);
      ac.keys.push_back(k);
      dense_of[s] = d;
    } else {
      dense_of[s] = it->second;
    }
  }
  size_t n = ps.sample_value.size();
  size_t base = ac.sample_dense.size();
  ac.sample_dense.resize(base + n);
  ac.sample_ts.resize(base + n);
  ac.sample_val.resize(base + n);
  for (size_t i = 0; i < n; ++i) {
    ac.sample_dense[base + i] = dense_of[ps.sample_series[i]];
  }
  std::memcpy(ac.sample_ts.data() + base, ps.sample_ts.data(), n * 8);
  std::memcpy(ac.sample_val.data() + base, ps.sample_value.data(), n * 8);
  return static_cast<int64_t>(ac.sample_dense.size());
}

void accum_flush_sorted(Accum& ac) {
  size_t k = ac.keys.size();
  size_t n = ac.sample_dense.size();
  // rank the unique keys by (mid, tsid)
  std::vector<int32_t> order(k);
  for (size_t i = 0; i < k; ++i) order[i] = static_cast<int32_t>(i);
  std::sort(order.begin(), order.end(), [&ac](int32_t a, int32_t b) {
    const SeriesKey &ka = ac.keys[a], &kb = ac.keys[b];
    if (ka.mid != kb.mid) return ka.mid < kb.mid;
    return ka.tsid < kb.tsid;
  });
  std::vector<int32_t> rank_of(k);
  for (size_t r = 0; r < k; ++r) rank_of[order[r]] = static_cast<int32_t>(r);
  // stable counting sort of samples by rank (arrival order kept per series)
  std::vector<int64_t> counts(k + 1, 0);
  for (size_t i = 0; i < n; ++i) counts[rank_of[ac.sample_dense[i]] + 1]++;
  for (size_t r = 1; r <= k; ++r) counts[r] += counts[r - 1];
  ac.out_mid.resize(n);
  ac.out_tsid.resize(n);
  ac.out_ts.resize(n);
  ac.out_val.resize(n);
  std::vector<int64_t> cursor(counts.begin(), counts.end() - 1);
  // scatter only the per-sample lanes (16 B/row of random writes); the key
  // lanes are constant per group and fill sequentially below — measurably
  // cheaper than scattering all 32 B/row through the cache
  for (size_t i = 0; i < n; ++i) {
    int32_t r = rank_of[ac.sample_dense[i]];
    int64_t pos = cursor[r]++;
    ac.out_ts[pos] = ac.sample_ts[i];
    ac.out_val[pos] = ac.sample_val[i];
  }
  for (size_t r = 0; r < k; ++r) {
    const SeriesKey& key = ac.keys[order[r]];
    std::fill(ac.out_mid.begin() + counts[r], ac.out_mid.begin() + counts[r + 1],
              key.mid);
    std::fill(ac.out_tsid.begin() + counts[r],
              ac.out_tsid.begin() + counts[r + 1], key.tsid);
  }
  // scrapes normally arrive in time order; repair any series whose ts
  // dips (stable, local to the group)
  for (size_t r = 0; r < k; ++r) {
    int64_t lo = counts[r], hi = counts[r + 1];
    bool sorted = true;
    for (int64_t i = lo + 1; i < hi; ++i) {
      if (ac.out_ts[i] < ac.out_ts[i - 1]) { sorted = false; break; }
    }
    if (sorted) continue;
    std::vector<int32_t> idx(hi - lo);
    for (int64_t i = 0; i < hi - lo; ++i) idx[i] = static_cast<int32_t>(i);
    std::stable_sort(idx.begin(), idx.end(), [&ac, lo](int32_t a, int32_t b) {
      return ac.out_ts[lo + a] < ac.out_ts[lo + b];
    });
    std::vector<int64_t> ts2(hi - lo);
    std::vector<double> v2(hi - lo);
    for (int64_t i = 0; i < hi - lo; ++i) {
      ts2[i] = ac.out_ts[lo + idx[i]];
      v2[i] = ac.out_val[lo + idx[i]];
    }
    std::memcpy(ac.out_ts.data() + lo, ts2.data(), ts2.size() * 8);
    std::memcpy(ac.out_val.data() + lo, v2.data(), v2.size() * 8);
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// Mirrors the vector layout above; pointers are valid until the next
// rw_parse/rw_parser_free on the same handle.
struct RwResult {
  int64_t n_series;
  int64_t n_labels;
  int64_t n_samples;
  int64_t n_exemplars;
  int64_t n_metadata;
  const int64_t* series_label_start;
  const int64_t* series_label_count;
  const int64_t* series_sample_start;
  const int64_t* series_sample_count;
  const int64_t* label_name_off;
  const int64_t* label_name_len;
  const int64_t* label_value_off;
  const int64_t* label_value_len;
  const double* sample_value;
  const int64_t* sample_ts;
  const int64_t* sample_series;
  const double* exemplar_value;
  const int64_t* exemplar_ts;
  const int64_t* exemplar_series;
  int64_t n_ex_labels;
  const int64_t* exemplar_label_start;
  const int64_t* exemplar_label_count;
  const int64_t* ex_label_name_off;
  const int64_t* ex_label_name_len;
  const int64_t* ex_label_value_off;
  const int64_t* ex_label_value_len;
  const int64_t* meta_type;
  const int64_t* meta_name_off;
  const int64_t* meta_name_len;
};

// Metric-engine id lanes (see compute_hashes); valid until the next
// rw_parse*/rw_parser_free on the same handle.
struct RwHashResult {
  const uint64_t* series_metric_id;
  const uint64_t* series_tsid;
  const int64_t* series_name_off;
  const int64_t* series_name_len;  // -1 = series had no __name__ label
  const int64_t* series_key_off;
  const int64_t* series_key_len;
  const uint8_t* key_arena;
  int64_t key_arena_len;
  // inverted-index lanes (ABI v5): per sorted non-name label pair —
  // posting hash + payload slices; series s owns
  // [series_tag_start[s], series_tag_start[s+1]).
  const uint64_t* tag_hash;
  const int64_t* tag_k_off;
  const int64_t* tag_k_len;
  const int64_t* tag_v_off;
  const int64_t* tag_v_len;
  const int64_t* series_tag_start;  // n_series + 1 entries
  int64_t n_tags;
};

// Sorted flush lanes; valid until the next rw_accum_clear/free.
struct RwFlushResult {
  int64_t n;
  const uint64_t* mid;
  const uint64_t* tsid;
  const int64_t* ts;
  const double* val;
};

// Bumped whenever the ABI of any struct/function here changes; the Python
// binding refuses (and rebuilds) a stale .so whose version mismatches.
int rw_abi_version() { return 5; }

// One-FFI-call copy of the hot per-series id lanes into caller buffers
// (each ctypes string_at crossing costs ~10us; three lanes per request add
// up at millions of samples/s). Caller sizes buffers to n_series.
void rw_copy_id_lanes(void* h, uint64_t* mid, uint64_t* tsid, int64_t* nlen) {
  Parser& ps = *static_cast<Parser*>(h);
  size_t n = ps.series_metric_id.size();
  std::memcpy(mid, ps.series_metric_id.data(), n * 8);
  std::memcpy(tsid, ps.series_tsid.data(), n * 8);
  std::memcpy(nlen, ps.series_name_len.data(), n * 8);
}

void* rw_accum_new() { return new Accum(); }

void rw_accum_free(void* h) { delete static_cast<Accum*>(h); }

void rw_accum_clear(void* h) { static_cast<Accum*>(h)->clear(); }

int64_t rw_accum_rows(void* h) {
  return static_cast<int64_t>(static_cast<Accum*>(h)->sample_dense.size());
}

// Append the parser's CURRENT parse (must follow rw_parse_hashed on the
// same parser handle, before its next parse). Returns total buffered rows,
// or -1 if the parser holds no hash lanes.
int64_t rw_accum_add(void* parser, void* accum) {
  Parser& ps = *static_cast<Parser*>(parser);
  if (ps.series_metric_id.size() != ps.series_label_start.size()) return -1;
  return accum_add(*static_cast<Accum*>(accum), ps);
}

// Sort the buffered samples into pk order and expose the lanes. Does NOT
// clear itself — but the Python caller (NativeAccum.take_sorted) copies the
// lanes and clears IMMEDIATELY, so rows arriving during subsequent awaited
// writes are never lost; write-failure retry is provided by the Python-side
// re-buffering of those copies (SampleManager._flush_accum), NOT by data
// lingering here.
int rw_accum_flush(void* h, RwFlushResult* out) {
  Accum& ac = *static_cast<Accum*>(h);
  accum_flush_sorted(ac);
  out->n = static_cast<int64_t>(ac.out_mid.size());
  out->mid = ac.out_mid.data();
  out->tsid = ac.out_tsid.data();
  out->ts = ac.out_ts.data();
  out->val = ac.out_val.data();
  return 0;
}

void* rw_parser_new() { return new Parser(); }

void rw_parser_free(void* h) { delete static_cast<Parser*>(h); }

// Returns 0 on success, non-zero on malformed input. Output arrays live in
// the parser's arena (reused across calls, pooled semantics).
int rw_parse(void* h, const uint8_t* buf, uint64_t len, RwResult* out) {
  Parser& ps = *static_cast<Parser*>(h);
  ps.clear();
  ps.base = buf;
  if (!parse_write_request(ps, Reader{buf, buf + len})) return 1;
  out->n_series = static_cast<int64_t>(ps.series_label_start.size());
  out->n_labels = static_cast<int64_t>(ps.label_name_off.size());
  out->n_samples = static_cast<int64_t>(ps.sample_value.size());
  out->n_exemplars = static_cast<int64_t>(ps.exemplar_value.size());
  out->n_metadata = static_cast<int64_t>(ps.meta_type.size());
  out->series_label_start = ps.series_label_start.data();
  out->series_label_count = ps.series_label_count.data();
  out->series_sample_start = ps.series_sample_start.data();
  out->series_sample_count = ps.series_sample_count.data();
  out->label_name_off = ps.label_name_off.data();
  out->label_name_len = ps.label_name_len.data();
  out->label_value_off = ps.label_value_off.data();
  out->label_value_len = ps.label_value_len.data();
  out->sample_value = ps.sample_value.data();
  out->sample_ts = ps.sample_ts.data();
  out->sample_series = ps.sample_series.data();
  out->exemplar_value = ps.exemplar_value.data();
  out->exemplar_ts = ps.exemplar_ts.data();
  out->exemplar_series = ps.exemplar_series.data();
  out->n_ex_labels = static_cast<int64_t>(ps.ex_label_name_off.size());
  out->exemplar_label_start = ps.exemplar_label_start.data();
  out->exemplar_label_count = ps.exemplar_label_count.data();
  out->ex_label_name_off = ps.ex_label_name_off.data();
  out->ex_label_name_len = ps.ex_label_name_len.data();
  out->ex_label_value_off = ps.ex_label_value_off.data();
  out->ex_label_value_len = ps.ex_label_value_len.data();
  out->meta_type = ps.meta_type.data();
  out->meta_name_off = ps.meta_name_off.data();
  out->meta_name_len = ps.meta_name_len.data();
  return 0;
}

// Parse + metric-engine id lanes in one pass over the arena. Same return
// contract as rw_parse; `hashes` is only valid when 0 is returned.
int rw_parse_hashed(void* h, const uint8_t* buf, uint64_t len, RwResult* out,
                    RwHashResult* hashes) {
  int rc = rw_parse(h, buf, len, out);
  if (rc != 0) return rc;
  Parser& ps = *static_cast<Parser*>(h);
  compute_hashes(ps, buf);
  hashes->series_metric_id = ps.series_metric_id.data();
  hashes->series_tsid = ps.series_tsid.data();
  hashes->series_name_off = ps.series_name_off.data();
  hashes->series_name_len = ps.series_name_len.data();
  hashes->series_key_off = ps.series_key_off.data();
  hashes->series_key_len = ps.series_key_len.data();
  hashes->key_arena = ps.key_arena.data();
  hashes->key_arena_len = static_cast<int64_t>(ps.key_arena.size());
  hashes->tag_hash = ps.tag_hash.data();
  hashes->tag_k_off = ps.tag_k_off.data();
  hashes->tag_k_len = ps.tag_k_len.data();
  hashes->tag_v_off = ps.tag_v_off.data();
  hashes->tag_v_len = ps.tag_v_len.data();
  hashes->series_tag_start = ps.series_tag_start.data();
  hashes->n_tags = static_cast<int64_t>(ps.tag_hash.size());
  return 0;
}

}  // extern "C"
