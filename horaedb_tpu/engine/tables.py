"""Schemas of the four metric-engine tables over ColumnarStorage.

RFC table layouts (docs/rfcs/20240827-metric-engine.md:100-145) mapped onto
the storage schema contract (pk columns first, then values). String columns
from the RFC are binary here (labels are not UTF-8-validated, matching the
ingest contract), and each table gets numeric hash pks so primary-key
comparisons stay on the device-friendly numeric path.
"""

from __future__ import annotations

import pyarrow as pa

# metrics: pk (metric_id, field_id); values: names + type
METRICS_SCHEMA = pa.schema(
    [
        ("metric_id", pa.uint64()),
        ("field_id", pa.uint64()),
        ("metric_name", pa.binary()),
        ("field_name", pa.binary()),
        ("field_type", pa.uint64()),
    ]
)
METRICS_NUM_PKS = 2

# series: pk (metric_id, tsid); value: the canonical sorted-label key
SERIES_SCHEMA = pa.schema(
    [
        ("metric_id", pa.uint64()),
        ("tsid", pa.uint64()),
        ("series_key", pa.binary()),
    ]
)
SERIES_NUM_PKS = 2

# index (inverted): pk (metric_id, tag_hash, tsid); values: raw tag bytes for
# collision verification and LabelValues queries
INDEX_SCHEMA = pa.schema(
    [
        ("metric_id", pa.uint64()),
        ("tag_hash", pa.uint64()),
        ("tsid", pa.uint64()),
        ("tag_key", pa.binary()),
        ("tag_value", pa.binary()),
    ]
)
INDEX_NUM_PKS = 3

# data: pk (metric_id, tsid, field_id, ts); value: the sample
# (RFC :218-232 keeps MetricID/TSID/FieldID as the sorted prefix; ts joins
# the pk here because rows are raw samples, not 30-min compressed batches)
DATA_SCHEMA = pa.schema(
    [
        ("metric_id", pa.uint64()),
        ("tsid", pa.uint64()),
        ("field_id", pa.uint64()),
        ("ts", pa.int64()),
        ("value", pa.float64()),
    ]
)
DATA_NUM_PKS = 4

# tags (RFC :118-130, the "optional" table): pk (metric_id, tag_hash);
# values: the raw tag bytes. One row per DISTINCT (metric, key, value) —
# the LabelValues acceleration surface that avoids touching per-series
# posting rows. The hash pk keeps pk comparisons numeric (engine-wide
# contract); raw bytes disambiguate collisions at read time.
TAGS_SCHEMA = pa.schema(
    [
        ("metric_id", pa.uint64()),
        ("tag_hash", pa.uint64()),
        ("tag_key", pa.binary()),
        ("tag_value", pa.binary()),
    ]
)
TAGS_NUM_PKS = 2

# exemplars: pk (metric_id, tsid, ts); values: sample + serialized labels
# (length-prefixed KV encoding from engine.types, carrying trace ids etc.)
EXEMPLARS_SCHEMA = pa.schema(
    [
        ("metric_id", pa.uint64()),
        ("tsid", pa.uint64()),
        ("ts", pa.int64()),
        ("value", pa.float64()),
        ("labels", pa.binary()),
    ]
)
EXEMPLARS_NUM_PKS = 3
