"""IndexManager: series registry + inverted tag index.

Implements the reference's `IndexManager::populate_series_ids` skeleton
(src/metric_engine/src/index/mod.rs:34-41, dead code in the snapshot) per
the RFC: a `series` table mapping (metric_id, tsid) -> canonical series key,
and an inverted `index` table mapping (metric_id, tag KV) -> posting list of
TSIDs (RFC :114-136).

Query side: `find_tsids` intersects posting lists for the given tag filters
— the host-side index probe whose result feeds the device-side TSID
set-membership filter (SURVEY §7.7). Hash collisions are handled by
verifying the stored raw tag bytes.
"""

from __future__ import annotations

import threading
from collections import defaultdict

import numpy as np
import pyarrow as pa

from horaedb_tpu.engine.tables import INDEX_SCHEMA, SERIES_SCHEMA
from horaedb_tpu.engine.types import (
    SeriesId,
    decode_series_key,
    series_id_of,
    series_key_of,
    tag_hash_of,
)
from horaedb_tpu.storage.read import ScanRequest, WriteRequest
from horaedb_tpu.storage.types import TimeRange

_ALL_TIME = TimeRange(-(2**62), 2**62)

# Python `re` lacks RE2's linear-time guarantee; bounding pattern size limits
# the blast radius of untrusted matcher patterns (the evaluation also runs
# off the event loop, engine.py::_resolve_query_async).
MAX_REGEX_LEN = 512
# A regex matcher that would run against a label value longer than this
# raises instead (never silently truncates — wrong matches are worse than a
# loud error): sre backtracking cost grows with subject length and runs in C
# holding the GIL, so a thread offload alone cannot contain it.
MAX_REGEX_SUBJECT_LEN = 4096


def _reject_catastrophic(pattern: str) -> None:
    """Reject patterns with nested unbounded repeats (the `(a+)+b` shape):
    sre backtracks exponentially on them while holding the GIL, freezing the
    whole process, not just the worker thread. A parse-tree walk catches the
    common catastrophic shapes; the length caps bound what slips through."""
    import re._parser as sre_parse

    from horaedb_tpu.common.error import HoraeError

    def walk(items, in_repeat: bool) -> None:
        for op, arg in items:
            name = str(op)
            if name in ("MAX_REPEAT", "MIN_REPEAT"):
                _lo, hi, sub = arg
                unbounded = hi is sre_parse.MAXREPEAT or hi >= 1 << 16
                # a counted outer repeat like (a+){2,100} backtracks
                # combinatorially too: any repeat wider than a few counts
                # as repeat context
                repeatish = unbounded or hi > 10
                if in_repeat and repeatish:
                    raise HoraeError(
                        "regex matcher rejected: nested wide repetition "
                        "(catastrophic backtracking risk)"
                    )
                walk(sub, in_repeat or repeatish)
            elif name == "SUBPATTERN":
                walk(arg[3], in_repeat)
            elif name == "BRANCH":
                for alt in arg[1]:
                    walk(alt, in_repeat)
            elif name in ("ASSERT", "ASSERT_NOT"):
                walk(arg[1], in_repeat)

    try:
        tree = sre_parse.parse(pattern)
    except Exception:  # noqa: BLE001 — compile() will surface the real error
        return
    walk(tree, False)


class IndexManager:
    def __init__(self, series_storage, index_storage, segment_duration_ms: int):
        self._series = series_storage
        self._index = index_storage
        self._segment_duration = segment_duration_ms
        # (metric_id, tsid) set of known series — write-through cache
        self._known: set[tuple[int, int]] = set()
        # (metric_id, tag_hash) -> {tsid -> (key, value)} posting lists
        self._postings: dict[tuple[int, int], dict[int, tuple[bytes, bytes]]] = defaultdict(dict)
        # metric_id -> its posting keys (per-metric scans stay O(one metric))
        self._metric_postings: dict[int, set[tuple[int, int]]] = defaultdict(set)
        # Guards the three structures above: queries run in worker threads
        # (engine.py::_resolve_query_async) while ingest mutates on the event
        # loop; iterating a mutating set/dict raises RuntimeError. Held only
        # for in-memory access — never across awaits or regex evaluation.
        self._mu = threading.Lock()

    async def open(self) -> None:
        async for batch in self._series.scan(ScanRequest(range=_ALL_TIME)):
            for m, t in zip(
                batch.column("metric_id").to_pylist(), batch.column("tsid").to_pylist()
            ):
                self._known.add((m, t))
        async for batch in self._index.scan(ScanRequest(range=_ALL_TIME)):
            for m, h, t, k, v in zip(
                batch.column("metric_id").to_pylist(),
                batch.column("tag_hash").to_pylist(),
                batch.column("tsid").to_pylist(),
                batch.column("tag_key").to_pylist(),
                batch.column("tag_value").to_pylist(),
            ):
                self._postings[(m, h)][t] = (k, v)
                self._metric_postings[m].add((m, h))

    # -- write path ----------------------------------------------------------
    async def populate_series_ids(
        self,
        metric_ids: list[int],
        label_sets: list[list[tuple[bytes, bytes]]],
        now_ms: int,
    ) -> list[SeriesId]:
        """Resolve TSIDs for (metric, labels) pairs, registering new series
        in the series table and the inverted index."""
        tsids: list[SeriesId] = []
        new_series_rows: list[tuple[int, int, bytes]] = []
        new_index_rows: list[tuple[int, int, int, bytes, bytes]] = []
        staged: set[tuple[int, int]] = set()
        for mid, labels in zip(metric_ids, label_sets):
            key = series_key_of(labels)
            tsid = series_id_of(key)
            tsids.append(tsid)
            if (mid, tsid) in self._known or (mid, tsid) in staged:
                continue
            staged.add((mid, tsid))
            new_series_rows.append((mid, tsid, key))
            for k, v in labels:
                new_index_rows.append((mid, tag_hash_of(k, v), tsid, k, v))
        if new_series_rows:
            # Persist FIRST, update caches only on success: caching before a
            # failed write would mark the series known while the durable
            # index rows never land, silently dropping it from tag queries
            # after the client's retry (and from recovery after restart).
            await self._persist(new_series_rows, new_index_rows, now_ms)
            self._commit_rows(new_series_rows, new_index_rows)
        return tsids

    def _commit_rows(self, series_rows, index_rows) -> None:
        """Apply persisted rows to the in-memory caches (under the lock —
        queries read these structures from worker threads)."""
        with self._mu:
            for mid, tsid, _key in series_rows:
                self._known.add((mid, tsid))
            for mid, h, tsid, k, v in index_rows:
                self._postings[(mid, h)][tsid] = (k, v)
                self._metric_postings[mid].add((mid, h))

    async def ensure_series_fast(
        self,
        metric_ids: np.ndarray,  # u64 per series (native hash lanes)
        tsids: np.ndarray,       # u64 per series
        key_of,                  # series index -> canonical key bytes
        now_ms: int,
    ) -> None:
        """Hash-lane fast path: ids and canonical keys were computed by the
        native parser; only genuinely new series pay Python-object costs
        (key decode + posting rows). The Python seahash remains the
        differential oracle in tests, per the reference hash contract
        (src/metric_engine/src/types.rs:18-41)."""
        known = self._known
        new_idx: list[int] = []
        staged: set[tuple[int, int]] = set()
        for i, (m, t) in enumerate(zip(metric_ids.tolist(), tsids.tolist())):
            if (m, t) in known or (m, t) in staged:
                continue
            staged.add((m, t))
            new_idx.append(i)
        if not new_idx:
            return
        mids = metric_ids.tolist()
        tids = tsids.tolist()
        new_series_rows: list[tuple[int, int, bytes]] = []
        new_index_rows: list[tuple[int, int, int, bytes, bytes]] = []
        for i in new_idx:
            key = key_of(i)
            new_series_rows.append((mids[i], tids[i], key))
            for k, v in decode_series_key(key):
                new_index_rows.append((mids[i], tag_hash_of(k, v), tids[i], k, v))
        # persist-before-cache, same reasoning as populate_series_ids
        await self._persist(new_series_rows, new_index_rows, now_ms)
        self._commit_rows(new_series_rows, new_index_rows)

    async def _persist(self, series_rows, index_rows, now_ms: int) -> None:
        seg_start = now_ms - now_ms % self._segment_duration
        rng = TimeRange(seg_start, seg_start + 1)
        s_batch = pa.RecordBatch.from_pydict(
            {
                "metric_id": np.asarray([r[0] for r in series_rows], dtype=np.uint64),
                "tsid": np.asarray([r[1] for r in series_rows], dtype=np.uint64),
                "series_key": [r[2] for r in series_rows],
            },
            schema=SERIES_SCHEMA,
        )
        await self._series.write(WriteRequest(s_batch, rng))
        if index_rows:
            i_batch = pa.RecordBatch.from_pydict(
                {
                    "metric_id": np.asarray([r[0] for r in index_rows], dtype=np.uint64),
                    "tag_hash": np.asarray([r[1] for r in index_rows], dtype=np.uint64),
                    "tsid": np.asarray([r[2] for r in index_rows], dtype=np.uint64),
                    "tag_key": [r[3] for r in index_rows],
                    "tag_value": [r[4] for r in index_rows],
                },
                schema=INDEX_SCHEMA,
            )
            await self._index.write(WriteRequest(i_batch, rng))

    # -- query path ------------------------------------------------------------
    def find_tsids(
        self,
        metric_id: int,
        filters: list[tuple[bytes, bytes]],
        matchers: "list[tuple[bytes, str, bytes]] | None" = None,
    ) -> list[SeriesId] | None:
        """TSIDs matching ALL tag filters; None means 'no constraint' (caller
        scans the whole metric). Posting lists verify raw bytes to reject
        hash collisions.

        `matchers` extends equality with Prometheus-style ops per
        (key, op, pattern): "ne" (!=), "re" (=~ full-match), "nre" (!~).
        Non-equality matchers evaluate against the metric's own postings
        (O(one metric), the RFC's two-step fallback shape)."""
        if not filters and not matchers:
            return None
        result: set[int] | None = None

        def intersect(matched: set[int]) -> bool:
            nonlocal result
            result = matched if result is None else (result & matched)
            return bool(result)

        # Structure access happens under the lock (this runs in a worker
        # thread while ingest mutates on the event loop); regex evaluation
        # happens on snapshots after release.
        matcher_values: list[dict[int, bytes]] = []
        with self._mu:
            for k, v in filters:
                h = tag_hash_of(k, v)
                posting = self._postings.get((metric_id, h), {})
                if not intersect({t for t, kv in posting.items() if kv == (k, v)}):
                    return []
            all_tsids: set[int] | None = None
            if matchers:
                all_tsids = {t for m, t in self._known if m == metric_id}
                # one O(postings) pass collects values for every matcher key
                # (the lock blocks event-loop ingest while held — don't
                # re-walk the postings per matcher). Prometheus semantics:
                # an absent label reads as empty for both =~ and !~.
                wanted = {k for k, _op, _p in matchers}
                values_by_key: dict[bytes, dict[int, bytes]] = {
                    k: {} for k in wanted
                }
                for pk in self._metric_postings.get(metric_id, ()):
                    for tsid, (kk, vv) in self._postings[pk].items():
                        if kk in wanted:
                            values_by_key[kk][tsid] = vv
                matcher_values = [values_by_key[k] for k, _op, _p in matchers]
        for (k, op, pattern), values in zip(matchers or (), matcher_values):
            if op == "ne":
                matched = {t for t in all_tsids if values.get(t, b"") != pattern}
            elif op in ("re", "nre"):
                import re as _re

                from horaedb_tpu.common.error import HoraeError

                if len(pattern) > MAX_REGEX_LEN:
                    raise HoraeError(
                        f"regex matcher too long ({len(pattern)} > {MAX_REGEX_LEN})"
                    )
                decoded = pattern.decode(errors="replace")
                _reject_catastrophic(decoded)
                try:
                    rx = _re.compile(decoded)
                except _re.error as e:
                    raise HoraeError(f"bad regex matcher {pattern!r}: {e}") from e

                def subject(t: int) -> str:
                    raw = values.get(t, b"")
                    if len(raw) > MAX_REGEX_SUBJECT_LEN:
                        raise HoraeError(
                            f"label value too long for regex matcher "
                            f"({len(raw)} > {MAX_REGEX_SUBJECT_LEN} bytes); "
                            f"use equality filters for this label"
                        )
                    return raw.decode(errors="replace")

                hit = {t for t in all_tsids if rx.fullmatch(subject(t))}
                matched = hit if op == "re" else (all_tsids - hit)
            else:
                from horaedb_tpu.common.error import HoraeError

                raise HoraeError(f"unknown matcher op: {op!r}")
            if not intersect(matched):
                return []
        return sorted(result)

    def series_of(self, metric_id: int) -> list[SeriesId]:
        """All known TSIDs of a metric (the no-tag-filter downsample scope)."""
        with self._mu:
            return sorted(t for m, t in self._known if m == metric_id)

    def label_values(self, metric_id: int, key: bytes) -> list[bytes]:
        """LabelValues via the inverted index (the RFC's two-step fallback,
        RFC :120-130)."""
        out = set()
        with self._mu:
            for pk in self._metric_postings.get(metric_id, ()):
                for kv in self._postings[pk].values():
                    if kv[0] == key:
                        out.add(kv[1])
        return sorted(out)

    def series_labels(self, metric_id: int) -> dict[int, dict[bytes, bytes]]:
        """tsid -> label map for every series of a metric, including series
        with no tags at all (seeded from the known-series set so tagless
        series don't vanish from listings)."""
        with self._mu:
            per_tsid: dict[int, dict[bytes, bytes]] = {
                t: {} for m, t in self._known if m == metric_id
            }
            for pk in self._metric_postings.get(metric_id, ()):
                for tsid, (k, v) in self._postings[pk].items():
                    per_tsid.setdefault(tsid, {})[k] = v
        return per_tsid
