"""IndexManager: series registry + inverted tag index.

Implements the reference's `IndexManager::populate_series_ids` skeleton
(src/metric_engine/src/index/mod.rs:34-41, dead code in the snapshot) per
the RFC: a `series` table mapping (metric_id, tsid) -> canonical series key,
and an inverted `index` table mapping (metric_id, tag KV) -> posting list of
TSIDs (RFC :114-136).

Scale design (RFC's 10M-series design point): the index is TWO-TIER.

- BASE: immutable numpy/arrow arrays per metric, built vectorized at open
  (no per-row Python objects) — sorted tsid arrays for membership via
  searchsorted, posting rows sorted by tag_hash for range lookup, tag
  key/value kept as arrow binary arrays. Regex matchers evaluate once per
  UNIQUE value via dictionary encoding, then fan out to series by code —
  latency scales with distinct values, not series.
- DELTA: plain dicts holding series registered since open; merged into a
  fresh base (atomic swap) when it grows past a threshold.

Query side: `find_tsids` intersects posting lists for the given tag filters
— the host-side index probe whose result feeds the device-side TSID
set-membership filter (SURVEY §7.7). Hash collisions are handled by
verifying the stored raw tag bytes.
"""

from __future__ import annotations

import logging
import threading
from collections import defaultdict
from dataclasses import dataclass

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from horaedb_tpu.engine.tables import INDEX_SCHEMA, SERIES_SCHEMA, TAGS_SCHEMA
from horaedb_tpu.engine.types import (
    SeriesId,
    decode_series_key,
    series_id_of,
    series_key_of,
    tag_hash_of,
)
from horaedb_tpu.storage.read import ScanRequest, WriteRequest
from horaedb_tpu.storage.types import TimeRange

logger = logging.getLogger(__name__)

_ALL_TIME = TimeRange(-(2**62), 2**62)

# Python `re` lacks RE2's linear-time guarantee; bounding pattern size limits
# the blast radius of untrusted matcher patterns (the evaluation also runs
# off the event loop, engine.py::_resolve_query_async).
MAX_REGEX_LEN = 512
# A regex matcher that would run against a label value longer than this
# raises instead (never silently truncates — wrong matches are worse than a
# loud error): sre backtracking cost grows with subject length and runs in C
# holding the GIL, so a thread offload alone cannot contain it.
MAX_REGEX_SUBJECT_LEN = 4096

# Delta series count that triggers a merge into the base arrays.
DELTA_COMPACT_THRESHOLD = 65_536
# Recently-seen (metric_id, tsid) cache bound: O(1) steady-state ingest
# probes; cleared wholesale when full (cold probes fall through to the
# base/delta tiers, so correctness never depends on it).
SEEN_CACHE_MAX = 1 << 20


def _reject_catastrophic(pattern: str) -> None:
    """Reject patterns with nested unbounded repeats (the `(a+)+b` shape):
    sre backtracks exponentially on them while holding the GIL, freezing the
    whole process, not just the worker thread. A parse-tree walk catches the
    common catastrophic shapes; the length caps bound what slips through.
    Deliberately strict: `([a-z]+\\.)+` -style selectors are refused too —
    they are the textbook ReDoS shape on failing subjects."""
    try:
        import re._parser as sre_parse  # Python >= 3.11
    except ImportError:  # 3.10 spells the private parser sre_parse
        import sre_parse

    from horaedb_tpu.common.error import HoraeError

    def walk(items, in_repeat: bool) -> None:
        for op, arg in items:
            name = str(op)
            if name in ("MAX_REPEAT", "MIN_REPEAT"):
                _lo, hi, sub = arg
                unbounded = hi is sre_parse.MAXREPEAT or hi >= 1 << 16
                # a counted outer repeat like (a+){2,100} backtracks
                # combinatorially too: any repeat wider than a few counts
                # as repeat context
                repeatish = unbounded or hi > 10
                if in_repeat and repeatish:
                    raise HoraeError(
                        "regex matcher rejected: nested wide repetition "
                        "(catastrophic backtracking risk)"
                    )
                walk(sub, in_repeat or repeatish)
            elif name == "SUBPATTERN":
                walk(arg[3], in_repeat)
            elif name == "BRANCH":
                for alt in arg[1]:
                    walk(alt, in_repeat)
            elif name in ("ASSERT", "ASSERT_NOT"):
                walk(arg[1], in_repeat)

    try:
        tree = sre_parse.parse(pattern)
    except Exception:  # noqa: BLE001 — compile() will surface the real error
        return
    walk(tree, False)


def _compile_matcher(pattern: bytes):
    import re as _re

    from horaedb_tpu.common.error import HoraeError

    if len(pattern) > MAX_REGEX_LEN:
        raise HoraeError(
            f"regex matcher too long ({len(pattern)} > {MAX_REGEX_LEN})"
        )
    decoded = pattern.decode(errors="replace")
    _reject_catastrophic(decoded)
    try:
        return _re.compile(decoded)
    except _re.error as e:
        raise HoraeError(f"bad regex matcher {pattern!r}: {e}") from e


def _subject_of(raw: bytes) -> str:
    from horaedb_tpu.common.error import HoraeError

    if len(raw) > MAX_REGEX_SUBJECT_LEN:
        raise HoraeError(
            f"label value too long for regex matcher "
            f"({len(raw)} > {MAX_REGEX_SUBJECT_LEN} bytes); "
            f"use equality filters for this label"
        )
    return raw.decode(errors="replace")


@dataclass
class _MetricIndex:
    """One metric's immutable base arrays."""

    tsids: np.ndarray       # u64, sorted — the series set
    p_hash: np.ndarray      # u64 posting tag_hash, sorted
    p_tsid: np.ndarray      # u64 aligned with p_hash
    p_key: pa.Array         # binary aligned
    p_value: pa.Array       # binary aligned

    def has_tsid(self, tsid: int) -> bool:
        i = np.searchsorted(self.tsids, np.uint64(tsid))
        return i < len(self.tsids) and int(self.tsids[i]) == tsid

    def posting(self, h: int, k: bytes, v: bytes) -> np.ndarray:
        """TSIDs whose (k, v) posting matches — raw bytes verified."""
        lo = np.searchsorted(self.p_hash, np.uint64(h), side="left")
        hi = np.searchsorted(self.p_hash, np.uint64(h), side="right")
        if lo == hi:
            return self.p_tsid[0:0]
        keys = self.p_key.slice(lo, hi - lo)
        vals = self.p_value.slice(lo, hi - lo)
        ok = pc.and_(pc.equal(keys, k), pc.equal(vals, v))
        return self.p_tsid[lo:hi][np.asarray(ok.to_numpy(zero_copy_only=False))]

    def key_rows(self, k: bytes) -> tuple[np.ndarray, pa.Array]:
        """(tsids, values) of every posting row whose key == k."""
        ok = np.asarray(pc.equal(self.p_key, k).to_numpy(zero_copy_only=False))
        idx = np.flatnonzero(ok)
        return self.p_tsid[idx], self.p_value.take(pa.array(idx))


_EMPTY = _MetricIndex(
    tsids=np.empty(0, np.uint64),
    p_hash=np.empty(0, np.uint64),
    p_tsid=np.empty(0, np.uint64),
    p_key=pa.array([], pa.binary()),
    p_value=pa.array([], pa.binary()),
)


def _build_base(
    s_mid: np.ndarray, s_tsid: np.ndarray,
    i_mid: np.ndarray, i_hash: np.ndarray, i_tsid: np.ndarray,
    i_key: pa.Array, i_value: pa.Array,
) -> dict[int, _MetricIndex]:
    """Group flat table arrays into per-metric sorted bases — vectorized,
    no per-row Python."""
    out: dict[int, _MetricIndex] = {}
    if len(s_mid):
        order = np.lexsort((s_tsid, s_mid))
        s_mid, s_tsid = s_mid[order], s_tsid[order]
        mids, starts = np.unique(s_mid, return_index=True)
        bounds = np.append(starts, len(s_mid))
        for j, m in enumerate(mids.tolist()):
            ts = np.unique(s_tsid[bounds[j]:bounds[j + 1]])
            out[m] = _MetricIndex(
                tsids=ts,
                p_hash=_EMPTY.p_hash, p_tsid=_EMPTY.p_tsid,
                p_key=_EMPTY.p_key, p_value=_EMPTY.p_value,
            )
    if len(i_mid):
        order = np.lexsort((i_hash, i_mid))
        i_mid, i_hash, i_tsid = i_mid[order], i_hash[order], i_tsid[order]
        take = pa.array(order)
        i_key = i_key.take(take)
        i_value = i_value.take(take)
        mids, starts = np.unique(i_mid, return_index=True)
        bounds = np.append(starts, len(i_mid))
        for j, m in enumerate(mids.tolist()):
            lo, hi = int(bounds[j]), int(bounds[j + 1])
            prev = out.get(m, _EMPTY)
            out[m] = _MetricIndex(
                tsids=prev.tsids,
                p_hash=i_hash[lo:hi],
                p_tsid=i_tsid[lo:hi],
                p_key=i_key.slice(lo, hi - lo).combine_chunks()
                if isinstance(i_key, pa.ChunkedArray) else i_key.slice(lo, hi - lo),
                p_value=i_value.slice(lo, hi - lo).combine_chunks()
                if isinstance(i_value, pa.ChunkedArray) else i_value.slice(lo, hi - lo),
            )
    return out


class IndexManager:
    def __init__(
        self,
        series_storage,
        index_storage,
        segment_duration_ms: int,
        sidecar_store=None,
        sidecar_path: str = "",
        tags_storage=None,
        read_only: bool = False,
    ):
        self._series = series_storage
        self._index = index_storage
        self._segment_duration = segment_duration_ms
        # cluster replica mode: the index is a VIEW rebuilt from another
        # writer's tables — never dump the sidecar cache or backfill tags
        # rows (both are store writes a replica must not issue)
        self._read_only = read_only
        # RFC :118-130 optional `tags` table: one row per distinct
        # (metric, key, value) — the storage-backed LabelValues surface.
        # pk = (metric_id, tag_hash): the engine accepts 64-bit hash
        # identity here exactly as it does for TSIDs (reference contract,
        # types.rs:18-41). The seen-set only suppresses duplicate WRITES
        # (rewrites are idempotent pk overwrites), so it starts empty per
        # process without any correctness cost.
        self._tags = tags_storage
        self._tags_seen: set[tuple[int, int]] = set()
        # Arrow-IPC base sidecar (VERDICT r03 #7): open used to be O(full
        # rebuild) — a scan of the whole series+index tables (~10 s at 1M
        # series, ~100 s at the RFC's 10M design point). The sidecar dumps
        # the folded base at close (and after a cold rebuild), stamped with
        # the max SST id it covers; open loads it and replays only the SSTs
        # that landed after the watermark.
        self._sidecar_store = sidecar_store
        self._sidecar_path = sidecar_path
        # BASE tier: metric_id -> immutable arrays (atomic reference swap)
        self._base: dict[int, _MetricIndex] = {}
        # DELTA tier (series registered since open/compact):
        # metric_id -> tsids registered since the base was built
        self._metric_known: dict[int, set[int]] = defaultdict(set)
        self._delta_series = 0
        # recently-seen ingest probe cache (see SEEN_CACHE_MAX)
        self._seen_cache: set[tuple[int, int]] = set()
        # (metric_id, tag_hash) -> {tsid -> (key, value)} posting lists
        self._postings: dict[tuple[int, int], dict[int, tuple[bytes, bytes]]] = defaultdict(dict)
        # metric_id -> its posting keys (per-metric scans stay O(one metric))
        self._metric_postings: dict[int, set[tuple[int, int]]] = defaultdict(set)
        # Guards the delta structures + the base reference: queries run in
        # worker threads (engine.py::_resolve_query_async) while ingest
        # mutates on the event loop; iterating a mutating set/dict raises
        # RuntimeError. Held only for in-memory access — never across
        # awaits or regex evaluation (base arrays are immutable, so readers
        # use them lock-free after grabbing the reference). Lock sections
        # copy ONLY what the query needs (per-hash postings, one metric's
        # delta) — never the whole delta.
        self._mu = threading.Lock()
        # Serializes delta->base compactions (run in a worker thread).
        self._compact_lock: "asyncio.Lock | None" = None

    async def open(self) -> None:
        watermark = await self._load_sidecar()
        if watermark is not None:
            await self._replay_since(watermark)
            await self._backfill_tags()
            return
        await self._rebuild_from_tables()
        await self._backfill_tags()
        if self._read_only:
            return  # a replica view never writes the sidecar cache
        # make the NEXT open fast even if this process never closes cleanly;
        # best-effort — the sidecar is a cache, a failed put must not abort
        # an open whose rebuild just succeeded
        try:
            await self.dump_sidecar()
        except Exception:  # noqa: BLE001
            logger.warning("index sidecar write failed at open; next open "
                           "will rebuild", exc_info=True)

    async def _rebuild_from_tables(self) -> None:
        s_mid, s_tsid = [], []
        req = ScanRequest(range=_ALL_TIME)
        async for batch in self._series.scan(req):
            s_mid.append(batch.column("metric_id").to_numpy(zero_copy_only=False))
            s_tsid.append(batch.column("tsid").to_numpy(zero_copy_only=False))
        i_mid, i_hash, i_tsid, i_key, i_val = [], [], [], [], []
        async for batch in self._index.scan(req):
            i_mid.append(batch.column("metric_id").to_numpy(zero_copy_only=False))
            i_hash.append(batch.column("tag_hash").to_numpy(zero_copy_only=False))
            i_tsid.append(batch.column("tsid").to_numpy(zero_copy_only=False))
            i_key.append(batch.column("tag_key"))
            i_val.append(batch.column("tag_value"))

        def cat(parts, dtype):
            return (np.concatenate(parts).astype(dtype, copy=False)
                    if parts else np.empty(0, dtype))

        def cat_arrow(parts):
            if not parts:
                return _EMPTY.p_key
            return pa.concat_arrays(
                [c for p in parts for c in (p.chunks if isinstance(p, pa.ChunkedArray) else [p])]
            )

        self._base = _build_base(
            cat(s_mid, np.uint64), cat(s_tsid, np.uint64),
            cat(i_mid, np.uint64), cat(i_hash, np.uint64), cat(i_tsid, np.uint64),
            cat_arrow(i_key), cat_arrow(i_val),
        )

    # -- base sidecar ---------------------------------------------------------
    # Layout: b"HIDX" + u32 version + u64 watermark + u64 len(bounds ipc) +
    # u64 len(series ipc), then three Arrow IPC streams:
    #   bounds:   metric_id / s_start / s_count / p_start / p_count
    #   series:   metric_id / tsid            (sorted by (metric_id, tsid))
    #   postings: metric_id / tag_hash / tsid / tag_key / tag_value
    #                                         (sorted by (metric_id, hash))
    # The arrays are dumped PRE-SORTED with per-metric boundaries, so load
    # skips every sort: per metric it takes O(1) numpy views / arrow slices
    # of the (possibly memory-mapped) buffers — open cost is O(#metrics),
    # not O(#series log #series). Loaded with a blanket try/except: a
    # corrupt or version-skewed sidecar falls back to the full rebuild — it
    # is a CACHE of the tables, never the source of truth.

    _SIDECAR_MAGIC = b"HIDX"
    _SIDECAR_VERSION = 2

    def _watermark(self) -> int:
        ids = [s.id for s in self._series._manifest.all_ssts()]
        ids += [s.id for s in self._index._manifest.all_ssts()]
        return max(ids, default=0)

    async def dump_sidecar(self) -> None:
        """Write the folded base+delta as the sidecar. Callers must be
        quiesced (open/close): with registrations in flight, a row can be
        durable in an SST <= watermark but not yet committed to the delta,
        and the dump would lose it."""
        if self._sidecar_store is None or self._read_only:
            return
        with self._mu:
            base = dict(self._base)
            known = {m: set(s) for m, s in self._metric_known.items()}
            postings = {k: dict(v) for k, v in self._postings.items()}
        watermark = self._watermark()

        def build() -> bytes:
            # flatten base + delta, one global sort each, then per-metric
            # boundaries — the LOAD side never sorts
            s_mid_l: list[np.ndarray] = []
            s_tsid_l: list[np.ndarray] = []
            for m, b in base.items():
                s_mid_l.append(np.full(len(b.tsids), m, np.uint64))
                s_tsid_l.append(np.asarray(b.tsids, np.uint64))
            for m, s in known.items():
                arr = np.fromiter(s, np.uint64, len(s))
                s_mid_l.append(np.full(len(arr), m, np.uint64))
                s_tsid_l.append(arr)
            s_mid = (np.concatenate(s_mid_l) if s_mid_l
                     else np.empty(0, np.uint64))
            s_tsid = (np.concatenate(s_tsid_l) if s_tsid_l
                      else np.empty(0, np.uint64))
            order = np.lexsort((s_tsid, s_mid))
            s_mid, s_tsid = s_mid[order], s_tsid[order]
            if len(s_mid):  # dedup (mid, tsid) pairs — base invariant
                keep = np.ones(len(s_mid), bool)
                keep[1:] = (s_mid[1:] != s_mid[:-1]) | (s_tsid[1:] != s_tsid[:-1])
                s_mid, s_tsid = s_mid[keep], s_tsid[keep]

            i_mid_l = [np.full(len(b.p_hash), m, np.uint64)
                       for m, b in base.items() if len(b.p_hash)]
            i_hash_l = [np.asarray(b.p_hash) for b in base.values()
                        if len(b.p_hash)]
            i_tsid_l = [np.asarray(b.p_tsid) for b in base.values()
                        if len(b.p_hash)]
            i_kv_l = [(b.p_key, b.p_value) for b in base.values()
                      if len(b.p_hash)]
            d_mid, d_hash, d_tsid, d_k, d_v = [], [], [], [], []
            for (m, h), rows in postings.items():
                for t, (k, v) in rows.items():
                    d_mid.append(m)
                    d_hash.append(h)
                    d_tsid.append(t)
                    d_k.append(k)
                    d_v.append(v)
            if d_mid:
                i_mid_l.append(np.asarray(d_mid, np.uint64))
                i_hash_l.append(np.asarray(d_hash, np.uint64))
                i_tsid_l.append(np.asarray(d_tsid, np.uint64))
                i_kv_l.append((pa.array(d_k, pa.binary()),
                               pa.array(d_v, pa.binary())))
            if i_mid_l:
                i_mid = np.concatenate(i_mid_l)
                i_hash = np.concatenate(i_hash_l)
                i_tsid = np.concatenate(i_tsid_l)
                i_key = pa.concat_arrays([
                    c for k, _ in i_kv_l
                    for c in (k.chunks if isinstance(k, pa.ChunkedArray) else [k])
                ])
                i_val = pa.concat_arrays([
                    c for _, v in i_kv_l
                    for c in (v.chunks if isinstance(v, pa.ChunkedArray) else [v])
                ])
                iorder = np.lexsort((i_hash, i_mid))
                i_mid, i_hash, i_tsid = (
                    i_mid[iorder], i_hash[iorder], i_tsid[iorder]
                )
                take = pa.array(iorder)
                i_key, i_val = i_key.take(take), i_val.take(take)
            else:
                i_mid = i_hash = i_tsid = np.empty(0, np.uint64)
                i_key = i_val = pa.array([], pa.binary())

            # per-metric boundaries over BOTH sorted tables
            mids = np.union1d(np.unique(s_mid), np.unique(i_mid))
            s_start = np.searchsorted(s_mid, mids, side="left")
            s_end = np.searchsorted(s_mid, mids, side="right")
            p_start = np.searchsorted(i_mid, mids, side="left")
            p_end = np.searchsorted(i_mid, mids, side="right")
            bounds = pa.table({
                "metric_id": mids.astype(np.uint64),
                "s_start": s_start.astype(np.int64),
                "s_count": (s_end - s_start).astype(np.int64),
                "p_start": p_start.astype(np.int64),
                "p_count": (p_end - p_start).astype(np.int64),
            })
            s_table = pa.table({"metric_id": s_mid, "tsid": s_tsid})
            i_table = pa.table({
                "metric_id": i_mid, "tag_hash": i_hash, "tsid": i_tsid,
                "tag_key": i_key, "tag_value": i_val,
            })

            def ipc(table: pa.Table) -> bytes:
                sink = pa.BufferOutputStream()
                with pa.ipc.new_stream(sink, table.schema) as w:
                    w.write_table(table)
                return sink.getvalue().to_pybytes()

            b_ipc, s_ipc, i_ipc = ipc(bounds), ipc(s_table), ipc(i_table)
            import struct

            header = self._SIDECAR_MAGIC + struct.pack(
                "<IQQQ", self._SIDECAR_VERSION, watermark,
                len(b_ipc), len(s_ipc),
            )
            return header + b_ipc + s_ipc + i_ipc

        import asyncio

        payload = await asyncio.to_thread(build)
        # jaxlint: disable=J008 control-plane sidecar dump at quiesce/close, not the append path
        await self._sidecar_store.put(self._sidecar_path, payload)

    async def _load_sidecar(self) -> int | None:
        """Load the base from the sidecar; returns its watermark, or None
        (absent/corrupt/stale-version) meaning: do the full rebuild.

        Zero-sort load: the payload is pre-sorted with per-metric
        boundaries, so this is one buffer read (memory-mapped when the
        store has a local path) + O(#metrics) numpy views / arrow slices."""
        if self._sidecar_store is None:
            return None
        import struct

        from horaedb_tpu.objstore import NotFound

        local = self._sidecar_store.local_path(self._sidecar_path)
        try:
            if local is not None:
                try:
                    buf = pa.memory_map(local).read_buffer()
                except (OSError, pa.ArrowInvalid):
                    return None
                payload = memoryview(buf)
            else:
                payload = memoryview(await self._sidecar_store.get(
                    self._sidecar_path
                ))
        except NotFound:
            return None
        try:
            if bytes(payload[:4]) != self._SIDECAR_MAGIC:
                return None
            version, watermark, b_len, s_len = struct.unpack(
                "<IQQQ", payload[4:32]
            )
            if version != self._SIDECAR_VERSION:
                return None
            body = payload[32:]
            bounds = pa.ipc.open_stream(body[:b_len]).read_all()
            s_table = pa.ipc.open_stream(body[b_len:b_len + s_len]).read_all()
            i_table = pa.ipc.open_stream(body[b_len + s_len:]).read_all()

            def flat(table, name) -> np.ndarray:
                col = table.column(name)
                return col.to_numpy(zero_copy_only=False)

            s_tsid = flat(s_table, "tsid").astype(np.uint64, copy=False)
            i_hash = flat(i_table, "tag_hash").astype(np.uint64, copy=False)
            i_tsid = flat(i_table, "tsid").astype(np.uint64, copy=False)

            def bin_col(table, name) -> pa.Array:
                col = table.column(name)
                return (col.combine_chunks()
                        if isinstance(col, pa.ChunkedArray) else col)

            i_key = bin_col(i_table, "tag_key")
            i_val = bin_col(i_table, "tag_value")

            base: dict[int, _MetricIndex] = {}
            for m, ss, sc, ps, pc in zip(
                flat(bounds, "metric_id").tolist(),
                flat(bounds, "s_start").tolist(),
                flat(bounds, "s_count").tolist(),
                flat(bounds, "p_start").tolist(),
                flat(bounds, "p_count").tolist(),
            ):
                base[m] = _MetricIndex(
                    tsids=s_tsid[ss:ss + sc],
                    p_hash=i_hash[ps:ps + pc],
                    p_tsid=i_tsid[ps:ps + pc],
                    p_key=i_key.slice(ps, pc),
                    p_value=i_val.slice(ps, pc),
                )
            self._base = base
            return int(watermark)
        except Exception:  # noqa: BLE001 — cache corrupt: rebuild from truth
            self._base = {}
            return None

    async def _replay_since(self, watermark: int) -> None:
        """Fold SSTs newer than the sidecar watermark into the delta.
        Idempotent by construction: compaction outputs carry fresh file ids,
        so already-based rows can reappear — the known-series filter drops
        them (series and their postings are always persisted together)."""
        req = ScanRequest(range=_ALL_TIME, min_sst_id=watermark)
        new_pairs: set[tuple[int, int]] = set()
        series_rows: list[tuple[int, int, bytes]] = []
        async for batch in self._series.scan(req):
            mids = batch.column("metric_id").to_pylist()
            tsids = batch.column("tsid").to_pylist()
            keys = batch.column("series_key").to_pylist()
            for m, t, k in zip(mids, tsids, keys):
                if (m, t) not in new_pairs and not self._is_known(m, t):
                    new_pairs.add((m, t))
                    series_rows.append((m, t, k))
        index_rows: list[tuple[int, int, int, bytes, bytes]] = []
        if new_pairs:
            async for batch in self._index.scan(req):
                mids = batch.column("metric_id").to_pylist()
                hashes = batch.column("tag_hash").to_pylist()
                tsids = batch.column("tsid").to_pylist()
                ks = batch.column("tag_key").to_pylist()
                vs = batch.column("tag_value").to_pylist()
                for m, h, t, k, v in zip(mids, hashes, tsids, ks, vs):
                    if (m, t) in new_pairs:
                        index_rows.append((m, h, t, k, v))
        if series_rows:
            # a large crash replay can overfill the delta tier — honor the
            # compaction signal exactly like the live registration paths
            if self._commit_rows(series_rows, index_rows):
                await self._compact_delta()

    # -- write path ----------------------------------------------------------
    def _is_known(self, mid: int, tsid: int) -> bool:
        base = self._base.get(mid)
        if base is not None and base.has_tsid(tsid):
            return True
        delta = self._metric_known.get(mid)
        return delta is not None and tsid in delta

    async def populate_series_ids(
        self,
        metric_ids: list[int],
        label_sets: list[list[tuple[bytes, bytes]]],
        now_ms: int,
    ) -> list[SeriesId]:
        """Resolve TSIDs for (metric, labels) pairs, registering new series
        in the series table and the inverted index."""
        tsids: list[SeriesId] = []
        new_series_rows: list[tuple[int, int, bytes]] = []
        new_index_rows: list[tuple[int, int, int, bytes, bytes]] = []
        staged: set[tuple[int, int]] = set()
        for mid, labels in zip(metric_ids, label_sets):
            key = series_key_of(labels)
            tsid = series_id_of(key)
            tsids.append(tsid)
            if self._is_known(mid, tsid) or (mid, tsid) in staged:
                continue
            staged.add((mid, tsid))
            new_series_rows.append((mid, tsid, key))
            for k, v in labels:
                new_index_rows.append((mid, tag_hash_of(k, v), tsid, k, v))
        if new_series_rows:
            # Persist FIRST, update caches only on success: caching before a
            # failed write would mark the series known while the durable
            # index rows never land, silently dropping it from tag queries
            # after the client's retry (and from recovery after restart).
            await self._persist(new_series_rows, new_index_rows, now_ms)
            if self._commit_rows(new_series_rows, new_index_rows):
                await self._compact_delta()
        return tsids

    def _commit_rows(self, series_rows, index_rows) -> bool:
        """Apply persisted rows to the in-memory delta (under the lock —
        queries read these structures from worker threads). Returns True
        when the delta is due for compaction."""
        with self._mu:
            for mid, tsid, _key in series_rows:
                s = self._metric_known[mid]
                if tsid not in s:
                    s.add(tsid)
                    self._delta_series += 1
            for mid, h, tsid, k, v in index_rows:
                self._postings[(mid, h)][tsid] = (k, v)
                self._metric_postings[mid].add((mid, h))
            return self._delta_series >= DELTA_COMPACT_THRESHOLD

    async def _compact_delta(self) -> None:
        """Merge the delta dicts into fresh base arrays (atomic swap).

        The heavy merge runs in a worker thread — the base is immutable, so
        the event loop only pays the two short lock sections. Registrations
        that land WHILE merging survive: the swap subtracts exactly the
        snapshot that was merged instead of clearing the delta."""
        import asyncio

        if self._compact_lock is None:
            self._compact_lock = asyncio.Lock()
        async with self._compact_lock:
            with self._mu:
                # re-check: writers queued behind an in-flight merge must
                # not each repeat a full-base merge on a near-empty delta
                if self._delta_series < DELTA_COMPACT_THRESHOLD:
                    return
                known = {m: set(s) for m, s in self._metric_known.items()}
                postings = {k: dict(v) for k, v in self._postings.items()}
                base = self._base
            merged = await asyncio.to_thread(
                self._merge_delta_into_base, base, known, postings
            )
            with self._mu:
                self._base = merged
                for m, s in known.items():
                    live = self._metric_known.get(m)
                    if live is not None:
                        live -= s
                        self._delta_series -= len(s)
                        if not live:
                            del self._metric_known[m]
                for pk, rows in postings.items():
                    live_rows = self._postings.get(pk)
                    if live_rows is None:
                        continue
                    for t in rows:
                        live_rows.pop(t, None)
                    if not live_rows:
                        del self._postings[pk]
                        mp = self._metric_postings.get(pk[0])
                        if mp is not None:
                            mp.discard(pk)
                            if not mp:
                                del self._metric_postings[pk[0]]

    @staticmethod
    def _merge_delta_into_base(
        base: dict[int, _MetricIndex], known, postings
    ) -> dict[int, _MetricIndex]:
        s_mid_l, s_tsid_l = [], []
        for m, s in known.items():
            s_mid_l.extend([m] * len(s))
            s_tsid_l.extend(s)
        i_mid, i_hash, i_tsid, i_key, i_val = [], [], [], [], []
        for (m, h), rows in postings.items():
            for t, (k, v) in rows.items():
                i_mid.append(m)
                i_hash.append(h)
                i_tsid.append(t)
                i_key.append(k)
                i_val.append(v)
        delta_base = _build_base(
            np.asarray(s_mid_l, dtype=np.uint64),
            np.asarray(s_tsid_l, dtype=np.uint64),
            np.asarray(i_mid, dtype=np.uint64),
            np.asarray(i_hash, dtype=np.uint64),
            np.asarray(i_tsid, dtype=np.uint64),
            pa.array(i_key, pa.binary()),
            pa.array(i_val, pa.binary()),
        )
        merged: dict[int, _MetricIndex] = dict(base)
        for m, d in delta_base.items():
            b = merged.get(m)
            if b is None:
                merged[m] = d
                continue
            order = np.argsort(
                np.concatenate([b.p_hash, d.p_hash]), kind="stable"
            )
            ph = np.concatenate([b.p_hash, d.p_hash])[order]
            pt = np.concatenate([b.p_tsid, d.p_tsid])[order]
            keys = pa.concat_arrays([
                *(b.p_key.chunks if isinstance(b.p_key, pa.ChunkedArray) else [b.p_key]),
                *(d.p_key.chunks if isinstance(d.p_key, pa.ChunkedArray) else [d.p_key]),
            ]).take(pa.array(order))
            vals = pa.concat_arrays([
                *(b.p_value.chunks if isinstance(b.p_value, pa.ChunkedArray) else [b.p_value]),
                *(d.p_value.chunks if isinstance(d.p_value, pa.ChunkedArray) else [d.p_value]),
            ]).take(pa.array(order))
            merged[m] = _MetricIndex(
                tsids=np.unique(np.concatenate([b.tsids, d.tsids])),
                p_hash=ph, p_tsid=pt, p_key=keys, p_value=vals,
            )
        return merged

    async def ensure_series_fast(
        self,
        metric_ids: np.ndarray,  # u64 per series (native hash lanes)
        tsids: np.ndarray,       # u64 per series
        key_of,                  # series index -> canonical key bytes
        now_ms: int,
        tag_rows_of=None,        # series index -> [(hash, k, v)] | None
    ) -> None:
        """Hash-lane fast path: ids and canonical keys were computed by the
        native parser; only genuinely new series pay Python-object costs
        (key decode + posting rows — and with `tag_rows_of` the posting
        hashes too come precomputed from the C++ tag lanes). The Python
        seahash remains the differential oracle in tests, per the reference
        hash contract (src/metric_engine/src/types.rs:18-41).

        Steady-state probes hit a bounded recently-seen cache (O(1) per
        series); only cache misses consult the base/delta tiers."""
        cache = self._seen_cache
        mids = metric_ids.tolist()
        tids = tsids.tolist()
        pairs = list(zip(mids, tids))
        miss = [i for i, p in enumerate(pairs) if p not in cache]
        if not miss:
            return
        new_idx: list[int] = []
        staged: set[tuple[int, int]] = set()
        for i in miss:
            m, t = pairs[i]
            if (m, t) in staged or self._is_known(m, t):
                continue
            staged.add((m, t))
            new_idx.append(i)

        def cache_all() -> None:
            # only after the new series are DURABLE: caching unpersisted
            # pairs would mark them known while the index rows never landed
            if len(cache) > SEEN_CACHE_MAX:
                cache.clear()
            cache.update(pairs)

        if not new_idx:
            cache_all()
            return
        new_series_rows: list[tuple[int, int, bytes]] = []
        new_index_rows: list[tuple[int, int, int, bytes, bytes]] = []
        for i in new_idx:
            key = key_of(i)
            new_series_rows.append((mids[i], tids[i], key))
            rows = tag_rows_of(i) if tag_rows_of is not None else None
            if rows is not None:
                # native lanes: posting hashes precomputed in C++, k/v
                # sliced zero-copy from the payload (same sorted order as
                # the canonical key) — the Python seahash survives only as
                # the differential oracle (tests/test_ingest.py)
                for h, k, v in rows:
                    new_index_rows.append((mids[i], h, tids[i], k, v))
            else:
                for k, v in decode_series_key(key):
                    new_index_rows.append(
                        (mids[i], tag_hash_of(k, v), tids[i], k, v)
                    )
        # persist-before-cache, same reasoning as populate_series_ids
        await self._persist(new_series_rows, new_index_rows, now_ms)
        oversized = self._commit_rows(new_series_rows, new_index_rows)
        cache_all()
        if oversized:
            await self._compact_delta()

    async def _persist(self, series_rows, index_rows, now_ms: int) -> None:
        seg_start = now_ms - now_ms % self._segment_duration
        rng = TimeRange(seg_start, seg_start + 1)
        s_batch = pa.RecordBatch.from_pydict(
            {
                "metric_id": np.asarray([r[0] for r in series_rows], dtype=np.uint64),
                "tsid": np.asarray([r[1] for r in series_rows], dtype=np.uint64),
                "series_key": [r[2] for r in series_rows],
            },
            schema=SERIES_SCHEMA,
        )
        if not index_rows:
            await self._series.write(WriteRequest(s_batch, rng))
            return
        # optional tags table first: distinct (metric, key, value) rows are
        # advisory ghosts until the index/series writes land — harmless on
        # a crash, and writing them last could lose a LabelValues row for
        # an acked series forever
        if self._tags is not None:
            dedup: dict[tuple[int, int], tuple] = {}
            for m, h, _t, k, v in index_rows:
                if (m, h) not in self._tags_seen:
                    dedup.setdefault((m, h), (m, h, k, v))
            if dedup:
                await self._write_tags_rows(list(dedup.values()), rng)
        i_batch = pa.RecordBatch.from_pydict(
            {
                "metric_id": np.asarray([r[0] for r in index_rows], dtype=np.uint64),
                "tag_hash": np.asarray([r[1] for r in index_rows], dtype=np.uint64),
                "tsid": np.asarray([r[2] for r in index_rows], dtype=np.uint64),
                "tag_key": [r[3] for r in index_rows],
                "tag_value": [r[4] for r in index_rows],
            },
            schema=INDEX_SCHEMA,
        )
        # index BEFORE series: "known" (series-ack) derives from the SERIES
        # table (_is_known), so the recoverable half-state must be
        # postings-without-series — a benign ghost (no samples can have been
        # acked for it; a retry rewrites both batches idempotently, pk+seq
        # dedup). The inverse order would leave a series marked known with
        # its postings missing FOREVER: tag-filtered queries would silently
        # skip it while its samples keep landing.
        await self._index.write(WriteRequest(i_batch, rng))
        await self._series.write(WriteRequest(s_batch, rng))

    async def _write_tags_rows(
        self, rows: list[tuple], rng: TimeRange
    ) -> None:
        """Write distinct (metric_id, tag_hash, key, value) rows to the
        tags table and record them in the bounded seen-set (cleared
        wholesale at the cap, like the series seen-cache — a miss only
        costs an idempotent pk-overwrite rewrite)."""
        t_batch = pa.RecordBatch.from_pydict(
            {
                "metric_id": np.asarray([r[0] for r in rows], dtype=np.uint64),
                "tag_hash": np.asarray([r[1] for r in rows], dtype=np.uint64),
                "tag_key": [r[2] for r in rows],
                "tag_value": [r[3] for r in rows],
            },
            schema=TAGS_SCHEMA,
        )
        await self._tags.write(WriteRequest(t_batch, rng))
        if len(self._tags_seen) > SEEN_CACHE_MAX:
            self._tags_seen.clear()
        self._tags_seen.update((r[0], r[1]) for r in rows)

    async def _backfill_tags(self) -> None:
        """One-time migration: a store written before the tags table
        existed has series/index rows but no tags rows — backfill distinct
        pairs from the freshly-opened in-memory index so
        label_values_storage agrees with label_values on legacy stores."""
        if self._read_only or self._tags is None \
                or self._tags._manifest.all_ssts():
            return
        with self._mu:
            base = dict(self._base)
            postings = {k: dict(v) for k, v in self._postings.items()}
        rows: dict[tuple[int, int], tuple] = {}
        for m, b in base.items():
            if not len(b.p_hash):
                continue
            keys = b.p_key.to_pylist()
            vals = b.p_value.to_pylist()
            for h, k, v in zip(b.p_hash.tolist(), keys, vals):
                rows.setdefault((m, h), (m, h, k, v))
        for (m, h), rrows in postings.items():
            for _t, (k, v) in rrows.items():
                rows.setdefault((m, h), (m, h, k, v))
                break
        if not rows:
            return
        from horaedb_tpu.common.time_ext import now_ms as _now_ms

        now = _now_ms()
        seg_start = now - now % self._segment_duration
        await self._write_tags_rows(
            list(rows.values()), TimeRange(seg_start, seg_start + 1)
        )
        logger.info("backfilled %d tags rows from the index", len(rows))

    # -- query path ------------------------------------------------------------
    def _metric_delta(self, metric_id: int):
        """Copy ONE metric's delta (postings + tsids) under the lock — used
        by matcher/listing paths; equality filters copy per-hash instead."""
        with self._mu:
            base = self._base.get(metric_id, _EMPTY)
            delta_keys = list(self._metric_postings.get(metric_id, ()))
            delta_postings = {pk: dict(self._postings[pk]) for pk in delta_keys}
            delta_tsids = set(self._metric_known.get(metric_id, ()))
        return base, delta_postings, delta_tsids

    def find_tsids(
        self,
        metric_id: int,
        filters: list[tuple[bytes, bytes]],
        matchers: "list[tuple[bytes, str, bytes]] | None" = None,
    ) -> list[SeriesId] | None:
        """TSIDs matching ALL tag filters; None means 'no constraint' (caller
        scans the whole metric). Posting lists verify raw bytes to reject
        hash collisions.

        `matchers` extends equality with Prometheus-style ops per
        (key, op, pattern): "ne" (!=), "re" (=~ full-match), "nre" (!~).
        Base postings evaluate regexes once per unique value (arrow
        dictionary encoding); only matching series materialize Python ints."""
        if not filters and not matchers:
            return None
        result: set[int] | None = None

        def intersect(matched: set[int]) -> bool:
            nonlocal result
            result = matched if result is None else (result & matched)
            return bool(result)

        if filters:
            hashes = [tag_hash_of(k, v) for k, v in filters]
            with self._mu:
                base = self._base.get(metric_id, _EMPTY)
                flt_delta = [
                    dict(self._postings.get((metric_id, h), {})) for h in hashes
                ]
            for (k, v), h, drows in zip(filters, hashes, flt_delta):
                matched = set(base.posting(h, k, v).tolist())
                for t, kv in drows.items():
                    if kv == (k, v):
                        matched.add(t)
                if not intersect(matched):
                    return []
        if matchers:
            base, delta_postings, delta_tsids = self._metric_delta(metric_id)
            # all_tsids/present materialize O(series) Python ints — computed
            # lazily, only in the branches that actually union over absent
            # series ('nre', '!= non-empty', or a regex matching empty)
            _all: list[set] = []

            def all_tsids() -> set:
                if not _all:
                    _all.append(set(base.tsids.tolist()) | delta_tsids)
                return _all[0]

            for k, op, pattern in matchers:
                # base rows for this key, dictionary-encoded: the predicate
                # evaluates once per UNIQUE value, series fan out by code
                b_tsids, b_values = base.key_rows(k)
                enc = b_values.dictionary_encode()
                uniq_vals = enc.dictionary.to_pylist()
                codes = np.asarray(enc.indices.to_numpy(zero_copy_only=False))
                # delta overlay (small): tsid -> value for this key; delta
                # wins over base on duplicates
                delta_vals: dict[int, bytes] = {}
                for _pk, rows in delta_postings.items():
                    for t, (kk, vv) in rows.items():
                        if kk == k:
                            delta_vals[t] = vv
                if op == "ne":
                    # absent label reads as b"": it matches != pattern
                    # unless the pattern is itself empty
                    ok_uniq = np.asarray([v != pattern for v in uniq_vals], bool)
                elif op in ("re", "nre"):
                    rx = _compile_matcher(pattern)
                    ok_uniq = np.asarray(
                        [rx.fullmatch(_subject_of(v)) is not None for v in uniq_vals],
                        bool,
                    )
                else:
                    from horaedb_tpu.common.error import HoraeError

                    raise HoraeError(f"unknown matcher op: {op!r}")
                hit = (
                    set(b_tsids[ok_uniq[codes]].tolist())
                    if len(b_tsids) else set()
                )
                # delta overlay corrections
                for t, v in delta_vals.items():
                    if op == "ne":
                        v_ok = v != pattern
                    else:
                        v_ok = rx.fullmatch(_subject_of(v)) is not None
                    (hit.add if v_ok else hit.discard)(t)

                def absent() -> set:
                    # absent-label semantics: value reads as b""
                    return all_tsids() - (set(b_tsids.tolist()) | set(delta_vals))

                if op == "ne":
                    if pattern != b"":
                        hit |= absent()
                    matched = hit
                else:
                    if rx.fullmatch(""):
                        hit |= absent()
                    matched = hit if op == "re" else (all_tsids() - hit)
                if not intersect(matched):
                    return []
        return sorted(result)

    async def label_values_storage(
        self, metric_id: int, key: bytes
    ) -> list[bytes]:
        """LabelValues from the DURABLE tags table (RFC :118-130: the
        two-step index fallback VM uses, accelerated to one distinct-rows
        scan). The in-memory index path (`label_values`) is faster when the
        index is resident; this surface exists for parity and for callers
        that must not depend on the in-memory tier (e.g. cold tooling over
        the object store)."""
        if self._tags is None:
            return []
        from horaedb_tpu.ops import filter as F

        out: set[bytes] = set()
        async for batch in self._tags.scan(ScanRequest(
            range=_ALL_TIME,
            predicate=F.And(
                F.Compare("metric_id", "eq", metric_id),
                F.Compare("tag_key", "eq", key),
            ),
        )):
            out.update(batch.column("tag_value").to_pylist())
        return sorted(out)

    def series_of(self, metric_id: int) -> list[SeriesId]:
        """All known TSIDs of a metric (the no-tag-filter downsample scope)."""
        with self._mu:
            base = self._base.get(metric_id, _EMPTY)
            delta_tsids = set(self._metric_known.get(metric_id, ()))
        return sorted(set(base.tsids.tolist()) | delta_tsids)

    def series_lanes(self) -> tuple[np.ndarray, np.ndarray]:
        """(metric_id, tsid) u64 lanes of EVERY registered series — the
        cardinality sketch's recovery seed at engine open (the sketch is
        in-memory; restarts rebuild it from the index, which open just
        loaded anyway)."""
        mids: list[np.ndarray] = []
        tsids: list[np.ndarray] = []
        with self._mu:
            base_items = list(self._base.items())
            delta_items = [
                (m, np.fromiter(s, dtype=np.uint64, count=len(s)))
                for m, s in self._metric_known.items() if s
            ]
        for m, idx in base_items:
            if len(idx.tsids):
                mids.append(np.full(len(idx.tsids), m, dtype=np.uint64))
                tsids.append(idx.tsids.astype(np.uint64, copy=False))
        for m, arr in delta_items:
            mids.append(np.full(len(arr), m, dtype=np.uint64))
            tsids.append(arr)
        if not mids:
            e = np.empty(0, dtype=np.uint64)
            return e, e
        return np.concatenate(mids), np.concatenate(tsids)

    def known_pairs_mask(
        self, metric_ids: np.ndarray, tsids: np.ndarray
    ) -> np.ndarray:
        """Boolean mask: which (metric_id, tsid) pairs are ALREADY
        registered. Cold path of the cardinality limiter — consulted only
        once the estimate has crossed the limit, so the per-pair Python
        probes stay off the in-budget hot path."""
        out = np.empty(len(metric_ids), dtype=bool)
        mids = metric_ids.tolist()
        tids = tsids.tolist()
        for i, (m, t) in enumerate(zip(mids, tids)):
            out[i] = self._is_known(m, t)
        return out

    def label_values(self, metric_id: int, key: bytes) -> list[bytes]:
        """LabelValues via the inverted index (the RFC's two-step fallback,
        RFC :120-130). Unique values come straight from the dictionary —
        no per-series materialization."""
        base, delta_postings, _dt = self._metric_delta(metric_id)
        _tsids, b_values = base.key_rows(key)
        out = set(b_values.dictionary_encode().dictionary.to_pylist())
        for _pk, rows in delta_postings.items():
            for _t, (kk, vv) in rows.items():
                if kk == key:
                    out.add(vv)
        return sorted(out)

    def series_labels(self, metric_id: int) -> dict[int, dict[bytes, bytes]]:
        """tsid -> label map for every series of a metric, including series
        with no tags at all (seeded from the known-series set so tagless
        series don't vanish from listings). Materializes Python objects —
        an admin/listing surface, not a hot path."""
        base, delta_postings, delta_tsids = self._metric_delta(metric_id)
        per_tsid: dict[int, dict[bytes, bytes]] = {
            int(t): {} for t in base.tsids
        }
        for t in delta_tsids:
            per_tsid.setdefault(t, {})
        kcol = base.p_key.to_pylist()
        vcol = base.p_value.to_pylist()
        for t, k, v in zip(base.p_tsid.tolist(), kcol, vcol):
            per_tsid.setdefault(t, {})[k] = v
        for _pk, rows in delta_postings.items():
            for tsid, (k, v) in rows.items():
                per_tsid.setdefault(tsid, {})[k] = v
        return per_tsid
