"""MetricManager: metric-name -> MetricId registry.

Implements the reference's `MetricManager::populate_metric_ids` skeleton
(src/metric_engine/src/metric/mod.rs:34-57): a write-through in-memory cache
over the `metrics` table. The full table loads at open (metric cardinality
is tiny next to data) and new metrics append as storage writes.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from horaedb_tpu.engine.tables import METRICS_SCHEMA
from horaedb_tpu.engine.types import MetricId, metric_id_of
from horaedb_tpu.storage.read import ScanRequest, WriteRequest
from horaedb_tpu.storage.types import TimeRange

DEFAULT_FIELD = b"value"
FIELD_TYPE_F64 = 0


class MetricManager:
    def __init__(self, storage, segment_duration_ms: int):
        self._storage = storage
        self._segment_duration = segment_duration_ms
        # name -> (metric_id, field_id); write-through cache over the table
        self._cache: dict[bytes, tuple[int, int]] = {}
        # id-keyed view of the same cache for the hash-lane fast path
        self._known_ids: set[int] = set()
        # Prometheus metric-family metadata (remote-write METADATA records,
        # prompb MetricMetadata.type). Advisory and in-memory only, like
        # Prometheus itself: clients re-send it on a slow clock.
        self.metadata: dict[bytes, str] = {}

    # prompb MetricMetadata.MetricType enum
    _PROM_TYPES = (
        "unknown", "counter", "gauge", "histogram",
        "gaugehistogram", "summary", "info", "stateset",
    )

    def record_metadata(self, name: bytes, type_code: int) -> None:
        t = self._PROM_TYPES[type_code] if 0 <= type_code < len(self._PROM_TYPES) \
            else "unknown"
        self.metadata[bytes(name)] = t

    async def open(self) -> None:
        async for batch in self._storage.scan(
            ScanRequest(range=TimeRange(-(2**62), 2**62))
        ):
            names = batch.column("metric_name").to_pylist()
            mids = batch.column("metric_id").to_pylist()
            fids = batch.column("field_id").to_pylist()
            for n, m, f in zip(names, mids, fids):
                self._cache[n] = (m, f)
                self._known_ids.add(m)

    def get(self, name: bytes) -> tuple[int, int] | None:
        return self._cache.get(name)

    def names(self) -> list[bytes]:
        """All registered metric names."""
        return sorted(self._cache.keys())

    async def populate_metric_ids(
        self, names: list[bytes], now_ms: int
    ) -> dict[bytes, MetricId]:
        """Resolve (registering if new) ids for a batch of metric names."""
        out: dict[bytes, MetricId] = {}
        new: list[bytes] = []
        for name in names:
            hit = self._cache.get(name)
            if hit is None:
                out[name] = metric_id_of(name)
                new.append(name)
            else:
                out[name] = hit[0]
        if new:
            await self._persist(sorted(set(new)), out, now_ms)
        return out

    def unknown_ids(self, metric_ids) -> "np.ndarray":
        """Unique metric ids not yet registered (hash-lane fast path: the
        ids were already seahashed by the native parser)."""
        # set-difference beats np.unique for the small per-payload id lane
        # (a few hundred values, heavy repeats) on the hot write path
        new = set(np.asarray(metric_ids, dtype=np.uint64).tolist())
        new.difference_update(self._known_ids)
        return np.fromiter(new, dtype=np.uint64, count=len(new))

    async def register_named(self, names: list[bytes], ids: list[int], now_ms: int) -> None:
        """Register metrics whose ids are precomputed (native hash lanes);
        id == seahash(name) is the contract both sides share."""
        fresh = sorted({n for n in names if n not in self._cache})
        if fresh:
            await self._persist(fresh, dict(zip(names, ids)), now_ms)

    async def _persist(self, new_names: list[bytes], ids: dict[bytes, int], now_ms: int) -> None:
        n = len(new_names)
        field_id = 0
        batch = pa.RecordBatch.from_pydict(
            {
                "metric_id": np.asarray([ids[x] for x in new_names], dtype=np.uint64),
                "field_id": np.full(n, field_id, dtype=np.uint64),
                "metric_name": list(new_names),
                "field_name": [DEFAULT_FIELD] * n,
                "field_type": np.full(n, FIELD_TYPE_F64, dtype=np.uint64),
            },
            schema=METRICS_SCHEMA,
        )
        seg_start = now_ms - now_ms % self._segment_duration
        await self._storage.write(
            WriteRequest(batch, TimeRange(seg_start, seg_start + 1), enable_check=True)
        )
        for name in new_names:
            self._cache[name] = (ids[name], field_id)
            self._known_ids.add(ids[name])
