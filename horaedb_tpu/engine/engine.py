"""MetricEngine facade: Prometheus-shaped writes and queries end-to-end.

Ties the three managers over four ColumnarStorage tables (one sub-root each:
{root}/{metrics,series,index,data}). The write path is the RFC pipeline:
populate metric ids -> populate series ids (registering new series + inverted
index entries) -> persist samples; the read path is index probe -> storage
scan with device predicate -> device aggregation.
"""

from __future__ import annotations

import copy
import hashlib
import logging
from dataclasses import dataclass, field

import numpy as np

from horaedb_tpu.common import tracing
from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.time_ext import ReadableDuration, now_ms
from horaedb_tpu.engine import tables
from horaedb_tpu.engine.data import SampleManager
from horaedb_tpu.engine.index import IndexManager
from horaedb_tpu.engine.metric import MetricManager
from horaedb_tpu.ingest.cardinality import CardinalityLimited, SeriesSketch
from horaedb_tpu.ingest.types import ParsedWriteRequest
from horaedb_tpu.objstore import ObjectStore
from horaedb_tpu.server.metrics import GLOBAL_METRICS
from horaedb_tpu.storage.config import ColumnOptions, StorageConfig
from horaedb_tpu.storage.storage import ObjectBasedStorage
from horaedb_tpu.storage.types import TimeRange

logger = logging.getLogger(__name__)

NAME_LABEL = b"__name__"

DEFAULT_SEGMENT_MS = 2 * 3600_000  # 2h data segments

SERIES_CARDINALITY = GLOBAL_METRICS.gauge(
    "horaedb_series_cardinality",
    help="HLL-sketch estimate of distinct (metric, tsid) series this "
         "table has ever ingested (ingest/cardinality.py; seeded from "
         "the index at open). The cardinality-explosion early-warning "
         "signal, and the value the max_series limit compares against.",
    labelnames=("table",),
)
CARD_REJECTED_SAMPLES = GLOBAL_METRICS.counter(
    "horaedb_cardinality_rejected_samples_total",
    help="Samples dropped because their series was NEW while the table "
         "sat at its series-cardinality limit (partial-accept 503s; "
         "existing-series samples in the same request were accepted).",
    labelnames=("table",),
)
CARD_REJECTED_SERIES = GLOBAL_METRICS.counter(
    "horaedb_cardinality_rejected_series_total",
    help="Distinct new-series registrations rejected at the "
         "series-cardinality limit (per request; a series retried across "
         "requests counts each time).",
    labelnames=("table",),
)
CARD_LIMITED_REQUESTS = GLOBAL_METRICS.counter(
    "horaedb_cardinality_limited_requests_total",
    help="Write requests answered with the 503/Retry-After "
         "partial-accept because the series-cardinality limit rejected "
         "at least one new series.",
    labelnames=("table",),
)
TOMBSTONES_CREATED = GLOBAL_METRICS.counter(
    "horaedb_tombstones_created_total",
    help="Tombstone delete records created via the delete API, by table "
         "root (applied at scan time immediately, physically at "
         "compaction; horaedb_tombstones_applied_total tracks rows).",
    labelnames=("table",),
)


def sample_table_config(config: StorageConfig | None) -> StorageConfig:
    """Data/exemplars-table write config with measured encoding defaults.

    The RFC floats a custom compressed sample payload (delta-of-delta
    timestamps + XOR values packed into opaque bytes, RFC :218-232).
    Measured on realistic scrape-shaped data (benchmarks/
    compression_bench.py): parquet's own DELTA_BINARY_PACKED (int lanes)
    + BYTE_STREAM_SPLIT/zstd (values) beats that design — smaller than
    the byte-aligned gorilla variant AND decode stays columnar/vectorized,
    so scans get faster, not slower. These are therefore the sample-table
    defaults; explicit user column_options always win.

    Each default carries enable_dict=False: parquet rejects an explicit
    column_encoding for a dictionary-encoded column, so the tuned columns
    opt out of dictionary mode individually — a user's global
    enable_dict=true still applies to every other column."""
    cfg = copy.deepcopy(config) if config is not None else StorageConfig()
    opts = dict(cfg.write.column_options or {})
    defaults = {
        "metric_id": "DELTA_BINARY_PACKED",
        "tsid": "DELTA_BINARY_PACKED",
        "field_id": "DELTA_BINARY_PACKED",
        "ts": "DELTA_BINARY_PACKED",
        "value": "BYTE_STREAM_SPLIT",
    }
    for name, enc in defaults.items():
        opts.setdefault(name, ColumnOptions(
            enable_dict=False, encoding=enc,
            compression="zstd" if name == "value" else None,
        ))
    cfg.write.column_options = opts
    return cfg


@dataclass
class QueryRequest:
    metric: bytes
    start_ms: int
    end_ms: int
    filters: list[tuple[bytes, bytes]] = field(default_factory=list)
    # Prometheus-style extended matchers: (key, op, pattern) with op in
    # "ne" (!=), "re" (=~ full match), "nre" (!~)
    matchers: list[tuple[bytes, str, bytes]] = field(default_factory=list)
    bucket_ms: int | None = None  # None -> raw rows
    # Raw-row limit PUSHED INTO the scan: segments stop being read once
    # `limit` merged rows have accumulated (segments scan old->new), so a
    # 100M-row table queried with limit=100k pays ~100k rows of work, not
    # full materialization. None = unbounded. Ignored for bucketed queries.
    limit: int | None = None
    # Region restriction for the distributed scatter-gather read path:
    # None = all regions (the single-node behavior); a list restricts
    # `query_partial_grids` to exactly these region shards — each
    # computing node receives its assigned subset here. Ignored by the
    # plain `query` surface (whole queries always see every region).
    regions: "list[int] | None" = None


class MetricEngine:
    def __init__(self) -> None:
        raise RuntimeError("use MetricEngine.open")

    @classmethod
    async def open(
        cls,
        root: str,
        store: ObjectStore,
        segment_duration_ms: int = DEFAULT_SEGMENT_MS,
        config: StorageConfig | None = None,
        enable_compaction: bool = True,
        ingest_buffer_rows: int = 0,
        flush_workers: int = 2,
        flush_queue_max: int = 4,
        flush_stall_deadline_s: float = 30.0,
        sst_executor=None,
        manifest_executor=None,
        parser_pool=None,
        fence_node_id: str | None = None,
        fence_validate_interval_s: float = 5.0,
        retention_period_ms: int | None = None,
        max_series: int = 0,
        serving=None,
        read_only: bool = False,
    ) -> "MetricEngine":
        """`ingest_buffer_rows` > 0 buffers data-table rows across writes
        and flushes as one SST per segment when the threshold is reached
        (see SampleManager.__init__ for the durability trade-off);
        `flush_workers`/`flush_queue_max`/`flush_stall_deadline_s` size the
        background flush executor (engine/flush_executor.py) that decouples
        the append hot path from drain/encode/upload work.
        `sst_executor`/`manifest_executor` size CPU-heavy storage work
        (ThreadConfig, see ObjectBasedStorage.try_new). `parser_pool` shares
        the caller's ParserPool (so e.g. the server's pool telemetry covers
        engine ingest); None = engine creates its own on first use.
        `fence_node_id` claims exclusive write ownership of this engine
        root: ONE epoch fence covers all six tables (the region is the
        ownership unit, RFC :28-76); a later claimant deposes this process
        and its writes fail with FencedError (storage/fence.py).

        `retention_period_ms`: samples older than now - period stop
        existing — row-exact at scan time (storage/visibility.py), and the
        compaction scheduler's TTL expires whole SSTs physically. Applies
        to the data + exemplars tables only (the registration tables hold
        definitions, not samples). None = keep forever.

        `max_series`: per-engine series-cardinality limit enforced by an
        HLL sketch on the ingest path (ingest/cardinality.py): once the
        estimate reaches the limit, NEW series are rejected with a
        503/Retry-After partial-accept while existing-series samples keep
        landing. 0 = unlimited (the sketch still runs and exports
        horaedb_series_cardinality).

        `serving`: ServingTierConfig for the dashboard serving tier
        (horaedb_tpu/serving — compaction-time rollups, the result
        cache, device block residency). None = defaults (ON: the tier
        is bit-exact vs forced-cold scans by construction).

        `read_only`: cluster replica mode (horaedb_tpu/cluster): open a
        read-only VIEW over a root a writer process owns on the shared
        store — no fence, no compaction, no flush pipeline, no sidecar
        dumps; every mutation raises ReplicaReadOnlyError. Queries work
        unchanged with bounded staleness (the replica's watch loop swaps
        in fresh views)."""
        from horaedb_tpu.serving import ServingTier

        self = object.__new__(cls)
        self._read_only = read_only
        if read_only:
            fence_node_id = None
            enable_compaction = False
            ingest_buffer_rows = 0
        self._store = store
        self._segment_duration = segment_duration_ms
        self._pool = parser_pool
        self._table_label = root.strip("/")
        self._max_series = int(max_series)
        self._sketch = SeriesSketch()
        self._card_events = 0
        for fam in (CARD_REJECTED_SAMPLES, CARD_REJECTED_SERIES,
                    CARD_LIMITED_REQUESTS, TOMBSTONES_CREATED):
            fam.labels(self._table_label)
        SERIES_CARDINALITY.labels(self._table_label).set(0)

        fence = None
        if fence_node_id is not None:
            from horaedb_tpu.storage.fence import EpochFence

            fence = await EpochFence.acquire(
                store, root.strip("/"), fence_node_id,
                validate_interval_s=fence_validate_interval_s,
            )
        self._fence = fence

        self.serving = ServingTier(serving)
        sample_cfg = sample_table_config(config)
        # serving tier layer a: compaction-time rollups on the sample
        # tables (emission only ever runs where a compaction scheduler
        # exists — the data table). User storage-config overrides win.
        if not sample_cfg.rollup.enabled:
            sample_cfg.rollup.enabled = (
                self.serving.config.enabled
                and self.serving.config.rollup_enabled
            )
            sample_cfg.rollup.resolutions = list(
                self.serving.config.rollup_resolutions
            )
        if retention_period_ms is not None and retention_period_ms > 0:
            # single source of truth: the compaction scheduler's TTL drives
            # BOTH physical expiry (picker expireds + the expired-only task)
            # and scan-time retention masking (storage.retention_floor_ms).
            # Sample-bearing tables only — retention must never expire
            # metric/series/index/tags registrations.
            sample_cfg.scheduler.ttl = ReadableDuration.millis(
                int(retention_period_ms)
            )

        async def open_table(name, schema, num_pks, compaction):
            sample_table = name in ("data", "exemplars")
            return await ObjectBasedStorage.try_new(
                root=f"{root}/{name}",
                store=store,
                arrow_schema=schema,
                num_primary_keys=num_pks,
                segment_duration_ms=segment_duration_ms,
                # sample-bearing tables get the measured encoding defaults
                config=sample_cfg if sample_table else config,
                enable_compaction_scheduler=compaction,
                sst_executor=sst_executor,
                manifest_executor=manifest_executor,
                fence=fence,
                # row-exact retention + time-range tombstone deletes
                # (storage/visibility.py) need the schema's time column
                time_column="ts" if sample_table else None,
                read_only=read_only,
            )

        self.metrics_table = await open_table(
            "metrics", tables.METRICS_SCHEMA, tables.METRICS_NUM_PKS, False
        )
        self.series_table = await open_table(
            "series", tables.SERIES_SCHEMA, tables.SERIES_NUM_PKS, False
        )
        self.index_table = await open_table(
            "index", tables.INDEX_SCHEMA, tables.INDEX_NUM_PKS, False
        )
        self.tags_table = await open_table(
            "tags", tables.TAGS_SCHEMA, tables.TAGS_NUM_PKS, False
        )
        self.data_table = await open_table(
            "data", tables.DATA_SCHEMA, tables.DATA_NUM_PKS, enable_compaction
        )
        self.exemplars_table = await open_table(
            "exemplars", tables.EXEMPLARS_SCHEMA, tables.EXEMPLARS_NUM_PKS, False
        )

        self.metric_mgr = MetricManager(self.metrics_table, segment_duration_ms)
        self.index_mgr = IndexManager(
            self.series_table, self.index_table, segment_duration_ms,
            # base sidecar lives beside the two tables it caches, in a
            # namespace neither table's manifest/data layout touches
            sidecar_store=store,
            sidecar_path=f"{root}/index_sidecar/base.arrow",
            tags_storage=self.tags_table,
            read_only=read_only,
        )
        # Payload-shape fingerprint cache: scrapers resend the same series
        # set every interval, so the (metric_id, tsid) lane BYTES repeat
        # exactly payload-over-payload. A hit proves this exact lane-set was
        # fully registered (entries are added only after durable
        # registration), collapsing steady-state id resolution to one set
        # probe. Keys are 16-byte blake2b digests of the lane bytes — fixed
        # memory (64 KB at the 4096-entry cap) even for 10k-series payloads
        # whose shapes churn, at cryptographic collision resistance.
        self._lanes_fp: set[bytes] = set()
        self.sample_mgr = SampleManager(
            self.data_table, segment_duration_ms,
            buffer_rows=ingest_buffer_rows,
            flush_workers=flush_workers,
            flush_queue_max=flush_queue_max,
            flush_stall_deadline_s=flush_stall_deadline_s,
            serving=self.serving,
        )
        self.exemplar_mgr = SampleManager(
            self.exemplars_table, segment_duration_ms, serving=self.serving,
        )
        await self.metric_mgr.open()
        await self.index_mgr.open()
        # seed the cardinality sketch from the index the open just loaded:
        # the estimate (and the limit) survive restarts without any extra
        # durable state
        mids, tsids = self.index_mgr.series_lanes()
        self._sketch.add_pairs(mids, tsids)
        SERIES_CARDINALITY.labels(self._table_label).set(
            round(self._sketch.estimate())
        )
        return self

    def sub_engines(self) -> "dict[str, MetricEngine]":
        """Uniform enumeration for observability surfaces — one unpartitioned
        engine; RegionedEngine returns one entry per region."""
        return {"": self}

    @property
    def read_only(self) -> bool:
        """True in cluster replica mode (see `open`'s read_only)."""
        return self._read_only

    def manifest_epoch(self) -> int:
        """Monotonic catch-up token over ALL six tables' manifests: the
        replica's view matches the writer's exactly when the epochs are
        equal (cluster/replica.py floors it so the surfaced token never
        moves backwards across GC)."""
        return max(
            t.manifest_epoch()
            for t in (self.metrics_table, self.series_table,
                      self.index_table, self.tags_table,
                      self.data_table, self.exemplars_table)
        )

    def _ensure_writable(self, what: str) -> None:
        if self._read_only:
            from horaedb_tpu.common.error import ReplicaReadOnlyError

            raise ReplicaReadOnlyError(
                f"engine {self._table_label} is a read-only replica view; "
                f"refusing {what} (route the mutation to the owning writer)"
            )

    async def flush(self) -> None:
        """Flush any buffered ingest rows to durable SSTs (waits out any
        in-flight background flush first)."""
        await self.sample_mgr.drain()

    async def close(self) -> None:
        await self.flush()
        # quiesced now: fold the index into its sidecar so the next open
        # replays nothing (best-effort — open rebuilds from the tables if
        # this never lands)
        try:
            await self.index_mgr.dump_sidecar()
        except Exception:  # noqa: BLE001
            logger.warning("index sidecar dump failed; next open will rebuild",
                           exc_info=True)
        for t in (
            self.metrics_table,
            self.series_table,
            self.index_table,
            self.tags_table,
            self.data_table,
            self.exemplars_table,
        ):
            await t.close()

    # -- write path -----------------------------------------------------------
    def metadata(self) -> dict[bytes, str]:
        """Metric-family metadata (family name -> prom type string)."""
        return dict(self.metric_mgr.metadata)

    def _record_metadata(self, req: ParsedWriteRequest) -> None:
        """Fold remote-write METADATA records (family name -> prom type)
        into the advisory metadata cache (served at /api/v1/metadata)."""
        for i in range(len(req.meta_type)):
            self.metric_mgr.record_metadata(
                req.meta_name(i), int(req.meta_type[i])
            )

    async def write_parsed(self, req: ParsedWriteRequest) -> int:
        """Ingest one decoded remote-write request; returns sample count.

        When the native parser supplied metric-id/tsid hash lanes
        (ingest/types.py), id resolution is pure numpy + set probes — no
        per-series label slicing or Python seahash (the reference hash
        contract lives in C++, src/metric_engine/src/types.rs:18-41)."""
        self._ensure_writable("write_parsed")
        if len(req.meta_type):
            self._record_metadata(req)
        if req.n_series == 0:
            return 0
        if req.series_tsid is not None:
            return await self._write_parsed_fast(req)
        ts_now = now_ms()
        # 1. metric names from __name__ labels
        names: list[bytes] = []
        label_sets: list[list[tuple[bytes, bytes]]] = []
        for s in range(req.n_series):
            labels = req.series_labels(s)
            name = b""
            rest = []
            for k, v in labels:
                if k == NAME_LABEL:
                    name = v
                else:
                    rest.append((k, v))
            ensure(bool(name), f"series {s} missing __name__ label")
            names.append(name)
            label_sets.append(rest)
        ids = await self.metric_mgr.populate_metric_ids(names, ts_now)
        metric_per_series = [ids[n] for n in names]
        # 2. cardinality gate (the pure-Python path derives the tsids it
        # needs for the known-series probe — only once the estimate has
        # already crossed the limit, so the hot case pays nothing)
        rejected = None
        if self._max_series and self._sketch.estimate() >= self._max_series:
            from horaedb_tpu.engine.types import series_id_of, series_key_of

            pred_tsids = np.fromiter(
                (series_id_of(series_key_of(ls)) for ls in label_sets),
                dtype=np.uint64, count=len(label_sets),
            )
            marr = np.asarray(metric_per_series, dtype=np.uint64)
            known = self.index_mgr.known_pairs_mask(marr, pred_tsids)
            if not bool(known.all()):
                rejected = ~known
        # series registration + tsids (accepted series only under the gate)
        if rejected is None:
            tsids = np.asarray(await self.index_mgr.populate_series_ids(
                metric_per_series, label_sets, ts_now
            ), dtype=np.uint64)
        else:
            acc = np.flatnonzero(~rejected)
            acc_list = acc.tolist()
            acc_tsids = await self.index_mgr.populate_series_ids(
                [metric_per_series[i] for i in acc_list],
                [label_sets[i] for i in acc_list], ts_now,
            )
            tsids = np.zeros(req.n_series, dtype=np.uint64)
            tsids[acc] = np.asarray(acc_tsids, dtype=np.uint64)
        # 3. samples -> data rows
        n = req.n_samples
        metric_arr = np.asarray(metric_per_series, dtype=np.uint64)
        tsid_arr = tsids
        self._feed_sketch(
            metric_arr if rejected is None else metric_arr[~rejected],
            tsid_arr if rejected is None else tsid_arr[~rejected],
        )
        card_accept = card_reject = 0
        if n:
            series_idx = req.sample_series
            if rejected is not None:
                keep = ~rejected[series_idx]
                card_accept = int(np.count_nonzero(keep))
                card_reject = n - card_accept
                sel = np.flatnonzero(keep)
                series_idx = series_idx[sel]
                if card_accept:
                    await self.sample_mgr.persist(
                        metric_arr[series_idx], tsid_arr[series_idx],
                        req.sample_ts[sel], req.sample_value[sel],
                    )
            else:
                await self.sample_mgr.persist(
                    metric_arr[series_idx], tsid_arr[series_idx],
                    req.sample_ts, req.sample_value,
                )
        # 4. exemplars -> exemplars table (with their labels: trace ids are
        # the entire point of exemplars)
        if len(req.exemplar_value):
            await self._persist_exemplars(
                req, metric_arr, tsid_arr,
                keep_series=None if rejected is None else ~rejected,
            )
        if rejected is not None:
            self._raise_cardinality(
                int(np.count_nonzero(rejected)), card_reject, card_accept
            )
        return n

    def _cardinality_gate(self, metric_arr, tsid_arr) -> "np.ndarray | None":
        """Per-series rejection mask when the table sits at its series
        limit, else None. Cheap until the limit is actually reached (one
        cached-estimate compare); only then does it pay the per-pair
        known-series probes to tell existing traffic from the explosion."""
        if not self._max_series:
            return None
        if self._sketch.estimate() < self._max_series:
            return None
        known = self.index_mgr.known_pairs_mask(metric_arr, tsid_arr)
        if known.all():
            return None
        return ~known

    def _feed_sketch(self, metric_arr, tsid_arr) -> None:
        if self._sketch.add_pairs(metric_arr, tsid_arr):
            SERIES_CARDINALITY.labels(self._table_label).set(
                round(self._sketch.estimate())
            )

    def _raise_cardinality(
        self, rejected_series: int, rejected_samples: int,
        accepted_samples: int,
    ) -> None:
        """Count + sampled-log one partial-accept, then raise the typed
        overload signal (503/Retry-After at the HTTP layer). Raised AFTER
        the accepted samples were persisted/buffered — the ack contract
        for in-budget traffic is unchanged."""
        t = self._table_label
        CARD_REJECTED_SERIES.labels(t).inc(rejected_series)
        CARD_REJECTED_SAMPLES.labels(t).inc(rejected_samples)
        CARD_LIMITED_REQUESTS.labels(t).inc()
        self._card_events += 1
        if self._card_events == 1 or self._card_events % 100 == 0:
            logger.warning(
                "series cardinality limit on %s: rejected %d new series "
                "(%d samples), accepted %d samples (event %d, est ~%.0f, "
                "limit %d)",
                t, rejected_series, rejected_samples, accepted_samples,
                self._card_events, self._sketch.estimate(), self._max_series,
            )
        raise CardinalityLimited(
            table=t, limit=self._max_series,
            estimate=self._sketch.estimate(),
            accepted_samples=accepted_samples,
            rejected_samples=rejected_samples,
            rejected_series=rejected_series,
        )

    async def _resolve_ids_fast(self, req: ParsedWriteRequest):
        """Hash-lane id resolution: validate names, register unseen metrics
        and series. Returns (metric_arr, tsid_arr, rejected) — u64 lanes
        per series plus the cardinality-limit rejection mask (None in the
        overwhelmingly common in-budget case; True entries are NEW series
        that were NOT registered and whose samples the caller must drop
        and account via _raise_cardinality)."""
        ts_now = now_ms()
        name_len = req.series_name_len
        if np.any(name_len < 0):
            s = int(np.argmax(name_len < 0))
            ensure(False, f"series {s} missing __name__ label")
        metric_arr = req.series_metric_id
        tsid_arr = req.series_tsid
        # steady-state fast path: the exact lane bytes were seen (and their
        # series durably registered) before — one set probe, no per-series
        # Python work (registered series are by definition in-budget)
        h = hashlib.blake2b(metric_arr.tobytes(), digest_size=16)
        h.update(tsid_arr.tobytes())
        fp = h.digest()
        if fp in self._lanes_fp:
            return metric_arr, tsid_arr, None
        # 0. cardinality gate BEFORE any registration: at the limit, new
        # series must not bloat the metrics/series/index tables either
        rejected = self._cardinality_gate(metric_arr, tsid_arr)
        acc = None if rejected is None else np.flatnonzero(~rejected)
        m_acc = metric_arr if acc is None else metric_arr[acc]
        t_acc = tsid_arr if acc is None else tsid_arr[acc]
        # 1. register unseen metrics (rare after warmup), accepted series only
        new_ids = self.metric_mgr.unknown_ids(m_acc)
        if len(new_ids):
            new_set = set(new_ids.tolist())
            seen: dict[int, bytes] = {}
            series_iter = range(req.n_series) if acc is None else acc.tolist()
            for s in series_iter:
                m = int(metric_arr[s])
                if m in new_set and m not in seen:
                    seen[m] = req.series_name(s)
            ensure(all(seen.values()), "series missing __name__ label")
            await self.metric_mgr.register_named(
                list(seen.values()), list(seen.keys()), ts_now
            )
        # 2. register unseen series (accepted only; index accessors take
        # positions into the subset, so remap through `acc`)
        if acc is None:
            await self.index_mgr.ensure_series_fast(
                metric_arr, tsid_arr, req.series_key, ts_now,
                tag_rows_of=req.series_tag_rows,
            )
        else:
            idx = acc.tolist()
            await self.index_mgr.ensure_series_fast(
                m_acc, t_acc,
                (lambda i: req.series_key(idx[i])), ts_now,
                tag_rows_of=(lambda i: req.series_tag_rows(idx[i])),
            )
        self._feed_sketch(m_acc, t_acc)
        if rejected is not None:
            # a partially-accepted shape is NOT fully registered: never
            # fingerprint it, or a later in-budget retry would skip
            # registration of the still-missing series
            return metric_arr, tsid_arr, rejected
        # everything in these lanes is now durably registered — remember
        # the shape (bounded: scrape fleets send a few distinct shapes)
        if len(self._lanes_fp) >= 4096:
            self._lanes_fp.clear()
        self._lanes_fp.add(fp)
        return metric_arr, tsid_arr, None

    async def write_payload(self, payload: bytes) -> int:
        """Parse + ingest one wire payload end-to-end. With native buffering
        active (ingest_buffer_rows > 0 and the C++ library available),
        samples move straight from the parser arena into the C++
        accumulator — no Python-side sample materialization at all.

        Borrow discipline: the pool slot is held only for the arena-touching
        steps (parse, id resolution, accum add). Steady-state resolution has
        no awaits; only new-series registration persists while borrowed
        (series keys/names must come from the arena, and they are
        materialized to owned bytes before the await). Exemplar persistence
        and threshold flushes use owned copies and run after release."""
        import asyncio

        self._ensure_writable("write_payload")

        from horaedb_tpu.ingest import ParserPool

        from horaedb_tpu.ingest.pooled_parser import PARSE_SECONDS

        if self._pool is None:
            self._pool = ParserPool()
        if not self.sample_mgr.native_accum_active:
            parsed = await self._pool.decode(payload)
            with tracing.span("append", samples=parsed.n_samples):
                return await self.write_parsed(parsed)
        from horaedb_tpu.ingest.native import NativeParser

        total = 0
        async with self._pool.borrow() as parser:
            if not isinstance(parser, NativeParser):
                with tracing.span("parse", bytes=len(payload)), \
                        PARSE_SECONDS.time():
                    parsed = await asyncio.to_thread(parser.parse, payload)
                with tracing.span("append", samples=parsed.n_samples):
                    return await self.write_parsed(parsed)
            # small payloads parse inline: the native parse runs ~1 GB/s, so
            # a sub-256KB payload blocks the loop far less than a thread
            # handoff costs (~100us)
            with tracing.span("parse", bytes=len(payload)), \
                    PARSE_SECONDS.time():
                if len(payload) <= 256 * 1024:
                    req = parser.parse_light(payload)
                else:
                    req = await asyncio.to_thread(parser.parse_light, payload)
            if len(req.meta_type):
                self._record_metadata(req)
            if req.n_series == 0:
                return 0
            rejected = None
            card_accept = card_reject = 0
            with tracing.span("append", samples=req.n_samples):
                metric_arr, tsid_arr, rejected = \
                    await self._resolve_ids_fast(req)
                if len(req.exemplar_value) or rejected is not None:
                    # the id lanes may be views into the borrowed parser's
                    # decode arena (pooled_parser.DecodeArena) — exemplar
                    # persistence (and the rejection raise below) runs
                    # after release, so own them first
                    metric_arr = np.array(metric_arr)
                    tsid_arr = np.array(tsid_arr)
                if req.n_samples and rejected is None:
                    total = self.sample_mgr.buffer_native_add(parser)
                elif req.n_samples:
                    # cardinality-limit degradation: the all-or-nothing C++
                    # accumulator can't take a subset, so this (rare,
                    # already-throttled) payload materializes its sample
                    # lanes and buffers only existing-series samples —
                    # in-budget traffic is never lost
                    vals, ts, series = parser.sample_lanes()
                    keep = ~rejected[series]
                    card_accept = int(np.count_nonzero(keep))
                    card_reject = len(series) - card_accept
                    if card_accept:
                        sel = np.flatnonzero(keep)
                        s_idx = series[sel]
                        # persist() runs its own threshold seal, so the
                        # post-borrow should_flush below stays untriggered
                        # (total stays 0) — a near-empty active memtable
                        # must not seal into a tiny SST just because the
                        # flush executor already holds pending rows
                        await self.sample_mgr.persist(
                            metric_arr[s_idx], tsid_arr[s_idx],
                            ts[sel], vals[sel],
                        )
        if len(req.exemplar_value):
            await self._persist_exemplars(
                req, metric_arr, tsid_arr,
                keep_series=None if rejected is None else ~rejected,
            )
        if rejected is not None:
            self._raise_cardinality(
                int(np.count_nonzero(rejected)), card_reject, card_accept
            )
        if total and self.sample_mgr.should_flush(total):
            # hand the sealed memtable to the background flush executor:
            # drain/encode/upload overlap continued ingest, and a FULL
            # flush queue blocks here with a stall deadline (backpressure
            # -> 5xx -> sender retries) instead of acking rows into an
            # unbounded buffer
            await self.sample_mgr.seal_and_submit()
        if self.sample_mgr.flush_in_flight:
            # cooperative yield: the steady write path never suspends, so a
            # driver hammering write_payload back-to-back would starve the
            # flush workers; one loop turn per payload lets their
            # thread-offload completions schedule (a real server yields at
            # socket reads)
            await asyncio.sleep(0)
        return req.n_samples

    async def _write_parsed_fast(self, req: ParsedWriteRequest) -> int:
        """Hash-lane write path: per-series ids come from the C++ parser."""
        metric_arr, tsid_arr, rejected = await self._resolve_ids_fast(req)
        # 3. samples
        n = req.n_samples
        card_accept = card_reject = 0
        if n:
            if rejected is not None:
                # partial accept at the cardinality limit: only
                # existing-series samples are buffered/persisted
                series_idx = req.sample_series
                keep = ~rejected[series_idx]
                card_accept = int(np.count_nonzero(keep))
                card_reject = n - card_accept
                if card_accept:
                    sel = np.flatnonzero(keep)
                    s_idx = series_idx[sel]
                    await self.sample_mgr.persist(
                        metric_arr[s_idx], tsid_arr[s_idx],
                        req.sample_ts[sel], req.sample_value[sel],
                    )
            elif self.sample_mgr.buffering:
                await self.sample_mgr.buffer_request(metric_arr, tsid_arr, req)
            else:
                series_idx = req.sample_series
                await self.sample_mgr.persist(
                    metric_arr[series_idx], tsid_arr[series_idx],
                    req.sample_ts, req.sample_value,
                )
        if len(req.exemplar_value):
            await self._persist_exemplars(
                req, metric_arr, tsid_arr,
                keep_series=None if rejected is None else ~rejected,
            )
        if rejected is not None:
            self._raise_cardinality(
                int(np.count_nonzero(rejected)), card_reject, card_accept
            )
        return n

    async def _persist_exemplars(
        self, req: ParsedWriteRequest, metric_arr, tsid_arr,
        keep_series: "np.ndarray | None" = None,
    ) -> None:
        import pyarrow as pa

        from horaedb_tpu.engine.types import series_key_of
        from horaedb_tpu.storage.read import WriteRequest as StorageWrite

        ex_idx = req.exemplar_series
        ts = req.exemplar_ts
        vals = req.exemplar_value
        ex_pos = np.arange(len(vals))
        if keep_series is not None:
            # cardinality partial-accept: exemplars of rejected series drop
            # with their samples
            sel = np.flatnonzero(keep_series[ex_idx])
            if not len(sel):
                return
            ex_idx = ex_idx[sel]
            ts = ts[sel]
            vals = vals[sel]
            ex_pos = sel
        m = metric_arr[ex_idx]
        t = tsid_arr[ex_idx]
        labels = [
            series_key_of(req.exemplar_labels(int(i))) for i in ex_pos
        ]
        seg = ts - (ts % self._segment_duration)
        for seg_start in np.unique(seg):
            msk = seg == seg_start
            idxs = np.nonzero(msk)[0]
            batch = pa.RecordBatch.from_pydict(
                {
                    "metric_id": m[msk].astype(np.uint64),
                    "tsid": t[msk].astype(np.uint64),
                    "ts": ts[msk],
                    "value": vals[msk],
                    "labels": [labels[i] for i in idxs],
                },
                schema=tables.EXEMPLARS_SCHEMA,
            )
            lo, hi = int(ts[msk].min()), int(ts[msk].max()) + 1
            await self.exemplars_table.write(StorageWrite(batch, TimeRange(lo, hi)))

    # -- query path -------------------------------------------------------------
    def _resolve_query(
        self, metric: bytes, filters, matchers=None
    ) -> tuple[int, list | None] | None:
        """Shared lookup prologue: metric id + TSID candidates, or None when
        the metric is unknown / no series matches the filters."""
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return None
        tsids = self.index_mgr.find_tsids(hit[0], filters, matchers)
        if tsids == []:
            return None
        return hit[0], tsids

    async def _resolve_query_async(self, req: QueryRequest):
        """Regex matchers evaluate in a worker thread: Python re has no
        linear-time guarantee and must not stall the event loop."""
        import asyncio

        if req.matchers:
            return await asyncio.to_thread(
                self._resolve_query, req.metric, req.filters, req.matchers
            )
        return self._resolve_query(req.metric, req.filters, req.matchers)

    async def query(self, req: QueryRequest):
        """Raw rows (bucket_ms None) or downsample grids per series."""
        from horaedb_tpu.common import deadline as deadline_ctx

        # cooperative end-to-end deadline (common/deadline.py): a query
        # whose budget is already spent must not pay resolution + scan
        deadline_ctx.check("query_resolve")
        resolved = await self._resolve_query_async(req)
        if resolved is None:
            return None
        metric_id, tsids = resolved
        rng = TimeRange(req.start_ms, req.end_ms)
        if req.bucket_ms is None:
            return await self.sample_mgr.query_raw(
                metric_id, tsids, rng, limit=req.limit
            )
        filtered = tsids is not None
        if tsids is None:  # no tag filter: all series of the metric
            tsids = self.index_mgr.series_of(metric_id)
        return await self.sample_mgr.query_downsample(
            metric_id, tsids, rng, req.bucket_ms, filtered=filtered
        )

    async def query_partial_grids(self, req: QueryRequest):
        """Distributed scatter-gather leaf: per-region partial grids as
        [(region_id, tsids, grids)]. A plain (un-regioned) engine is one
        region — id 0 — and answers only when the restriction includes
        it. Runs the NORMAL downsample query path (serving cache,
        rollups, encoding, admission on the serving node all apply); the
        coordinator folds fragments with cluster/partial.merge_partials
        in canonical region order so the distributed result is
        bit-exact vs single-node."""
        from horaedb_tpu.common.error import ensure

        ensure(req.bucket_ms is not None,
               "query_partial_grids requires a bucketed (grid) query")
        if req.regions is not None and 0 not in [int(r) for r in req.regions]:
            return []
        out = await self.query(req)
        if out is None:
            return []
        tsids, grids = out
        return [(0, tsids, grids)]

    async def query_exemplars(self, req: QueryRequest):
        """Raw exemplar rows (incl. their labels) for a metric."""
        resolved = await self._resolve_query_async(req)
        if resolved is None:
            return None
        metric_id, tsids = resolved
        return await self.exemplar_mgr.query_raw(
            metric_id, tsids, TimeRange(req.start_ms, req.end_ms), limit=req.limit
        )

    def label_values(self, metric: bytes, key: bytes) -> list[bytes]:
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return []
        return self.index_mgr.label_values(hit[0], key)

    async def label_values_storage(self, metric: bytes, key: bytes) -> list[bytes]:
        """LabelValues from the durable tags table (RFC :118-130) — agrees
        with `label_values` (tested); see IndexManager.label_values_storage
        for when to prefer which."""
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return []
        return await self.index_mgr.label_values_storage(hit[0], key)

    def metric_names(self) -> list[bytes]:
        """All registered metric names (the /api/v1/metrics surface)."""
        return self.metric_mgr.names()

    def series_count(self, metric: bytes) -> int:
        """Registered series of a metric (in-memory index lookup, no IO).
        The admission scheduler's cost model sizes grid queries with
        this (server/admission.py); 0 for unknown metrics."""
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return 0
        return len(self.index_mgr.series_of(hit[0]))

    def label_names(self) -> list[bytes]:
        """All label KEYS across every registered series (the
        /api/v1/labels no-match[] surface; `__name__` is the endpoint's
        concern). Public like `metric_names` so regioned deployments can
        answer via fan-out instead of reaching into the managers."""
        names: set[bytes] = set()
        for metric in self.metric_mgr.names():
            hit = self.metric_mgr.get(metric)
            if hit is None:
                continue
            for labs in self.index_mgr.series_labels(hit[0]).values():
                names.update(labs)
        return sorted(names)

    def series(self, metric: bytes) -> list[dict[str, str]]:
        """Label sets of every series of a metric (the /api/v1/series
        surface), including tagless series."""
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return []
        per_tsid = self.index_mgr.series_labels(hit[0])
        return [
            {k.decode(errors="replace"): v.decode(errors="replace")
             for k, v in labels.items()} | {"__tsid__": str(t)}
            for t, labels in sorted(per_tsid.items())
        ]

    def series_labels_map(
        self, metric: bytes, tsids: "list[int] | None" = None
    ) -> dict[int, dict[bytes, bytes]]:
        """tsid -> raw label map for a metric, optionally restricted to
        `tsids` (so a selective query never decodes the whole metric's
        series). PromQL/discovery surface — implemented by RegionedEngine
        too (fan-out union)."""
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return {}
        per_tsid = self.index_mgr.series_labels(hit[0])
        if tsids is None:
            return per_tsid
        return {t: per_tsid[t] for t in tsids if t in per_tsid}

    async def match_series(
        self, metric: bytes, filters, matchers
    ) -> dict[int, dict[bytes, bytes]]:
        """Matched tsid -> label map (Prometheus match[] resolution). Regex
        matchers evaluate off the event loop — same safeguard as queries
        (_resolve_query_async): Python `re` has no linear-time guarantee."""
        resolved = await self._resolve_query_async(
            QueryRequest(metric=metric, start_ms=0, end_ms=1,
                         filters=filters, matchers=matchers)
        )
        if resolved is None:
            return {}
        metric_id, tsids = resolved
        per_tsid = self.index_mgr.series_labels(metric_id)
        if tsids is None:
            return per_tsid
        return {t: per_tsid[t] for t in tsids if t in per_tsid}

    async def compact(self, time_range=None) -> None:
        """Manual compaction trigger on the data table (the /compact hook).
        `time_range` scopes the pick (and its follow-on picks) to SSTs
        overlapping that window; None compacts globally."""
        from horaedb_tpu.storage.read import CompactRequest

        self._ensure_writable("compact")
        await self.data_table.compact(CompactRequest(time_range=time_range))

    # -- deletes ---------------------------------------------------------------
    async def delete_series(
        self,
        metric: bytes,
        filters=None,
        matchers=None,
        start_ms: int = 0,
        end_ms: "int | None" = None,
    ) -> dict:
        """Tombstone delete: series of `metric` matching `filters`/
        `matchers`, samples in [start_ms, end_ms). The delete is visible
        to scans IMMEDIATELY (storage/visibility.py masks at read time)
        and physically applied when compaction rewrites the SSTs; rows
        written AFTER this call survive (re-ingest works). Exemplars of
        the matched series in the range are deleted too.

        `end_ms=None` (the "all time" form) caps at NOW rather than
        infinity: rows written after this call survive by sequence
        anyway, so an unbounded range would only buy coverage of
        already-written future-dated samples — while making the
        tombstone un-GC-able forever (it would overlap every live SST
        for the rest of the table's life). Pass an explicit end_ms to
        delete pre-written future-dated data.

        Flushes first, so every previously-ACKED sample carries a write
        sequence below the tombstone's and is therefore covered — the
        delete-then-crash-then-replay case cannot resurrect data."""
        from horaedb_tpu.storage.visibility import build_series_matchers

        self._ensure_writable("delete_series")

        if end_ms is None:
            end_ms = now_ms() + 1
        resolved = await self._resolve_query_async(QueryRequest(
            metric=metric, start_ms=start_ms, end_ms=end_ms,
            filters=list(filters or []), matchers=list(matchers or []),
        ))
        if resolved is None:
            return {"matched_series": 0, "tombstones": 0}
        metric_id, tsids = resolved
        # acked-but-buffered rows must be sealed (seq pinned) before the
        # tombstone's seq is allocated
        await self.flush()
        rng = TimeRange(start_ms, end_ms)
        mats = build_series_matchers(metric_id, tsids)
        tombs = [await self.data_table.delete_rows(rng, mats)]
        tombs.append(await self.exemplars_table.delete_rows(rng, mats))
        TOMBSTONES_CREATED.labels(self._table_label).inc(len(tombs))
        matched = (
            len(tsids) if tsids is not None
            else len(self.index_mgr.series_of(metric_id))
        )
        return {
            "matched_series": matched,
            "tombstones": len(tombs),
            "tombstone_ids": [t.id for t in tombs],
            "start_ms": start_ms,
            "end_ms": end_ms,
        }
